"""nnlint — project-specific static analysis (docs/static_analysis.md).

    python -m nnstreamer_tpu lint            # human output, exit 0/1
    python -m nnstreamer_tpu lint --json     # machine output
    python tools/nnlint.py                   # same, direct entry

`lint_report(paths)` is the in-process API (bench.py's env snapshot and
the tier-1 gate test use it).  The analysis core is stdlib-only — this
package never imports jax or the code it scans; `contract` (runtime
introspection for docs) is imported lazily for the same reason.
"""

from __future__ import annotations

from nnstreamer_tpu.analysis.core import (
    SCHEMA_VERSION, Finding, Module, Project, Report, Rule,
    build_project, load_baseline, project_from_sources, run_rules,
    write_baseline)
from nnstreamer_tpu.analysis.rules import ALL_RULES, iter_rules

__all__ = [
    "SCHEMA_VERSION", "Finding", "Module", "Project", "Report", "Rule",
    "ALL_RULES", "build_project", "element_contract", "iter_rules",
    "lint_report", "load_baseline", "project_from_sources", "run_rules",
    "write_baseline",
]


def lint_report(paths=("nnstreamer_tpu",), root=None,
                baseline_path=None, rules=None) -> Report:
    """One-call lint: build the project, run the rules, apply the
    baseline.  `Report.clean` is the gate bit."""
    project = build_project(paths, root=root)
    baseline = load_baseline(baseline_path) if baseline_path else []
    return run_rules(project, iter_rules(rules), baseline)


def element_contract(cls):
    """Lazy re-export (contract.py imports the graph layer)."""
    from nnstreamer_tpu.analysis.contract import element_contract as ec
    return ec(cls)
