"""nnlint core — rule runner, suppressions, baseline, output.

The runtime's correctness rests on conventions the compiler never
checks (docs/static_analysis.md): timer elements must implement the
`next_deadline`/`on_timer` pair, contract flags must match element
shape, every host sync must route through `runtime/sync.device_sync`,
no thread may block while holding a lock, jitted functions must stay
pure, spawn-imported modules must not touch the device at import, and
error classes must pickle across the worker pool. The reference
framework inherits these guarantees from GStreamer's core; our
substrate is homegrown threads + JAX, so each convention is one
refactor away from a silent race. This package encodes them as AST
rules so the gap shows up in review, not in production.

Mechanics (mirrors the reference's Coverity gate, SURVEY.md §5.2, but
project-specific):

- **Rules** subclass :class:`Rule` and yield ``(node, message)`` pairs
  from ``check(module, project)``; cross-module rules read the whole
  :class:`Project` index (jit-purity follows imports, spawn-safety
  walks the worker's import closure).
- **Suppressions**: ``# nnlint: disable=NNL003`` (comma list, or
  ``all``) on the finding's line waives it — for *deliberate*
  exceptions, each with a one-line justification in the same comment.
- **Baseline**: a committed JSON of finding fingerprints grandfathers
  pre-existing debt so the gate can be red-line-only. The repo's
  baseline (`nnlint_baseline.json`) is empty and the tier-1 gate test
  keeps it that way: new findings are fixed or inline-suppressed,
  never baselined.

Dependency-free (stdlib ast only) so the gate runs anywhere the code
parses — no jax import, no package import of the code under analysis.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: suppression comment grammar: `# nnlint: disable=NNL001[,NNL002]` or
#: `# nnlint: disable=all`; anything after the rule list is the
#: human justification and is ignored by the parser
_DISABLE_RE = re.compile(r"#\s*nnlint:\s*disable=([A-Za-z0-9_,]+|all)")

#: JSON report schema version (tests pin it)
SCHEMA_VERSION = 1


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str              # posix-relative path as scanned
    line: int
    col: int
    message: str
    suppressed: bool = False

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for baseline matching: a finding
        keeps its fingerprint when unrelated edits shift it down the
        file, and changes it when the offending code itself changes."""
        blob = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "fingerprint": self.fingerprint,
                "suppressed": self.suppressed}

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclass
class Module:
    """One parsed source file plus the per-line suppression table."""

    path: str                        # posix relative path
    src: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.src.splitlines()

    def disabled_rules(self, lineno: int) -> set:
        if not 1 <= lineno <= len(self.lines):
            return set()
        m = _DISABLE_RE.search(self.lines[lineno - 1])
        if not m:
            return set()
        spec = m.group(1)
        if spec == "all":
            return {"all"}
        return {r.strip().upper() for r in spec.split(",") if r.strip()}


class Project:
    """Index of every scanned module, for cross-module rules.

    Keyed by posix relative path; `by_dotted` resolves a package module
    name (``nnstreamer_tpu.runtime.sync``) back to its scanned file, so
    the jit-purity rule can follow ``from X import f`` and the
    spawn-safety rule can walk the worker's import closure without
    importing anything.
    """

    def __init__(self, modules: Dict[str, Module]):
        self.modules = modules
        self._dotted: Dict[str, str] = {}
        for path in modules:
            p = path[:-3] if path.endswith(".py") else path
            if p.endswith("/__init__"):
                p = p[: -len("/__init__")]
            self._dotted[p.replace("/", ".")] = path

    def by_dotted(self, dotted: str) -> Optional[Module]:
        path = self._dotted.get(dotted)
        return self.modules.get(path) if path else None

    def __iter__(self) -> Iterator[Module]:
        return iter(self.modules.values())


class Rule:
    """One lint rule. Subclasses set `rule_id`/`title`/`rationale` and
    implement `check()` yielding ``(node_or_lineno, message)``."""

    rule_id: str = "NNL000"
    title: str = ""
    rationale: str = ""

    def check(self, module: Module,
              project: Project) -> Iterable[Tuple[object, str]]:
        raise NotImplementedError

    def run(self, module: Module, project: Project) -> List[Finding]:
        out = []
        for node, msg in self.check(module, project):
            line = getattr(node, "lineno", node if isinstance(node, int) else 0)
            col = getattr(node, "col_offset", 0)
            disabled = module.disabled_rules(line)
            suppressed = "all" in disabled or self.rule_id in disabled
            out.append(Finding(self.rule_id, module.path, line, col,
                               msg, suppressed=suppressed))
        return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path) -> List[str]:
    """Fingerprint multiset from a baseline file; [] when absent."""
    p = Path(path)
    if not p.exists():
        return []
    data = json.loads(p.read_text() or "{}")
    return list(data.get("findings", []))


def write_baseline(path, findings: Iterable[Finding]) -> int:
    fps = sorted(f.fingerprint for f in findings if not f.suppressed)
    Path(path).write_text(json.dumps(
        {"version": SCHEMA_VERSION, "findings": fps}, indent=2) + "\n")
    return len(fps)


def apply_baseline(findings: List[Finding],
                   baseline: List[str]) -> Tuple[List[Finding], int]:
    """Split findings against the baseline multiset: returns
    (unbaselined findings, number grandfathered). Duplicate
    fingerprints consume one baseline entry each."""
    budget: Dict[str, int] = {}
    for fp in baseline:
        budget[fp] = budget.get(fp, 0) + 1
    fresh: List[Finding] = []
    matched = 0
    for f in findings:
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
            matched += 1
        else:
            fresh.append(f)
    return fresh, matched


# -- running -----------------------------------------------------------------

def _iter_py_files(paths: Iterable[str], root: Path) -> Iterator[Path]:
    for p in paths:
        pp = Path(p)
        if not pp.is_absolute():
            pp = root / pp
        if pp.is_dir():
            yield from sorted(pp.rglob("*.py"))
        elif pp.suffix == ".py":
            yield pp


def build_project(paths: Iterable[str], root=None) -> Project:
    """Parse every .py under `paths` (files or dirs) into a Project.
    Generated protobuf modules and caches are skipped; a file that does
    not parse becomes a synthetic parse-error module handled by the
    runner (syntax gate)."""
    root = Path(root or ".").resolve()
    modules: Dict[str, Module] = {}
    for f in _iter_py_files(paths, root):
        if "_pb2" in f.name or "__pycache__" in f.parts:
            continue
        try:
            rel = f.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        src = f.read_text()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            tree = ast.Module(body=[], type_ignores=[])
            mod = Module(rel, src, tree)
            mod.parse_error = (e.lineno or 0, e.msg)  # type: ignore
            modules[rel] = mod
            continue
        modules[rel] = Module(rel, src, tree)
    return Project(modules)


def project_from_sources(sources: Dict[str, str]) -> Project:
    """In-memory project for tests/fixtures: {relpath: source}."""
    modules = {}
    for rel, src in sources.items():
        modules[rel] = Module(rel, src, ast.parse(src, filename=rel))
    return Project(modules)


@dataclass
class Report:
    """Outcome of one lint run over a project."""

    findings: List[Finding]          # unbaselined, unsuppressed
    suppressed: List[Finding]
    baselined: int
    files: int
    rules: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "version": SCHEMA_VERSION,
            "clean": self.clean,
            "files": self.files,
            "rules": self.rules,
            "counts": counts,
            "baselined": self.baselined,
            "suppressed": [f.to_json() for f in self.suppressed],
            "findings": [f.to_json() for f in self.findings],
        }


def run_rules(project: Project, rules: Iterable[Rule],
              baseline: Optional[List[str]] = None) -> Report:
    rules = list(rules)
    all_findings: List[Finding] = []
    for module in project:
        err = getattr(module, "parse_error", None)
        if err is not None:
            all_findings.append(Finding(
                "NNL000", module.path, err[0], 0,
                f"syntax error: {err[1]}"))
            continue
        for rule in rules:
            all_findings.extend(rule.run(module, project))
    all_findings.sort(key=lambda f: (f.path, f.line, f.rule))
    active = [f for f in all_findings if not f.suppressed]
    suppressed = [f for f in all_findings if f.suppressed]
    fresh, matched = apply_baseline(active, baseline or [])
    return Report(findings=fresh, suppressed=suppressed,
                  baselined=matched, files=len(project.modules),
                  rules=[r.rule_id for r in rules])


# -- AST helpers shared by rules --------------------------------------------

def dotted(node: ast.AST) -> str:
    """Dotted name of an expression: `jax.block_until_ready` →
    "jax.block_until_ready"; non-name parts render as empty heads
    (``x[0].get`` → ".get") so suffix checks still work."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


def const_value(node: ast.AST):
    """Literal value of a class-body assignment RHS, with the graph
    module's DYNAMIC marker folded to its value (-1). Returns None for
    anything non-literal."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name) and node.id == "DYNAMIC":
        return -1
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
            and isinstance(node.operand, ast.Constant):
        return -node.operand.value
    return None


def walk_no_functions(stmts) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    bodies — the lock-discipline walker (code in a nested def does not
    run under the enclosing `with`)."""
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    stack = [s for s in stmts if not isinstance(s, skip)]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(c for c in ast.iter_child_nodes(node)
                     if not isinstance(c, skip))
