"""Runtime element-contract introspection.

The single source of truth the NNL001 rule checks *statically* and the
docs render *dynamically*: for a registered Element class, which
contract flags does it actually carry? `tools/gen_docs.py` uses this to
print the flags column in docs/elements.md, and tests cross-check it
against the scheduler's own `_chain_eligible` logic so the lint rule,
the docs, and the runtime can never disagree about what a class
declares.

This module imports the graph layer (it introspects live classes) —
keep it OUT of the linter's import path; `analysis.core`/`analysis
.rules` stay stdlib-only.
"""

from __future__ import annotations

from typing import Dict


def element_contract(cls) -> Dict[str, object]:
    """Declared contract flags for an Element class.

    ``timer`` mirrors the scheduler's check (scheduler.py
    `_chain_eligible`): an element is a timer element iff it overrides
    `next_deadline` or `on_timer` relative to the Element base.
    """
    from nnstreamer_tpu.graph.pipeline import DYNAMIC, Element

    has_timer = (cls.next_deadline is not Element.next_deadline
                 or cls.on_timer is not Element.on_timer)

    def _pads(v) -> str:
        return "dynamic" if v == DYNAMIC else str(v)

    return {
        "chain_fusable": bool(getattr(cls, "CHAIN_FUSABLE", False)),
        "device_resident": bool(getattr(cls, "DEVICE_RESIDENT", False)),
        "timer": has_timer,
        "sink_pads": _pads(getattr(cls, "NUM_SINK_PADS", 1)),
        "src_pads": _pads(getattr(cls, "NUM_SRC_PADS", 1)),
    }


def contract_badges(cls) -> str:
    """Compact human rendering for the docs table, e.g.
    ``fusable · device-resident · timer · pads 1→dynamic``."""
    c = element_contract(cls)
    badges = []
    if c["chain_fusable"]:
        badges.append("fusable")
    if c["device_resident"]:
        badges.append("device-resident")
    if c["timer"]:
        badges.append("timer")
    badges.append(f"pads {c['sink_pads']}→{c['src_pads']}")
    return " · ".join(badges)
