"""`python -m nnstreamer_tpu lint` — the nnlint command line.

Exit codes: 0 clean, 1 findings, 2 usage/internal error (pytest-style).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from nnstreamer_tpu.analysis.core import (
    build_project, load_baseline, run_rules, write_baseline)
from nnstreamer_tpu.analysis.rules import ALL_RULES, iter_rules

DEFAULT_BASELINE = "nnlint_baseline.json"


def _repo_root() -> Path:
    """Nearest ancestor holding the package dir — so `lint` works from
    any cwd inside the repo; from outside a checkout, fall back to the
    imported package's own location (scan what you're running)."""
    here = Path.cwd().resolve()
    for cand in (here, *here.parents):
        if (cand / "nnstreamer_tpu" / "__init__.py").exists():
            return cand
    import nnstreamer_tpu

    return Path(nnstreamer_tpu.__file__).resolve().parent.parent


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu lint",
        description="project-specific static analysis "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to scan (default: nnstreamer_tpu)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file (default {DEFAULT_BASELINE} "
                         f"at the repo root when present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "and exit 0 (policy: keep it empty — fix or "
                         "inline-suppress instead)")
    ap.add_argument("--rules", default=None, metavar="IDS",
                    help="comma list of rule ids to run (default all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.rule_id}  {r.title}")
            print(f"    {r.rationale}")
        return 0

    try:
        rules = iter_rules(args.rules.split(",") if args.rules else None)
    except ValueError as e:
        print(f"nnlint: {e}", file=sys.stderr)
        return 2

    root = _repo_root()
    baseline_path = Path(args.baseline) if args.baseline \
        else root / DEFAULT_BASELINE
    project = build_project(args.paths or ["nnstreamer_tpu"], root=root)
    if not project.modules:
        print("nnlint: no python files found under "
              f"{args.paths or ['nnstreamer_tpu']}", file=sys.stderr)
        return 2

    if args.write_baseline:
        report = run_rules(project, rules, baseline=None)
        n = write_baseline(baseline_path, report.findings)
        print(f"nnlint: wrote {n} fingerprint(s) to {baseline_path}")
        return 0

    report = run_rules(project, rules,
                       baseline=load_baseline(baseline_path))
    if args.json:
        print(json.dumps(report.to_json(), indent=2))
        return 0 if report.clean else 1

    for f in report.findings:
        print(f)
    tail = (f"nnlint: {len(report.findings)} finding(s) in "
            f"{report.files} file(s)")
    extras = []
    if report.baselined:
        extras.append(f"{report.baselined} baselined")
    if report.suppressed:
        extras.append(f"{len(report.suppressed)} suppressed inline")
    if extras:
        tail += f" ({', '.join(extras)})"
    print(tail, file=sys.stderr if report.clean else sys.stdout)
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
