"""The project-specific rule catalog (docs/static_analysis.md).

NNL001 element-contract   timer pair complete; CHAIN_FUSABLE matches
                          element shape; DEVICE_RESIDENT not on sinks;
                          contract flags declared, not mutated per-instance
NNL002 forced-sync        block_until_ready / jax.device_get / device
                          np.asarray only via runtime/sync.device_sync
NNL003 lock-discipline    no blocking call inside a `with <lock>:` body
NNL004 jit-purity         nothing impure reachable from jitted functions
NNL005 spawn-safety       no module-scope jax work in modules the spawn
                          worker imports
NNL006 picklable-errors   every public error class carries the
                          __reduce__ round-trip contract
NNL007 thread-audit       every thread is daemon or joined/cancelled on
                          a close path
NNL008 socket-audit       every socket in the serving path has a
                          deadline (timeout kwarg / settimeout) or is
                          owned by a reader/accept thread
NNL009 placement-audit    explicit device picks (jax.devices()[i])
                          only inside serving/placement.py and
                          parallel/ — placement decisions route
                          through the subsystem
NNL010 device-accounting  XLA cost-model reads (cost_analysis()),
                          device memory ledgers (memory_stats()) and
                          peak-FLOPs/bandwidth tables only inside
                          runtime/devprof.py (bench.py keeps its own
                          sweep-local copy) — one accounting site for
                          "peak" vs "achieved"
NNL011 seeded-chaos       no unseeded RNG construction
                          (random.Random() / np.random.default_rng()
                          with no arguments) in the chaos/load paths
                          (traffic/, scenario/, serving worker chaos
                          hooks) — every drill must replay bit-exact
                          from its recorded seed
NNL012 shard-safety       shard_map / NamedSharding / PartitionSpec
                          construction (and their jax imports) only
                          inside parallel/ and serving/sharding.py —
                          sharded serving's bit-parity contract holds
                          because every mesh program goes through the
                          canonical-blocking helpers; a stray
                          shard_map elsewhere reintroduces
                          shard-count-dependent numerics
NNL013 shm-safety         multiprocessing.shared_memory / mmap only
                          inside serving/shm.py (segment lifetime and
                          resource-tracker semantics live in ONE
                          place), and no per-frame `pickle.dumps`
                          inside loops on the serving hot paths — the
                          shm ring lane exists so steady-state hops
                          don't re-serialize per frame

Every rule is pure AST — nothing here imports the code under analysis.
Heuristics err toward silence (a missed finding is a review problem; a
noisy gate gets deleted), and every deliberate exception at a flagged
site takes an inline `# nnlint: disable=...` with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from nnstreamer_tpu.analysis.core import (
    Module, Project, Rule, const_value, dotted, walk_no_functions)

#: class names that mark an Element subclass without importing it
_ELEMENT_BASES = {"Element", "SourceElement", "SinkElement"}
#: sink-side bases (sync points: DEVICE_RESIDENT is a contradiction)
_SINK_BASES = {"SinkElement"}


def _is_element_class(node: ast.ClassDef) -> bool:
    for deco in node.decorator_list:
        if dotted(deco).split(".")[-1] == "register_element":
            return True
    return any(dotted(b).split(".")[-1] in _ELEMENT_BASES
               for b in node.bases)


def _class_assigns(node: ast.ClassDef) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for stmt in node.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            out[stmt.target.id] = stmt.value
    return out


def _method_names(node: ast.ClassDef) -> Set[str]:
    return {s.name for s in node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}


class ElementContract(Rule):
    rule_id = "NNL001"
    title = "element-contract"
    rationale = (
        "the scheduler trusts class-level contract flags: a timer "
        "element missing half its pair never wakes (or fires into a "
        "missing handler), a fusable multi-pad element would execute "
        "fan-in on a chain thread, and a DEVICE_RESIDENT sink would "
        "never sync its results")

    def check(self, module: Module, project: Project):
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) and _is_element_class(node):
                yield from self._check_class(node)
            # contract flags are class-level declarations the scheduler
            # and docs introspect — per-instance mutation hides them
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" \
                            and t.attr in ("CHAIN_FUSABLE",
                                           "DEVICE_RESIDENT"):
                        yield node, (
                            f"contract flag {t.attr} mutated per-instance; "
                            f"declare it on the class (the scheduler and "
                            f"docs introspect the class-level value)")

    def _check_class(self, node: ast.ClassDef):
        assigns = _class_assigns(node)
        methods = _method_names(node)
        has_deadline = "next_deadline" in methods
        has_timer = "on_timer" in methods
        if has_deadline != has_timer:
            missing = "on_timer" if has_deadline else "next_deadline"
            present = "next_deadline" if has_deadline else "on_timer"
            yield node, (
                f"element {node.name} defines {present} without "
                f"{missing}: the timer contract is a pair (scheduler "
                f"worker loop fires on_timer when next_deadline expires)")
        fusable = const_value(assigns["CHAIN_FUSABLE"]) \
            if "CHAIN_FUSABLE" in assigns else None
        if (has_deadline and has_timer) and fusable is not False:
            yield node, (
                f"timer element {node.name} must declare CHAIN_FUSABLE "
                f"= False: a fused chain member cannot be woken "
                f"independently of its chain head")
        for attr in ("NUM_SINK_PADS", "NUM_SRC_PADS"):
            if attr in assigns:
                v = const_value(assigns[attr])
                if isinstance(v, int) and (v == -1 or v >= 2) \
                        and fusable is not False:
                    yield node, (
                        f"element {node.name} declares {attr}="
                        f"{'DYNAMIC' if v == -1 else v} but not "
                        f"CHAIN_FUSABLE = False: chain fusion is for "
                        f"single-in/single-out call-through elements only")
                    break
        if const_value(assigns.get("DEVICE_RESIDENT",
                                   ast.Constant(False))) is True:
            if any(dotted(b).split(".")[-1] in _SINK_BASES
                   for b in node.bases):
                yield node, (
                    f"sink element {node.name} declares DEVICE_RESIDENT "
                    f"= True: sinks are sync points — their results "
                    f"must resolve (runtime/sync.device_sync)")


class ForcedSync(Rule):
    rule_id = "NNL002"
    title = "forced-sync"
    rationale = (
        "runtime/sync.device_sync is the single host-sync choke point: "
        "it does one whole-tuple block_until_ready and feeds the "
        "tracer's forced_syncs stat — a direct block_until_ready / "
        "device_get / device-array np.asarray elsewhere is an invisible "
        "host-path tax the bench can no longer attribute")

    #: the one module allowed to touch the primitives
    EXEMPT = ("runtime/sync.py",)
    #: directories where a bare single-arg np.asarray is presumed to be
    #: a device readback (elements/decoders consume host arrays the
    #: scheduler already resolved; the device-adjacent layers do not).
    #: Deliberately NOT listed: serving/ — the metrics/exposition plane
    #: (serving/metrics.py) and the pool router are host-only code that
    #: read counters under their own locks and never hold a device
    #: array, so a bare asarray there is a plain host copy, not a
    #: hidden sync. Widening this to serving/ would force the blessed
    #: device_sync idiom onto code with no device to sync.
    ASARRAY_DIRS = ("backends/", "runtime/")

    def check(self, module: Module, project: Project):
        if module.path.endswith(self.EXEMPT):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            if leaf == "block_until_ready":
                yield node, (
                    "direct block_until_ready bypasses "
                    "runtime/sync.device_sync (one sync call site keeps "
                    "the tracer's forced_syncs truthful)")
            elif d == "jax.device_get" or d.endswith(".device_get"):
                yield node, (
                    "jax.device_get bypasses runtime/sync.device_sync; "
                    "resolve via device_sync then read on host")
            elif d in ("np.asarray", "numpy.asarray") \
                    and self._in_asarray_scope(module.path) \
                    and len(node.args) == 1 and not node.keywords \
                    and not self._arg_is_synced(node.args[0]):
                yield node, (
                    "bare np.asarray in a device-adjacent layer is a "
                    "hidden host sync; use np.asarray(device_sync(x)) "
                    "so the sync is counted, or add a justification")

    def _in_asarray_scope(self, path: str) -> bool:
        return any(f"/{d}" in f"/{path}" for d in self.ASARRAY_DIRS)

    @staticmethod
    def _arg_is_synced(arg: ast.AST) -> bool:
        """np.asarray(device_sync(x)) is the blessed idiom: the sync is
        explicit and counted; the asarray is then a plain host copy."""
        return isinstance(arg, ast.Call) \
            and dotted(arg.func).split(".")[-1] == "device_sync"


class LockDiscipline(Rule):
    rule_id = "NNL003"
    title = "lock-discipline"
    rationale = (
        "a blocking call while holding a lock is the classic deadlock/"
        "latency-cliff shape: every other thread that needs the lock "
        "stalls behind the wait (runtime/channel.py exists to do this "
        "correctly with condition variables)")

    #: queue-ish receiver names where a positional .get()/.put() is a
    #: blocking channel operation, not a dict access
    QUEUE_NAMES = {"q", "queue", "outq", "inq", "sendq", "frames",
                   "channel", "chan", "done_q", "acks"}

    def check(self, module: Module, project: Project):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.With):
                continue
            lock_names = [dotted(item.context_expr).split(".")[-1]
                          for item in node.items]
            if not any(n.lower().endswith("lock") for n in lock_names):
                continue
            for inner in walk_no_functions(node.body):
                if isinstance(inner, ast.Call):
                    msg = self._blocking(inner)
                    if msg:
                        yield inner, (
                            f"{msg} inside `with "
                            f"{'/'.join(lock_names)}:` — blocking under "
                            f"a lock stalls every thread that needs it")

    def _blocking(self, call: ast.Call) -> Optional[str]:
        d = dotted(call.func)
        leaf = d.split(".")[-1]
        kwargs = {k.arg for k in call.keywords}
        if d == "time.sleep" or leaf == "sleep":
            return "time.sleep"
        if leaf == "device_sync":
            return "device_sync (host sync)"
        if leaf == "join" and (not call.args or "timeout" in kwargs):
            # str.join always takes one positional and never timeout=
            return "thread/process join"
        if leaf in ("recv", "recv_bytes", "accept", "recvfrom"):
            return f"socket/pipe {leaf}()"
        if leaf in ("get", "put"):
            recv = dotted(call.func.value).split(".")[-1].lower() \
                .lstrip("_") if isinstance(call.func, ast.Attribute) else ""
            if kwargs & {"timeout", "deadline"} \
                    or recv in self.QUEUE_NAMES:
                return f"queue/channel {leaf}()"
        return None


#: call prefixes/names that are impure under jax tracing: host clocks,
#: host RNG, I/O, tracer hooks, host syncs
_JIT_BANNED_PREFIX = ("time.", "random.", "np.random.", "numpy.random.",
                      "os.", "socket.", "logging.")
_JIT_BANNED_NAMES = {"open", "print", "input", "perf_counter",
                     "device_sync", "block_until_ready", "monotonic"}
#: module origins that make a bare imported name impure
_JIT_BANNED_MODULES = {"time", "random", "os", "socket"}


class JitPurity(Rule):
    rule_id = "NNL004"
    title = "jit-purity"
    rationale = (
        "a function traced by jax.jit/compose_segment runs its Python "
        "body ONCE at trace time: clocks freeze into constants, host "
        "RNG draws bake in forever, tracer/I-O calls fire at compile "
        "instead of per frame — all silent wrong-answer bugs")

    MAX_DEPTH = 8

    def check(self, module: Module, project: Project):
        imports = _import_map(module.tree)
        for node in ast.walk(module.tree):
            fn: Optional[Tuple[Module, ast.AST]] = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    if self._is_jit(deco):
                        fn = (module, node)
            elif isinstance(node, ast.Call) and self._is_jit(node) \
                    and node.args:
                fn = self._resolve(module, project, imports, node.args[0])
            if fn is None:
                continue
            seen: Set[Tuple[str, str]] = set()
            yield from self._scan(project, fn[0], fn[1], seen, 0)

    @staticmethod
    def _is_jit(node: ast.AST) -> bool:
        d = dotted(node)
        leaf = d.split(".")[-1]
        if leaf in ("jit", "pmap", "compose_segment"):
            return True
        # @partial(jax.jit, ...) / partial(jit, ...)
        if isinstance(node, ast.Call) \
                and dotted(node.func).split(".")[-1] == "partial" \
                and node.args:
            return JitPurity._is_jit(node.args[0])
        return False

    def _resolve(self, module: Module, project: Project, imports,
                 arg: ast.AST) -> Optional[Tuple[Module, ast.AST]]:
        """Name → its FunctionDef, locally or via `from X import f`
        when X is a scanned module. Lambdas/inline defs analyze in
        place; attributes (bound methods) are skipped."""
        if isinstance(arg, ast.Lambda):
            return module, arg
        if not isinstance(arg, ast.Name):
            return None
        fn = _module_function(module.tree, arg.id)
        if fn is not None:
            return module, fn
        origin = imports.get(arg.id)
        if origin:
            target = project.by_dotted(origin[0])
            if target is not None:
                fn = _module_function(target.tree, origin[1])
                if fn is not None:
                    return target, fn
        return None

    def _scan(self, project: Project, module: Module, fn: ast.AST,
              seen: Set[Tuple[str, str]], depth: int):
        key = (module.path, getattr(fn, "name", f"<lambda@{fn.lineno}>"))
        if key in seen or depth > self.MAX_DEPTH:
            return
        seen.add(key)
        imports = _import_map(module.tree)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in ast.walk(ast.Module(body=body, type_ignores=[])):
            if isinstance(node, ast.Attribute) and node.attr == "_tracer":
                yield _at(node, module, fn), (
                    f"tracer access reachable from jitted "
                    f"{key[1]} ({module.path}): hooks fire at trace "
                    f"time, not per frame")
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            banned = (
                any(d.startswith(p) for p in _JIT_BANNED_PREFIX)
                or d in _JIT_BANNED_NAMES
                or leaf == "block_until_ready"
                or (isinstance(node.func, ast.Name)
                    and imports.get(d, ("",))[0] in _JIT_BANNED_MODULES))
            if banned:
                yield _at(node, module, fn), (
                    f"impure call {d or leaf}() reachable from jitted "
                    f"{key[1]} ({module.path}): traces once, then "
                    f"freezes into the compiled program")
                continue
            # follow local/imported plain-function calls
            nxt = self._resolve(module, project, imports,
                                node.func if isinstance(node.func, ast.Name)
                                else ast.Constant(None))
            if nxt is not None:
                yield from self._scan(project, nxt[0], nxt[1], seen,
                                      depth + 1)


def _at(node: ast.AST, module: Module, fn: ast.AST):
    """Findings for cross-module reachability anchor on the defining
    module only when it is the one being scanned; otherwise on the
    jitted function's def line (suppressions stay local)."""
    return node if getattr(node, "lineno", None) else fn


def _module_function(tree: ast.AST, name: str) -> Optional[ast.AST]:
    """FunctionDef named `name` anywhere in the module (jit wrappees
    are often defined inside factory functions)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _import_map(tree: ast.AST) -> Dict[str, Tuple[str, str]]:
    """name → (module dotted path, original name) for every import in
    the tree (function-local imports included: the runtime imports jax
    lazily everywhere)."""
    out: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[(a.asname or a.name).split(".")[0]] = (a.name, "")
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = (node.module, a.name)
    return out


class SpawnSafety(Rule):
    rule_id = "NNL005"
    title = "spawn-safety"
    rationale = (
        "serving/pool.py uses the spawn context: every worker process "
        "re-imports its modules from scratch — module-scope jax work "
        "(or even a module-scope jax import) in that closure runs N "
        "times at fork-bomb speed, initializes device runtimes before "
        "the worker can configure them, and wedges startup")

    ROOT = "nnstreamer_tpu/serving/worker.py"
    PKG = "nnstreamer_tpu"

    def check(self, module: Module, project: Project):
        closure = self._closure(project)
        if module.path not in closure:
            return
        for stmt in self._module_scope(module.tree):
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        yield stmt, (
                            f"module-scope `import {a.name}` in a "
                            f"module the spawn worker imports "
                            f"({self.ROOT}): keep jax imports lazy")
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.module and (stmt.module == "jax"
                                    or stmt.module.startswith("jax.")):
                    yield stmt, (
                        f"module-scope `from {stmt.module} import ...` "
                        f"in a module the spawn worker imports "
                        f"({self.ROOT}): keep jax imports lazy")
            else:
                for node in walk_no_functions([stmt]):
                    if isinstance(node, ast.Call):
                        d = dotted(node.func)
                        if d.startswith("jax.") or d.startswith("jnp."):
                            yield node, (
                                f"module-scope device work {d}() in a "
                                f"module the spawn worker imports "
                                f"({self.ROOT}): every worker re-runs "
                                f"it at import")

    def _closure(self, project: Project) -> Set[str]:
        root = project.modules.get(self.ROOT)
        if root is None:
            return set()
        todo, seen = [self.ROOT], {self.ROOT}
        while todo:
            mod = project.modules.get(todo.pop())
            if mod is None:
                continue
            for stmt in self._module_scope(mod.tree):
                for name in self._imported_modules(stmt, mod.path):
                    if not name.startswith(self.PKG):
                        continue
                    target = project.by_dotted(name)
                    if target and target.path not in seen:
                        seen.add(target.path)
                        todo.append(target.path)
        return seen

    @staticmethod
    def _module_scope(tree: ast.AST):
        """Top-level statements, descending through top-level if/try
        blocks (conditional imports still run at import time) but never
        into function or class bodies' functions."""
        stack = list(getattr(tree, "body", []))
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, (ast.If, ast.Try, ast.With, ast.ClassDef)):
                for field in ("body", "orelse", "finalbody"):
                    stack.extend(getattr(stmt, field, []))
                for h in getattr(stmt, "handlers", []):
                    stack.extend(h.body)
                continue
            yield stmt

    @staticmethod
    def _imported_modules(stmt: ast.AST, path: str) -> Iterable[str]:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                yield a.name
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.level:
                pkg_parts = path.split("/")[:-1]
                base = ".".join(pkg_parts[: len(pkg_parts) - stmt.level + 1])
                mod = f"{base}.{stmt.module}" if stmt.module else base
            else:
                mod = stmt.module or ""
            # `from X import name`: name may be a submodule or an
            # attribute — offer both; by_dotted misses attributes
            yield mod
            for a in stmt.names:
                if a.name != "*":
                    yield f"{mod}.{a.name}"


class PicklableErrors(Rule):
    rule_id = "NNL006"
    title = "picklable-errors"
    rationale = (
        "errors cross process boundaries in the worker pool pickled; "
        "naive pickling re-invokes cls(*args), so any subclass with a "
        "custom __init__ signature raises TypeError at UNPICKLE time — "
        "the parent then loses the real failure")

    def check(self, module: Module, project: Project):
        if not module.path.endswith("errors.py"):
            return
        classes: Dict[str, ast.ClassDef] = {
            n.name: n for n in module.tree.body
            if isinstance(n, ast.ClassDef)}
        for node in classes.values():
            if node.name.startswith("_"):
                continue
            if not self._is_exception(node, classes):
                continue
            if not self._has_reduce(node, classes):
                yield node, (
                    f"public error class {node.name} has no __reduce__ "
                    f"in its local base chain: subclass "
                    f"NNStreamerTPUError (or define __reduce__) so it "
                    f"survives the worker-pool pickle round trip")

    def _is_exception(self, node: ast.ClassDef,
                      classes: Dict[str, ast.ClassDef]) -> bool:
        for b in node.bases:
            name = dotted(b).split(".")[-1]
            if name in ("Exception", "BaseException") \
                    or name.endswith("Error"):
                if name in classes:
                    return self._is_exception(classes[name], classes) \
                        or True
                return True
            if name in classes and self._is_exception(classes[name],
                                                      classes):
                return True
        return False

    def _has_reduce(self, node: ast.ClassDef,
                    classes: Dict[str, ast.ClassDef],
                    depth: int = 0) -> bool:
        if depth > 10:
            return False
        if "__reduce__" in _method_names(node):
            return True
        return any(self._has_reduce(classes[dotted(b).split(".")[-1]],
                                    classes, depth + 1)
                   for b in node.bases
                   if dotted(b).split(".")[-1] in classes)


class ThreadAudit(Rule):
    rule_id = "NNL007"
    title = "thread-audit"
    rationale = (
        "a non-daemon thread that nobody joins outlives its owner: "
        "teardown hangs waiting for it (a fired Timer held a worker "
        "process alive past its graceful exit), and tests leak "
        "threads across cases")

    def check(self, module: Module, project: Project):
        src = module.src
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_subclass(node)
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            if d not in ("threading.Thread", "threading.Timer") \
                    and leaf not in ("Thread", "Timer"):
                continue
            if leaf not in ("Thread", "Timer"):
                continue
            if not (d.startswith("threading.") or d in ("Thread", "Timer")):
                continue
            if self._daemon_kw(node):
                continue
            target = self._assign_target(module, node)
            if target and (f"{target}.join" in src
                           or f"{target}.cancel" in src
                           or f"{target}.daemon" in src):
                continue
            kind = "Timer" if leaf == "Timer" else "Thread"
            yield node, (
                f"threading.{kind} is neither daemon=True nor "
                f"joined/cancelled on a close path: it outlives its "
                f"owner and hangs teardown")

    @staticmethod
    def _daemon_kw(node: ast.Call) -> bool:
        for k in node.keywords:
            if k.arg == "daemon" \
                    and isinstance(k.value, ast.Constant) \
                    and k.value.value is True:
                return True
        return False

    @staticmethod
    def _assign_target(module: Module, call: ast.Call) -> Optional[str]:
        """Terminal name the Thread lands in (x / self.x / slot.x),
        found by locating the Assign/append wrapping this call."""
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    return t.id
                if isinstance(t, ast.Attribute):
                    return t.attr
            # timers.append(threading.Timer(...)) — audit the list name
            if isinstance(node, ast.Call) and call in node.args \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "append":
                return dotted(node.func.value).split(".")[-1]
        return None

    def _check_subclass(self, node: ast.ClassDef):
        if not any(dotted(b) in ("threading.Thread", "Thread")
                   for b in node.bases):
            return
        for stmt in node.body:
            if isinstance(stmt, ast.FunctionDef) \
                    and stmt.name == "__init__":
                blob = ast.dump(stmt)
                if "daemon" in blob:
                    return
                yield node, (
                    f"threading.Thread subclass {node.name}.__init__ "
                    f"never sets daemon: instances default non-daemon "
                    f"and hang interpreter exit unless every owner "
                    f"joins them")
                return


class SocketAudit(Rule):
    rule_id = "NNL008"
    title = "socket-audit"
    rationale = (
        "a socket with no deadline is an unbounded wait: an outbound "
        "dial into a blackholed address sits in the OS connect retry "
        "cycle (~2 minutes of SYN retransmits) wedging the dialing "
        "thread, and a blocking recv with no owner thread wedges "
        "whoever calls it — the mesh lease detector can fence a dead "
        "host in seconds only if no layer below it blocks for minutes")

    #: the serving path: every socket here sits under real traffic
    SCOPE = ("edge/", "serving/", "traffic/")
    #: creation calls we audit (module-qualified only: a bare .socket
    #: attribute or local create_connection helper is out of scope —
    #: heuristics err toward silence)
    DIAL_CALLS = ("socket.create_connection", "_socket.create_connection")
    RAW_CALLS = ("socket.socket", "_socket.socket")

    def check(self, module: Module, project: Project):
        if not any(f"/{d}" in f"/{module.path}" for d in self.SCOPE):
            return
        thread_owned = self._thread_owned_names(module.tree)
        src = module.src
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d in self.DIAL_CALLS:
                # create_connection(addr, timeout) — second positional
                # or timeout= kwarg bounds the dial
                if len(node.args) >= 2 \
                        or any(k.arg == "timeout" for k in node.keywords):
                    continue
                yield node, (
                    "outbound dial without a connect timeout: pass "
                    "timeout= (DEFAULT_CONNECT_TIMEOUT_S) — the OS "
                    "default is minutes of SYN retries and the dialing "
                    "thread wedges for all of them")
            elif d in self.RAW_CALLS:
                target = ThreadAudit._assign_target(module, node)
                if target and (f"{target}.settimeout" in src
                               or target in thread_owned):
                    continue
                yield node, (
                    "socket in the serving path with no deadline "
                    "discipline: call settimeout(), or hand it to a "
                    "dedicated reader/accept thread (NNL007-audited) "
                    "whose close path unblocks it")

    @staticmethod
    def _thread_owned_names(tree: ast.AST) -> Set[str]:
        """Names (x / self.x attrs) referenced inside a function that
        some threading.Thread/Timer in this module runs as target=.
        A socket owned by such a thread is bounded by the thread's
        lifecycle, which NNL007 separately audits."""
        targets: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and dotted(node.func).split(".")[-1] in ("Thread",
                                                             "Timer"):
                for k in node.keywords:
                    if k.arg == "target":
                        targets.add(dotted(k.value).split(".")[-1])
        owned: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in targets:
                for inner in ast.walk(node):
                    if isinstance(inner, ast.Attribute):
                        owned.add(inner.attr)
                    elif isinstance(inner, ast.Name):
                        owned.add(inner.id)
        return owned


class PlacementAudit(Rule):
    rule_id = "NNL009"
    title = "placement-audit"
    rationale = (
        "explicit device selection (`jax.devices()[i]`) scattered "
        "through the tree is how placement bugs are born: two call "
        "sites disagree about which chip owns a model and the result "
        "is silent cross-device copies or a replica serving on the "
        "wrong chip. All placement decisions route through "
        "serving/placement.py (visible_devices/device_of/"
        "accelerator_for) and parallel/ — everything else receives a "
        "device, it never picks one")

    #: the subsystem allowed to pick devices; everything else is flagged
    ALLOWED = ("serving/placement.py", "parallel/")
    DEVICE_CALLS = ("jax.devices", "jax.local_devices")

    def check(self, module: Module, project: Project):
        p = f"/{module.path}"
        if any(f"/{a}" in p for a in self.ALLOWED):
            return
        for node in ast.walk(module.tree):
            # jax.devices(...)[i] with a single (non-slice) index — a
            # hard-coded placement decision. Slices (`[:dp]`) pass:
            # taking "the first N devices" as a mesh axis is topology
            # enumeration, not placing one object on one chip.
            if not isinstance(node, ast.Subscript) \
                    or isinstance(node.slice, ast.Slice):
                continue
            v = node.value
            if isinstance(v, ast.Call) \
                    and dotted(v.func) in self.DEVICE_CALLS:
                yield node, (
                    f"explicit device pick `{dotted(v.func)}(...)[i]` "
                    f"outside the placement subsystem: take a device "
                    f"(or an accelerator= string) from the caller, or "
                    f"route through serving/placement.device_of()")


class DeviceAccountingAudit(Rule):
    rule_id = "NNL010"
    title = "device-accounting"
    rationale = (
        "MFU / roofline / HBM numbers are only trustworthy when "
        "'peak' and 'achieved' come from ONE accounting site. XLA "
        "cost-model reads (`.cost_analysis()`), device memory ledgers "
        "(`.memory_stats()`) and peak-FLOPs/bandwidth constant tables "
        "live in runtime/devprof.py; everything else reports into the "
        "profiler (capture_cost / note_dispatch) and reads stats() "
        "back out. bench.py (outside the package) keeps its own "
        "sweep-local peak table by design")

    #: the blessed accounting sites; everything else is flagged
    ALLOWED = ("runtime/devprof.py", "bench.py")
    #: attribute calls that ARE device accounting
    ACCOUNTING_ATTRS = ("cost_analysis", "memory_stats")
    #: module/class-level constant names that smell like a peak table
    PEAK_NAMES = ("TFLOPS", "GFLOPS", "FLOPS", "GBPS", "HBM_BW")

    def check(self, module: Module, project: Project):
        p = f"/{module.path}"
        if any(f"/{a}" in p for a in self.ALLOWED):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self.ACCOUNTING_ATTRS:
                yield node, (
                    f"device-accounting read `.{node.func.attr}()` "
                    f"outside runtime/devprof.py: report through "
                    f"DeviceProfiler.capture_cost() / read the ledger "
                    f"via devprof.get().stats() so 'peak' and "
                    f"'achieved' share one accounting site")
        # peak tables: module-scope constant assignments whose name
        # declares hardware peaks (PEAK_BF16_TFLOPS, PEAK_HBM_GBPS, …)
        for node in module.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                name = t.id if isinstance(t, ast.Name) else ""
                if name.isupper() and "PEAK" in name and any(
                        s in name for s in self.PEAK_NAMES):
                    yield node, (
                        f"hardware peak table `{name}` outside "
                        f"runtime/devprof.py: use devprof.PEAK_TFLOPS "
                        f"/ devprof.peak_for() — a second copy is how "
                        f"MFU denominators drift")


class SeededChaosAudit(Rule):
    rule_id = "NNL011"
    title = "seeded-chaos"
    rationale = (
        "the whole value of a chaos drill is that a failure replays "
        "bit-exact from its recorded seed (scenario replay, ChaosProxy "
        "streams, shrinker repros). One `random.Random()` or "
        "`np.random.default_rng()` constructed WITHOUT a seed anywhere "
        "in the load/fault path and the repro is theater: the schedule "
        "that failed last night cannot be rebuilt. Inside the chaos "
        "paths every RNG takes an explicit seed derived from the run's "
        "root (ScenarioSpec.sub_seed / per-connection streams)")

    #: the paths where determinism is load-bearing; elsewhere an
    #: unseeded rng is someone else's design decision
    SCOPED = ("traffic/", "scenario/", "serving/worker.py")
    #: constructors that mint a fresh RNG; unseeded = zero positional
    #: args and no seed= keyword
    RNG_CALLS = ("random.Random", "np.random.default_rng",
                 "numpy.random.default_rng", "default_rng")

    def check(self, module: Module, project: Project):
        p = f"/{module.path}"
        if not any(f"/{s}" in p for s in self.SCOPED):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name not in self.RNG_CALLS:
                continue
            seeded = bool(node.args) or any(
                k.arg in ("seed", "x") for k in node.keywords)
            if not seeded:
                yield node, (
                    f"unseeded `{name}()` in a chaos/load path: pass a "
                    f"seed derived from the run's root "
                    f"(ScenarioSpec.sub_seed / the harness seed) so "
                    f"the drill replays bit-exact")


class ShardSafety(Rule):
    rule_id = "NNL012"
    title = "shard-safety"
    rationale = (
        "sharded serving's headline guarantee — shards=N is "
        "bit-identical to shards=1 — holds because every mesh program "
        "is built by the canonical-blocking helpers in "
        "serving/sharding.py (fixed block count, fixed combine order) "
        "or the reviewed collectives in parallel/. A shard_map / "
        "NamedSharding / PartitionSpec constructed anywhere else is a "
        "private mesh program whose reduction order depends on the "
        "shard count: exactly the numerics drift the subsystem exists "
        "to rule out. Like NNL009, everything outside the subsystem "
        "consumes sharded trees and placers, it never builds them")

    #: the subsystem allowed to build mesh programs; everything else
    #: receives placed arrays / placer callables from it
    ALLOWED = ("parallel/", "serving/sharding.py")
    SHARD_NAMES = ("shard_map", "NamedSharding", "PartitionSpec")

    def check(self, module: Module, project: Project):
        p = f"/{module.path}"
        if any(f"/{a}" in p for a in self.ALLOWED):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if not (mod == "jax" or mod.startswith("jax.")):
                    continue
                for alias in node.names:
                    if alias.name in self.SHARD_NAMES:
                        yield node, (
                            f"`from {mod} import {alias.name}` outside "
                            f"the sharding subsystem: mesh programs are "
                            f"built only in parallel/ and "
                            f"serving/sharding.py — take a placed tree "
                            f"or a placer callable from there instead")
            elif isinstance(node, ast.Call):
                name = dotted(node.func).split(".")[-1]
                if name in self.SHARD_NAMES:
                    yield node, (
                        f"`{name}(...)` constructed outside the "
                        f"sharding subsystem: a private mesh program's "
                        f"reduction order depends on the shard count, "
                        f"breaking the shards=N bit-parity contract — "
                        f"route through serving/sharding.py "
                        f"(shard_params / kv_pool_placer / "
                        f"make_llm_fns) or parallel/")


class ShmSafety(Rule):
    rule_id = "NNL013"
    title = "shm-safety"
    rationale = (
        "the same-host shared-memory transport's conservation story "
        "(zero lost frames, zero orphan segments through worker "
        "kill/restart) holds because segment lifetime — create/attach/"
        "close/unlink and the resource-tracker unregister discipline — "
        "lives in exactly one module, serving/shm.py. A SharedMemory "
        "or mmap constructed anywhere else is a second lifetime "
        "story the kill drill does not audit. And on the serving hot "
        "paths, a `pickle.dumps` inside a loop re-serializes per "
        "frame — the tax the ring lane exists to remove; hoist the "
        "serialization out of the loop or route the payload through "
        "the transport")

    #: the one module allowed to own shared-memory segment lifetime
    ALLOWED = ("serving/shm.py",)
    #: where a per-frame pickle.dumps is a hot-path tax, not a choice
    HOT_PATHS = ("serving/",)

    def check(self, module: Module, project: Project):
        p = f"/{module.path}"
        if not any(f"/{a}" in p for a in self.ALLOWED):
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    if mod == "multiprocessing.shared_memory" or (
                            mod == "multiprocessing" and any(
                                a.name == "shared_memory"
                                for a in node.names)):
                        yield node, (
                            "multiprocessing.shared_memory imported "
                            "outside serving/shm.py: segment lifetime "
                            "(create/attach/close/unlink, resource-"
                            "tracker discipline) lives in ONE module — "
                            "use ShmRing from serving/shm.py")
                elif isinstance(node, ast.Call):
                    name = dotted(node.func)
                    if name.split(".")[-1] == "SharedMemory" \
                            or name == "mmap.mmap":
                        yield node, (
                            f"`{name}(...)` outside serving/shm.py: a "
                            f"shared segment constructed here has a "
                            f"lifetime the worker-kill drill does not "
                            f"audit — route through ShmRing "
                            f"(serving/shm.py)")
        if not any(f"/{s}" in p for s in self.HOT_PATHS):
            return
        seen: Set[int] = set()
        for loop in ast.walk(module.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for node in walk_no_functions(loop.body + loop.orelse):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                if dotted(node.func) == "pickle.dumps":
                    seen.add(id(node))
                    yield node, (
                        "per-frame `pickle.dumps` in a serving hot "
                        "loop: steady-state hops should not "
                        "re-serialize every frame — hoist the "
                        "serialization out of the loop or move the "
                        "payload onto the shm ring lane "
                        "(serving/shm.py)")


#: registry, in catalog order
ALL_RULES: List[Rule] = [
    ElementContract(), ForcedSync(), LockDiscipline(), JitPurity(),
    SpawnSafety(), PicklableErrors(), ThreadAudit(), SocketAudit(),
    PlacementAudit(), DeviceAccountingAudit(), SeededChaosAudit(),
    ShardSafety(), ShmSafety(),
]


def iter_rules(only: Optional[Iterable[str]] = None) -> List[Rule]:
    if not only:
        return list(ALL_RULES)
    want = {r.strip().upper() for r in only}
    unknown = want - {r.rule_id for r in ALL_RULES}
    if unknown:
        raise ValueError(
            f"unknown rule id(s) {sorted(unknown)}; available: "
            f"{[r.rule_id for r in ALL_RULES]}")
    return [r for r in ALL_RULES if r.rule_id in want]
