"""Decoder subplugins (reference: ext/nnstreamer/tensor_decoder/).

Importing this package registers all built-in decoders.
"""

from nnstreamer_tpu.decoders import label  # noqa: F401

__all__ = ["label"]
