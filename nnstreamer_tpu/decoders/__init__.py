"""Decoder subplugins (reference: ext/nnstreamer/tensor_decoder/).

Importing this package registers all built-in decoders.
"""

from nnstreamer_tpu.decoders import (  # noqa: F401
    boundingbox,
    direct_video,
    label,
    octet,
    pose,
    segment,
)

__all__ = ["boundingbox", "direct_video", "label", "octet", "pose", "segment"]
