"""image_labeling decoder — argmax over class scores → text label.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-imagelabel.c
(271 LoC): option1 = labels file (one label per line), output text/x-raw.
Output buffer: meta["label"], meta["label_index"], meta["score"], payload
= utf-8 bytes of the label.
"""

from __future__ import annotations

from typing import List

import numpy as np

from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import TextSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec


@register_decoder("image_labeling")
class ImageLabeling(DecoderSubplugin):
    def __init__(self):
        self.labels: List[str] = []

    def init(self, props: dict) -> None:
        from nnstreamer_tpu.decoders.util import load_labels

        self.labels = load_labels(props.get("option1", ""), "image_labeling")

    def negotiate(self, in_spec: TensorsSpec) -> TextSpec:
        if in_spec.num_tensors != 1:
            raise ValueError(
                f"expects exactly one scores tensor, got {in_spec.num_tensors}"
            )
        n_classes = in_spec.tensors[0].num_elements
        if self.labels and len(self.labels) not in (n_classes, n_classes - 1):
            raise ValueError(
                f"labels file has {len(self.labels)} entries but the scores "
                f"tensor has {n_classes} classes"
            )
        return TextSpec(rate=in_spec.rate)

    # -- device decode (tensor_decoder device=true) ------------------------
    def device_negotiate(self, in_spec: TensorsSpec) -> "TensorsSpec":
        self.negotiate(in_spec)
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

        return TensorsSpec.of(
            TensorInfo((1,), DType.INT32, name="class_index"),
            rate=in_spec.rate)

    def device_decode(self, tensors, aux=None):
        import jax.numpy as jnp

        idx = jnp.argmax(tensors[0].reshape(-1)).astype(jnp.int32)
        return (idx[None],)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        scores = np.asarray(buf.tensors[0]).reshape(-1)
        idx = int(scores.argmax())
        # background-class offset when labels == classes-1 (imagenet quant
        # models with class 0 = background, as in the reference test models)
        label_idx = idx
        if self.labels and len(self.labels) == scores.size - 1:
            label_idx = idx - 1
        label = (
            self.labels[label_idx]
            if self.labels and 0 <= label_idx < len(self.labels)
            else str(idx)
        )
        payload = np.frombuffer(label.encode("utf-8"), np.uint8).copy()
        out = buf.with_tensors((payload,))
        return out.with_meta(label=label, label_index=idx,
                             score=float(scores[idx]))
