"""bounding_boxes decoder — detection tensors → RGBA overlay video.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c
(1771 LoC): box schemes mobilenet-ssd (+priors), mobilenet-ssd-postprocess,
yolov5, ov-person-detection, mp-palm-detection (:143-158,177-184), NMS w/
IoU threshold (:125-127), label file, RGBA overlay with label text.

Options (reference property mapping):
- option1 = scheme (mode name above)
- option2 = labels file path (one per line)
- option3 = scheme config (mobilenet-ssd: "<score_thresh>:<iou_thresh>";
  priors come from models/ssd_mobilenet.generate_anchors — no sidecar
  box-priors file needed, TPU build generates them in-code)
- option4 = "W:H" output video size
- option5 = "W:H" model input size (box coordinate reference frame)

Output: RGBA video (boxes + labels on transparent background — the
reference draws on transparent RGBA for downstream compositing). Decoded
detections also ride `meta["boxes"]` as (N, 6) [ymin,xmin,ymax,xmax,
score,class] in output-pixel coordinates, so tests and downstream logic
need no pixel parsing.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.decoders.font import blit_text
from nnstreamer_tpu.decoders.util import load_labels, parse_wh
from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import VideoSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

SCHEMES = ("mobilenet-ssd", "mobilenet-ssd-postprocess", "yolov5",
           "ov-person-detection", "mp-palm-detection")

#: deterministic per-class overlay colors (RGBA)
_PALETTE = np.array([
    (255, 64, 64, 255), (64, 255, 64, 255), (64, 64, 255, 255),
    (255, 255, 64, 255), (255, 64, 255, 255), (64, 255, 255, 255),
    (255, 160, 0, 255), (160, 0, 255, 255),
], np.uint8)


def iou_matrix(boxes: np.ndarray) -> np.ndarray:
    """(N,4) [ymin,xmin,ymax,xmax] → (N,N) IoU."""
    area = np.maximum(0, boxes[:, 2] - boxes[:, 0]) * \
        np.maximum(0, boxes[:, 3] - boxes[:, 1])
    yx0 = np.maximum(boxes[:, None, :2], boxes[None, :, :2])
    yx1 = np.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = np.maximum(0.0, yx1 - yx0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return np.where(union > 0, inter / union, 0.0)


def nms(boxes: np.ndarray, scores: np.ndarray,
        iou_thresh: float, max_out: int = 100) -> np.ndarray:
    """Greedy per-call NMS → kept indices (descending score)."""
    order = np.argsort(-scores)
    keep: List[int] = []
    if order.size == 0:
        return np.array([], np.int64)
    ious = iou_matrix(boxes)
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        if len(keep) >= max_out:
            break
        suppressed |= ious[i] > iou_thresh
    return np.array(keep, np.int64)


@register_decoder("bounding_boxes")
class BoundingBoxes(DecoderSubplugin):
    def init(self, props: dict) -> None:
        self.scheme = props.get("option1", "") or "mobilenet-ssd"
        if self.scheme not in SCHEMES:
            raise PipelineError(
                f"bounding_boxes: unknown scheme {self.scheme!r}; "
                f"supported: {', '.join(SCHEMES)}"
            )
        self.labels = load_labels(props.get("option2", ""), "bounding_boxes")
        cfg = props.get("option3", "")
        parts = [x for x in cfg.split(":") if x]
        self.score_thresh = float(parts[0]) if parts else 0.5
        self.iou_thresh = float(parts[1]) if len(parts) > 1 else 0.5
        self.out_w, self.out_h = parse_wh(props.get("option4", ""), 640, 480)
        self.in_w, self.in_h = parse_wh(props.get("option5", ""), 300, 300)
        # option6 = device NMS formulation: greedy (exact host parity,
        # default) | fast (YOLACT matrix form for huge candidate counts)
        self._nms_mode = props.get("option6", "") or "greedy"
        if self._nms_mode not in ("greedy", "fast"):
            raise PipelineError(
                f"bounding_boxes option6 (device NMS) must be greedy|fast, "
                f"got {self._nms_mode!r}")
        # option7 = device=compact candidate count (top-K rows shipped)
        self._compact_k = int(props.get("option7", "") or 100)
        if self._compact_k < 1:
            raise PipelineError(
                f"bounding_boxes option7 (compact top-K) must be >= 1, "
                f"got {self._compact_k}")
        self._anchors: Optional[np.ndarray] = None

    def negotiate(self, in_spec: TensorsSpec) -> VideoSpec:
        if self.scheme in ("mobilenet-ssd",):
            if in_spec.num_tensors != 2:
                raise ValueError(
                    f"scheme mobilenet-ssd expects (loc, scores) tensors, "
                    f"got {in_spec.num_tensors}")
            from nnstreamer_tpu.models.ssd_mobilenet import generate_anchors

            self._anchors = generate_anchors()
            loc = in_spec.tensors[0]
            n_anchors = self._anchors.shape[0]
            if loc.num_elements % 4 or loc.num_elements // 4 != n_anchors:
                raise ValueError(
                    f"loc tensor {loc} does not hold {n_anchors} anchors ×4")
        elif self.scheme == "mobilenet-ssd-postprocess":
            # model already emits [boxes (N,4 normalized), classes, scores,
            # count] (tflite postprocess op layout)
            if in_spec.num_tensors not in (2, 4):
                raise ValueError(
                    "postprocess scheme expects (boxes, scores) or the "
                    "4-tensor tflite postprocess layout")
        elif self.scheme == "yolov5":
            if in_spec.num_tensors != 1:
                raise ValueError(
                    "yolov5 scheme expects one (1, N, 5+C) prediction tensor")
        elif self.scheme in ("ov-person-detection", "mp-palm-detection"):
            if in_spec.num_tensors != 1:
                raise ValueError(f"{self.scheme} expects one tensor")
        return VideoSpec(width=self.out_w, height=self.out_h, format="RGBA",
                         rate=in_spec.rate)

    # -- device decode (tensor_decoder device=true) ------------------------
    def device_negotiate(self, in_spec: TensorsSpec) -> TensorsSpec:
        if self.scheme != "mobilenet-ssd":
            raise PipelineError(
                f"bounding_boxes device decode supports scheme "
                f"mobilenet-ssd (raw loc+logits postprocess); "
                f"{self.scheme!r} decodes on host")
        self.negotiate(in_spec)   # validates tensors, builds anchors
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo

        self._top_k = 16
        return TensorsSpec.of(
            TensorInfo((self._top_k, 6), DType.FLOAT32, name="boxes"),
            rate=in_spec.rate)

    def device_aux(self):
        # anchors ride as a jit argument: ~1917×4 floats embedded as a
        # program literal degrade tunneled backends (backends/xla.py fuse)
        return {"anchors": np.asarray(self._anchors, np.float32)}

    def device_decode(self, tensors, aux=None):
        import jax.numpy as jnp

        from nnstreamer_tpu.decoders.device import ssd_decode_device

        anchors = (aux or {}).get("anchors")
        if anchors is None:   # host-side fallback path (backend declined)
            anchors = jnp.asarray(self._anchors, jnp.float32)
        loc, logits = tensors[0], tensors[1]
        det = ssd_decode_device(
            loc, logits, anchors,
            score_thresh=self.score_thresh, iou_thresh=self.iou_thresh,
            top_k=self._top_k, nms=self._nms_mode)
        # host decoder emits output-pixel coordinates; match it
        scale = jnp.array([self.out_h, self.out_w, self.out_h, self.out_w,
                           1.0, 1.0], jnp.float32)
        return (det * scale,)

    # -- device compaction (tensor_decoder device=compact) ------------------
    def device_compact_check(self) -> None:
        if self.scheme != "mobilenet-ssd":
            raise PipelineError(
                f"bounding_boxes device=compact supports scheme "
                f"mobilenet-ssd; {self.scheme!r} decodes on host")

    def device_compact(self, tensors, aux=None):
        """Raw (loc, logits) → (K,6) candidate rows on device; the host
        decode() keeps its exact threshold/NMS/overlay semantics. K=100
        (option7 overrides) covers every plausible above-threshold
        detection, so results match the full host path."""
        import jax.numpy as jnp

        from nnstreamer_tpu.decoders.device import ssd_compact_device

        self.device_compact_check()
        anchors = (aux or {}).get("anchors")
        if anchors is None:
            anchors = jnp.asarray(self._anchors, jnp.float32)
        return (ssd_compact_device(tensors[0], tensors[1], anchors,
                                   top_k=self._compact_k),)

    # -- per-scheme box extraction → (N, 6) [ymin,xmin,ymax,xmax,score,cls]
    def _extract(self, buf: TensorBuffer) -> np.ndarray:
        if getattr(self, "consume_compact", False):
            det = np.asarray(buf.tensors[0], np.float32)
            if det.ndim != 2 or det.shape[1] != 6:
                raise PipelineError(
                    f"compact bounding-box tensor must be (K,6), got "
                    f"{det.shape}")
            # Truncation signal: the compact tensor ships only the top-K
            # candidates (no threshold applied on device).  If even the
            # weakest shipped row clears the score threshold, rows that
            # would also have cleared it may have been cut — host parity
            # silently breaks.  Warn once per decoder; raise option7.
            if (len(det) and det[-1, 4] >= self.score_thresh
                    and not getattr(self, "_compact_trunc_warned", False)):
                self._compact_trunc_warned = True
                from nnstreamer_tpu.core.log import get_logger
                get_logger("decoder.bounding_boxes").warning(
                    "device=compact top-K (option7=%d) may be truncating: "
                    "last compact row score %.3f >= threshold %.3f; "
                    "detections above threshold may be missing — raise "
                    "option7", len(det), float(det[-1, 4]),
                    self.score_thresh)
            return det
        s = self.scheme
        if s == "mobilenet-ssd":
            from nnstreamer_tpu.models.ssd_mobilenet import decode_boxes

            loc = np.asarray(buf.tensors[0]).reshape(-1, 4)
            logits = np.asarray(buf.tensors[1])
            scores2d = logits.reshape(loc.shape[0], -1)
            if scores2d.min() < 0 or scores2d.max() > 1:
                scores2d = 1.0 / (1.0 + np.exp(-scores2d))  # logits → prob
            boxes = decode_boxes(loc, self._anchors)
            cls = scores2d[:, 1:].argmax(-1) + 1  # skip background 0
            score = scores2d[np.arange(len(cls)), cls]
            return np.concatenate(
                [boxes, score[:, None], cls[:, None].astype(np.float32)],
                axis=1)
        if s == "mobilenet-ssd-postprocess":
            if buf.num_tensors == 4:
                boxes = np.asarray(buf.tensors[0]).reshape(-1, 4)
                cls = np.asarray(buf.tensors[1]).reshape(-1)
                score = np.asarray(buf.tensors[2]).reshape(-1)
                n = int(np.asarray(buf.tensors[3]).reshape(-1)[0])
                boxes, cls, score = boxes[:n], cls[:n], score[:n]
            else:
                boxes = np.asarray(buf.tensors[0]).reshape(-1, 4)
                sc = np.asarray(buf.tensors[1]).reshape(len(boxes), -1)
                cls = sc.argmax(-1)
                score = sc[np.arange(len(cls)), cls]
            return np.concatenate(
                [boxes, score[:, None], cls[:, None].astype(np.float32)],
                axis=1)
        if s == "yolov5":
            p = np.asarray(buf.tensors[0]).reshape(-1,
                                                   buf.tensors[0].shape[-1])
            if len(p) == 0:  # empty frame: no detections, not an error
                return np.zeros((0, 6), np.float32)
            # [cx, cy, w, h, obj, class...] in input pixels or normalized
            xywh, obj, clsp = p[:, :4], p[:, 4], p[:, 5:]
            if xywh.max() > 2.0:  # pixel coords → normalize
                xywh = xywh / np.array(
                    [self.in_w, self.in_h, self.in_w, self.in_h], np.float32)
            cls = clsp.argmax(-1) if clsp.size else np.zeros(len(p))
            clsq = clsp[np.arange(len(p)), cls] if clsp.size else 1.0
            score = obj * clsq
            boxes = np.stack([
                xywh[:, 1] - xywh[:, 3] / 2, xywh[:, 0] - xywh[:, 2] / 2,
                xywh[:, 1] + xywh[:, 3] / 2, xywh[:, 0] + xywh[:, 2] / 2,
            ], axis=1)
            return np.concatenate(
                [boxes, np.asarray(score)[:, None],
                 np.asarray(cls)[:, None].astype(np.float32)], axis=1)
        if s == "ov-person-detection":
            # (N, 7) [image_id, label, conf, xmin, ymin, xmax, ymax]
            p = np.asarray(buf.tensors[0]).reshape(-1, 7)
            boxes = p[:, [4, 3, 6, 5]]
            return np.concatenate([boxes, p[:, 2:3], p[:, 1:2]], axis=1)
        # mp-palm-detection: (N, 18) [cx, cy, w, h, 7×kp(x,y)] w/ scores…
        p = np.asarray(buf.tensors[0]).reshape(-1, buf.tensors[0].shape[-1])
        if len(p) == 0:
            return np.zeros((0, 6), np.float32)
        cx, cy, w, h = p[:, 0], p[:, 1], p[:, 2], p[:, 3]
        score = p[:, 4] if p.shape[1] > 4 else np.ones(len(p), np.float32)
        if np.abs(cx).max() > 2.0:
            cx, cy = cx / self.in_w, cy / self.in_h
            w, h = w / self.in_w, h / self.in_h
        boxes = np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], 1)
        return np.concatenate(
            [boxes, score[:, None], np.zeros((len(p), 1), np.float32)], axis=1)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        det = self._extract(buf)
        det = det[det[:, 4] >= self.score_thresh]
        if len(det):
            keep = nms(det[:, :4], det[:, 4], self.iou_thresh)
            det = det[keep]
        img = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        out_px = det.copy()
        for row in det:
            y0, x0, y1, x1, score, cls = row
            color = _PALETTE[int(cls) % len(_PALETTE)]
            px0 = int(np.clip(x0 * self.out_w, 0, self.out_w - 1))
            px1 = int(np.clip(x1 * self.out_w, 0, self.out_w - 1))
            py0 = int(np.clip(y0 * self.out_h, 0, self.out_h - 1))
            py1 = int(np.clip(y1 * self.out_h, 0, self.out_h - 1))
            img[py0:py1 + 1, px0] = color
            img[py0:py1 + 1, px1] = color
            img[py0, px0:px1 + 1] = color
            img[py1, px0:px1 + 1] = color
            label = (self.labels[int(cls)]
                     if 0 <= int(cls) < len(self.labels) else str(int(cls)))
            blit_text(img, label[:16], px0 + 2, py0 + 2, color)
        if len(out_px):
            out_px[:, [0, 2]] *= self.out_h
            out_px[:, [1, 3]] *= self.out_w
        return buf.with_tensors((img,)).with_meta(boxes=out_px)
