"""Shared decoder option helpers (tensordecutil.c analog)."""

from __future__ import annotations

from pathlib import Path
from typing import List, Tuple

from nnstreamer_tpu.core.errors import PipelineError


def parse_wh(s: str, default_w: int, default_h: int) -> Tuple[int, int]:
    """'W:H' option string → (w, h); empty → defaults."""
    if not s:
        return default_w, default_h
    w, _, h = s.partition(":")
    try:
        return int(w), int(h)
    except ValueError:
        raise PipelineError(
            f"bad size option {s!r}: expected 'WIDTH:HEIGHT' (e.g. 640:480)"
        ) from None


def load_labels(path: str, what: str) -> List[str]:
    """One-label-per-line file → list; actionable error when missing."""
    if not path:
        return []
    p = Path(path)
    if not p.is_file():
        raise PipelineError(
            f"{what}: labels file {path!r} not found (expected a "
            f"one-label-per-line text file)")
    return [l.strip() for l in p.read_text().splitlines() if l.strip()]
