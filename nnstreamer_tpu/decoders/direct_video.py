"""direct_video decoder — uint8 tensor → raw video frames.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-directvideo.c
(377 LoC): 1/3/4-channel uint8 tensors become GRAY8/RGB/BGRx video.
Row-major (H, W, C) tensors map directly; option1 may force the format.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import VideoSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorsSpec

_BY_CHANNELS = {1: "GRAY8", 3: "RGB", 4: "RGBA"}


@register_decoder("direct_video")
class DirectVideo(DecoderSubplugin):
    def init(self, props: dict) -> None:
        self.force_format = props.get("option1", "") or None

    def negotiate(self, in_spec: TensorsSpec) -> VideoSpec:
        if in_spec.num_tensors != 1:
            raise ValueError(
                f"expects one video tensor, got {in_spec.num_tensors}")
        t = in_spec.tensors[0]
        if t.dtype != DType.UINT8:
            raise ValueError(
                f"direct_video needs uint8 input, got {t.dtype.type_name} "
                f"(insert tensor_transform mode=typecast option=uint8)")
        shape = t.shape
        if len(shape) == 4 and shape[0] == 1:
            shape = shape[1:]
        if len(shape) == 2:
            shape = shape + (1,)
        if len(shape) != 3 or shape[-1] not in _BY_CHANNELS:
            raise ValueError(
                f"cannot interpret shape {t.shape} as (H, W, C) video with "
                f"C in {sorted(_BY_CHANNELS)}")
        h, w, c = shape
        fmt = self.force_format or _BY_CHANNELS[c]
        return VideoSpec(width=w, height=h, format=fmt, rate=in_spec.rate)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        t = np.asarray(buf.tensors[0])
        if t.ndim == 4 and t.shape[0] == 1:
            t = t[0]
        if t.ndim == 2:
            t = t[..., None]
        return buf.with_tensors((np.ascontiguousarray(t),))
