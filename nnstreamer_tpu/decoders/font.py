"""Tiny bitmap font rasterizer shared by overlay decoders.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-font.c — an 8x8
raster font used by bounding-box/label overlays. Ours is an original 3x5
micro-glyph set (defined below as 15-bit masks) upscaled to 8x8 cells, so
overlay text is legible without shipping a font table.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

# 3 columns x 5 rows per glyph, row-major bits (msb = left column).
# Covers digits, uppercase, and common label punctuation; unknown chars
# render as a filled box.
_GLYPHS: Dict[str, Tuple[str, ...]] = {
    "0": ("111", "101", "101", "101", "111"),
    "1": ("010", "110", "010", "010", "111"),
    "2": ("111", "001", "111", "100", "111"),
    "3": ("111", "001", "111", "001", "111"),
    "4": ("101", "101", "111", "001", "001"),
    "5": ("111", "100", "111", "001", "111"),
    "6": ("111", "100", "111", "101", "111"),
    "7": ("111", "001", "010", "010", "010"),
    "8": ("111", "101", "111", "101", "111"),
    "9": ("111", "101", "111", "001", "111"),
    "A": ("010", "101", "111", "101", "101"),
    "B": ("110", "101", "110", "101", "110"),
    "C": ("011", "100", "100", "100", "011"),
    "D": ("110", "101", "101", "101", "110"),
    "E": ("111", "100", "110", "100", "111"),
    "F": ("111", "100", "110", "100", "100"),
    "G": ("011", "100", "101", "101", "011"),
    "H": ("101", "101", "111", "101", "101"),
    "I": ("111", "010", "010", "010", "111"),
    "J": ("001", "001", "001", "101", "010"),
    "K": ("101", "110", "100", "110", "101"),
    "L": ("100", "100", "100", "100", "111"),
    "M": ("101", "111", "111", "101", "101"),
    "N": ("101", "111", "111", "111", "101"),
    "O": ("010", "101", "101", "101", "010"),
    "P": ("110", "101", "110", "100", "100"),
    "Q": ("010", "101", "101", "110", "011"),
    "R": ("110", "101", "110", "110", "101"),
    "S": ("011", "100", "010", "001", "110"),
    "T": ("111", "010", "010", "010", "010"),
    "U": ("101", "101", "101", "101", "111"),
    "V": ("101", "101", "101", "101", "010"),
    "W": ("101", "101", "111", "111", "101"),
    "X": ("101", "101", "010", "101", "101"),
    "Y": ("101", "101", "010", "010", "010"),
    "Z": ("111", "001", "010", "100", "111"),
    " ": ("000", "000", "000", "000", "000"),
    "-": ("000", "000", "111", "000", "000"),
    "_": ("000", "000", "000", "000", "111"),
    ".": ("000", "000", "000", "000", "010"),
    ":": ("000", "010", "000", "010", "000"),
    "%": ("101", "001", "010", "100", "101"),
    "/": ("001", "001", "010", "100", "100"),
}

CELL = 8  # rendered glyph cell (8x8, reference-compatible density)


def _glyph_bitmap(ch: str) -> np.ndarray:
    rows = _GLYPHS.get(ch.upper())
    if rows is None:
        g = np.ones((5, 3), np.uint8)  # unknown → filled box
    else:
        g = np.array([[c == "1" for c in r] for r in rows], np.uint8)
    # upscale 3x5 → 6x5 horizontally padded to 8x8 cell with 1px margins
    up = np.repeat(g, 2, axis=1)            # (5, 6)
    cell = np.zeros((CELL, CELL), np.uint8)
    cell[1:6, 1:7] = up
    return cell


_CACHE: Dict[str, np.ndarray] = {}


def render_text(text: str) -> np.ndarray:
    """→ (8, 8*len(text)) uint8 {0,1} bitmap."""
    cells = []
    for ch in text:
        if ch not in _CACHE:
            _CACHE[ch] = _glyph_bitmap(ch)
        cells.append(_CACHE[ch])
    if not cells:
        return np.zeros((CELL, 0), np.uint8)
    return np.concatenate(cells, axis=1)


def blit_text(img: np.ndarray, text: str, x: int, y: int,
              color=(255, 255, 255, 255)) -> None:
    """Draw text onto an (H, W, C) uint8 image in place, clipped."""
    bm = render_text(text)
    h, w = bm.shape
    H, W = img.shape[:2]
    x0, y0 = max(0, x), max(0, y)
    x1, y1 = min(W, x + w), min(H, y + h)
    if x1 <= x0 or y1 <= y0:
        return
    sub = bm[y0 - y : y1 - y, x0 - x : x1 - x].astype(bool)
    img[y0:y1, x0:x1][sub] = np.array(color[: img.shape[2]], np.uint8)
