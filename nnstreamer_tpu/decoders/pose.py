"""pose_estimation decoder — keypoint heatmaps → skeleton overlay video.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-pose.c (845
LoC): heatmap argmax keypoint decode (+ optional offset refinement),
label/limb metadata, overlay output.

Options:
- option1 = "W:H" output video size (default 640:480)
- option2 = "W:H" model input size (default heatmap grid × stride 16)
- option3 = keypoint label file (optional)
- option4 = score threshold (default 0.3)

Input: (1, h, w, K) heatmaps [+ optional (1, h, w, 2K) offsets — the
zoo://posenet output pair]. Output: RGBA overlay; decoded keypoints in
meta["keypoints"] as (K, 3) [x_px, y_px, score].
"""

from __future__ import annotations


import numpy as np

from nnstreamer_tpu.decoders.font import blit_text
from nnstreamer_tpu.decoders.util import load_labels, parse_wh
from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import VideoSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

#: COCO-17 skeleton limb pairs (tensordec-pose.c default connection map)
LIMBS = (
    (5, 6), (5, 7), (7, 9), (6, 8), (8, 10), (5, 11), (6, 12), (11, 12),
    (11, 13), (13, 15), (12, 14), (14, 16), (0, 1), (0, 2), (1, 3), (2, 4),
)

_COLOR = np.array((64, 255, 64, 255), np.uint8)
_JOINT = np.array((255, 64, 64, 255), np.uint8)


@register_decoder("pose_estimation")
class PoseEstimation(DecoderSubplugin):
    def init(self, props: dict) -> None:
        self.out_w, self.out_h = parse_wh(props.get("option1", ""), 640, 480)
        # model input size: the pixel frame offsets are expressed in;
        # 0 = derive from the heatmap grid at output stride 16
        self.in_w, self.in_h = parse_wh(props.get("option2", ""), 0, 0)
        self.labels = load_labels(props.get("option3", ""), "pose_estimation")
        self.score_thresh = float(props.get("option4", "") or 0.3)

    def negotiate(self, in_spec: TensorsSpec) -> VideoSpec:
        if in_spec.num_tensors not in (1, 2):
            raise ValueError(
                f"expects heatmaps [+offsets], got {in_spec.num_tensors} "
                f"tensors")
        hm = in_spec.tensors[0]
        if len(hm.shape) != 4:
            raise ValueError(f"heatmap tensor must be (1, h, w, K); got {hm}")
        self._k = hm.shape[-1]
        if in_spec.num_tensors == 2:
            off = in_spec.tensors[1]
            if off.shape[-1] != 2 * self._k:
                raise ValueError(
                    f"offsets last dim {off.shape[-1]} != 2K={2 * self._k}")
        return VideoSpec(width=self.out_w, height=self.out_h, format="RGBA",
                         rate=in_spec.rate)

    # -- device decode (tensor_decoder device=true) ------------------------
    def device_negotiate(self, in_spec: TensorsSpec) -> TensorsSpec:
        self.negotiate(in_spec)   # validates, sets self._k
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo

        return TensorsSpec.of(
            TensorInfo((self._k, 3), DType.FLOAT32, name="keypoints"),
            rate=in_spec.rate)

    def device_decode(self, tensors, aux=None):
        import jax.numpy as jnp

        from nnstreamer_tpu.decoders.device import pose_decode_device

        kps = pose_decode_device(
            tensors[0], tensors[1] if len(tensors) > 1 else None,
            in_h=self.in_h, in_w=self.in_w)
        # host decoder emits [x_px, y_px, score]; match it
        scale = jnp.array([self.out_w, self.out_h, 1.0], jnp.float32)
        return (kps * scale,)

    def _keypoints(self, buf: TensorBuffer) -> np.ndarray:
        hm = np.asarray(buf.tensors[0])[0]          # (h, w, K)
        h, w, k = hm.shape
        offsets = (np.asarray(buf.tensors[1])[0]
                   if buf.num_tensors == 2 else None)
        flat = hm.reshape(-1, k)
        idx = flat.argmax(0)
        ys, xs = np.unravel_index(idx, (h, w))
        score = flat[idx, np.arange(k)]
        # map grid coords (+offset refinement) → [0,1] image space
        fy = (ys + 0.5) / h
        fx = (xs + 0.5) / w
        if offsets is not None:
            # offsets layout: [..., :K] = y-offset, [..., K:] = x-offset,
            # in MODEL-INPUT pixels (PoseNet short-range offsets). The
            # input frame is option2, or grid × stride-16 by default.
            in_h = self.in_h or h * 16
            in_w = self.in_w or w * 16
            oy = offsets[ys, xs, np.arange(k)]
            ox = offsets[ys, xs, k + np.arange(k)]
            fy = fy + oy / in_h
            fx = fx + ox / in_w
        return np.stack([fx * self.out_w, fy * self.out_h, score], axis=1)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        kps = self._keypoints(buf)
        img = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        ok = kps[:, 2] >= self.score_thresh
        for a, b in LIMBS:
            if a < len(kps) and b < len(kps) and ok[a] and ok[b]:
                self._line(img, kps[a, :2], kps[b, :2])
        for i, (x, y, s) in enumerate(kps):
            if not ok[i]:
                continue
            xi = int(np.clip(x, 1, self.out_w - 2))
            yi = int(np.clip(y, 1, self.out_h - 2))
            img[yi - 1:yi + 2, xi - 1:xi + 2] = _JOINT
            if self.labels and i < len(self.labels):
                blit_text(img, self.labels[i][:10], xi + 3, yi - 3, _JOINT)
        return buf.with_tensors((img,)).with_meta(keypoints=kps)

    def _line(self, img: np.ndarray, p0, p1) -> None:
        n = int(max(abs(p1[0] - p0[0]), abs(p1[1] - p0[1]), 1))
        xs = np.clip(np.linspace(p0[0], p1[0], n + 1), 0,
                     self.out_w - 1).astype(int)
        ys = np.clip(np.linspace(p0[1], p1[1], n + 1), 0,
                     self.out_h - 1).astype(int)
        img[ys, xs] = _COLOR
