"""Device-side decode programs — postprocessing that stays on the TPU.

TPU-first extension beyond the reference: its decoders run on host after
a full D2H of the raw model outputs (tensordec-boundingbox.c pulls every
anchor's loc+conf). On a tunneled/remote TPU host that transfer is the
entire pipeline bottleneck (measured: SSD at ~1.6 FPS with ~700 KB/frame
D2H vs thousands of device FPS). These functions run the decode as XLA
on device — top-K select, greedy NMS, heatmap refinement are all dense
tensor ops the MXU/VPU eat — so only the tiny result (e.g. 16×6 floats)
ever needs to cross to the host.

Used by `tensor_decoder device=true` (elements/decoder.py), which swaps
the media-overlay output for the compact result tensor.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def iou_matrix(boxes: jnp.ndarray) -> jnp.ndarray:
    """(N,4) [ymin,xmin,ymax,xmax] → (N,N) IoU (device twin of the host
    decoder's numpy version)."""
    area = jnp.maximum(0.0, boxes[:, 2] - boxes[:, 0]) * \
        jnp.maximum(0.0, boxes[:, 3] - boxes[:, 1])
    yx0 = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    yx1 = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(0.0, yx1 - yx0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def greedy_nms_mask(boxes: jnp.ndarray, iou_thresh: float) -> jnp.ndarray:
    """Exact greedy class-agnostic NMS over score-DESC-sorted boxes
    (N,4) → keep mask (N,). Sequential recurrence (fori_loop over IoU
    rows) — correct but ~N loop steps on device; prefer fast_nms_mask on
    the hot path."""
    n = boxes.shape[0]
    iou = iou_matrix(boxes)
    idx = jnp.arange(n)

    def body(i, keep):
        suppress = (iou[i] > iou_thresh) & (idx > i) & keep[i]
        return keep & ~suppress

    return lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def fast_nms_mask(boxes: jnp.ndarray, iou_thresh: float) -> jnp.ndarray:
    """Fast NMS (YOLACT): keep a box unless ANY higher-scored box
    overlaps it — one dense matrix op instead of a sequential loop, which
    is the MXU-friendly formulation (measured ~6 ms → ~0.1 ms for N=100
    on v5e). Slightly over-suppresses vs greedy when a mid-score box is
    itself suppressed by a higher one; negligible in practice (YOLACT
    §4.2) and irrelevant for sparse scenes."""
    n = boxes.shape[0]
    iou = iou_matrix(boxes)
    higher = jnp.arange(n)[:, None] < jnp.arange(n)[None, :]  # j<i pairs
    suppressed = jnp.any((iou > iou_thresh) & higher.T, axis=1)
    return ~suppressed


@partial(jax.jit, static_argnames=("top_k", "pre_nms", "score_thresh",
                                   "iou_thresh", "nms"))
def ssd_decode_device(loc, logits, anchors, *, score_thresh: float = 0.5,
                      iou_thresh: float = 0.5, top_k: int = 16,
                      pre_nms: int = 100, nms: str = "greedy"):
    """SSD postprocess on device: raw loc deltas + class logits →
    (top_k, 6) [ymin,xmin,ymax,xmax,score,class], zero-padded rows for
    missing detections. Matches the host mobilenet-ssd scheme: sigmoid
    scores, background class 0 skipped, class-agnostic NMS.

    nms="greedy" (default) is the exact host-parity recurrence —
    measured just as fast as "fast" at pre_nms=100 on v5e (~0.9 ms
    fused); "fast" (YOLACT matrix form) is available for much larger
    candidate counts where the sequential loop would dominate."""
    from nnstreamer_tpu.models.ssd_mobilenet import decode_boxes

    loc = loc.reshape(-1, 4).astype(jnp.float32)
    sc = logits.reshape(loc.shape[0], -1).astype(jnp.float32)
    sc = jax.nn.sigmoid(sc)
    cls = jnp.argmax(sc[:, 1:], axis=-1) + 1          # skip background
    score = jnp.take_along_axis(sc, cls[:, None], axis=1)[:, 0]
    boxes = decode_boxes(loc, anchors)

    # top-K preselect keeps NMS O(K²), K static
    k = min(pre_nms, score.shape[0])
    s_top, i_top = lax.top_k(score, k)
    b_top = boxes[i_top]
    c_top = cls[i_top].astype(jnp.float32)
    s_top = jnp.where(s_top >= score_thresh, s_top, 0.0)
    nms_fn = fast_nms_mask if nms == "fast" else greedy_nms_mask
    keep = nms_fn(b_top, iou_thresh)
    s_kept = jnp.where(keep, s_top, 0.0)
    out_k = min(top_k, k)
    s_fin, i_fin = lax.top_k(s_kept, out_k)
    det = jnp.concatenate(
        [b_top[i_fin], s_fin[:, None], c_top[i_fin][:, None]], axis=1)
    return jnp.where(s_fin[:, None] > 0, det, 0.0)    # (top_k, 6)


@partial(jax.jit, static_argnames=("in_h", "in_w"))
def pose_decode_device(heatmaps, offsets=None, *, in_h: int = 0,
                       in_w: int = 0):
    """PoseNet postprocess on device: heatmaps (1,h,w,K) [+ offsets
    (1,h,w,2K)] → (K, 3) [fx, fy, score] in [0,1] image space (caller
    scales to output pixels). Same math as the host decoder."""
    hm = heatmaps[0].astype(jnp.float32)              # (h, w, K)
    h, w, k = hm.shape
    flat = hm.reshape(-1, k)
    idx = jnp.argmax(flat, axis=0)                    # (K,)
    ys, xs = idx // w, idx % w
    score = jnp.take_along_axis(flat, idx[None, :], axis=0)[0]
    fy = (ys.astype(jnp.float32) + 0.5) / h
    fx = (xs.astype(jnp.float32) + 0.5) / w
    if offsets is not None:
        off = offsets[0].astype(jnp.float32)          # (h, w, 2K)
        ih = in_h or h * 16
        iw = in_w or w * 16
        kk = jnp.arange(k)
        oy = off[ys, xs, kk]
        ox = off[ys, xs, k + kk]
        fy = fy + oy / ih
        fx = fx + ox / iw
    return jnp.stack([fx, fy, score], axis=1)         # (K, 3)


@partial(jax.jit, static_argnames=("top_k",))
def ssd_compact_device(loc, logits, anchors, *, top_k: int = 100):
    """Top-K *compaction* (tensor_decoder device=compact): decode boxes
    and per-anchor best class/score on device, ship only the top_k
    candidate rows (K,6) [ymin,xmin,ymax,xmax,score,class] — NO
    threshold, NO NMS. The host bounding_boxes decoder then applies its
    exact reference semantics (score threshold, greedy NMS, RGBA
    overlay — tensordec-boundingbox.c:125-158) to the compact tensor
    instead of the raw anchor grids, cutting the per-frame D2H from
    ~700 KB to 2.4 KB while keeping host-decode parity (any detection
    the host path would keep has score above threshold and therefore
    ranks inside the top 100 candidates).
    """
    loc = loc.reshape(-1, 4).astype(jnp.float32)
    sc = logits.reshape(loc.shape[0], -1).astype(jnp.float32)
    # host parity: sigmoid only when the tensor looks like logits
    is_logits = jnp.logical_or(jnp.min(sc) < 0.0, jnp.max(sc) > 1.0)
    sc = jnp.where(is_logits, jax.nn.sigmoid(sc), sc)
    cls = jnp.argmax(sc[:, 1:], axis=-1) + 1          # skip background
    score = jnp.take_along_axis(sc, cls[:, None], axis=1)[:, 0]

    from nnstreamer_tpu.models.ssd_mobilenet import decode_boxes

    boxes = decode_boxes(loc, anchors)
    k = min(top_k, score.shape[0])
    s_top, i_top = lax.top_k(score, k)
    return jnp.concatenate(
        [boxes[i_top], s_top[:, None],
         cls[i_top].astype(jnp.float32)[:, None]], axis=1)    # (K, 6)
