"""octet_stream decoder — tensors → raw bytes.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-octetstream.c
(130 LoC): concatenates each tensor's bytes into application/octet-stream.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import OctetSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec


@register_decoder("octet_stream")
class OctetStream(DecoderSubplugin):
    def negotiate(self, in_spec: TensorsSpec) -> OctetSpec:
        return OctetSpec(rate=in_spec.rate)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        payload = b"".join(
            np.ascontiguousarray(np.asarray(t)).tobytes() for t in buf.tensors)
        return buf.with_tensors((np.frombuffer(payload, np.uint8).copy(),))
