"""image_segment decoder — per-pixel class tensors → color-mapped video.

Reference parity: ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c
(660 LoC): tflite-deeplab (float per-class scores, argmax) and snpe
(index map) variants, class→color LUT overlay.

Options:
- option1 = variant: tflite-deeplab | snpe-deeplab | index (raw class map)
- option2 = number of classes for the color LUT (default 21, Pascal VOC)

Output: RGBA video at the segmentation map's own resolution; class index
map rides meta["class_map"].
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import VideoSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

VARIANTS = ("tflite-deeplab", "snpe-deeplab", "index")


def _voc_palette(n: int) -> np.ndarray:
    """Pascal-VOC bit-twiddled color map (the canonical deeplab LUT)."""
    pal = np.zeros((n, 4), np.uint8)
    pal[:, 3] = 255
    for i in range(n):
        c, r, g, b = i, 0, 0, 0
        for j in range(8):
            r |= ((c >> 0) & 1) << (7 - j)
            g |= ((c >> 1) & 1) << (7 - j)
            b |= ((c >> 2) & 1) << (7 - j)
            c >>= 3
        pal[i, :3] = (r, g, b)
    pal[0] = (0, 0, 0, 0)  # background transparent
    return pal


@register_decoder("image_segment")
class ImageSegment(DecoderSubplugin):
    def init(self, props: dict) -> None:
        self.variant = props.get("option1", "") or "tflite-deeplab"
        if self.variant not in VARIANTS:
            raise PipelineError(
                f"image_segment: unknown variant {self.variant!r}; "
                f"supported: {', '.join(VARIANTS)}"
            )
        self.num_classes = int(props.get("option2", "") or 21)
        self._lut = _voc_palette(max(2, self.num_classes))

    def negotiate(self, in_spec: TensorsSpec) -> VideoSpec:
        if in_spec.num_tensors != 1:
            raise ValueError(f"expects one tensor, got {in_spec.num_tensors}")
        t = in_spec.tensors[0]
        shape = t.shape[1:] if len(t.shape) == 4 and t.shape[0] == 1 else t.shape
        if self.variant == "tflite-deeplab":
            if len(shape) != 3:
                raise ValueError(
                    f"tflite-deeplab needs (1, H, W, C) scores; got {t}")
            h, w, c = shape
            if c < 2:
                raise ValueError(f"need ≥2 classes, got {c}")
            self.num_classes = max(self.num_classes, c)
            self._lut = _voc_palette(self.num_classes)
        else:
            if len(shape) == 3 and shape[-1] == 1:
                shape = shape[:2]
            if len(shape) != 2:
                raise ValueError(
                    f"{self.variant} needs an (H, W) class-index map; got {t}")
            h, w = shape
        return VideoSpec(width=w, height=h, format="RGBA", rate=in_spec.rate)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        t = np.asarray(buf.tensors[0])
        if t.ndim == 4 and t.shape[0] == 1:
            t = t[0]
        if self.variant == "tflite-deeplab":
            class_map = t.argmax(-1).astype(np.int32)
        else:
            if t.ndim == 3 and t.shape[-1] == 1:
                t = t[..., 0]
            class_map = t.astype(np.int32)
        clipped = np.clip(class_map, 0, len(self._lut) - 1)
        img = self._lut[clipped]
        return buf.with_tensors((img,)).with_meta(class_map=class_map)

    # -- device decode (tensor_decoder device=true) ------------------------
    def device_negotiate(self, in_spec: TensorsSpec) -> TensorsSpec:
        if self.variant != "tflite-deeplab":
            raise PipelineError(
                f"image_segment device decode supports tflite-deeplab "
                f"(scores→argmax); {self.variant!r} is already an index "
                f"map, decode it on host")
        self.negotiate(in_spec)
        from nnstreamer_tpu.tensor.dtypes import DType
        from nnstreamer_tpu.tensor.info import TensorInfo

        t = in_spec.tensors[0]
        if t.shape[-1] > 256:
            raise PipelineError(
                f"device decode emits a uint8 class map but the model has "
                f"{t.shape[-1]} classes; use the host decoder (int32 map)")
        h, w = (t.shape[1:3] if len(t.shape) == 4 else t.shape[:2])
        return TensorsSpec.of(
            TensorInfo((h, w), DType.UINT8, name="class_map"),
            rate=in_spec.rate)

    def device_decode(self, tensors, aux=None):
        import jax.numpy as jnp

        t = tensors[0]
        if t.ndim == 4:
            t = t[0]
        # class count ≤ 255 by VOC-style palettes; uint8 map = 4× less
        # D2H than the int32 host map, and the overlay LUT stays host-side
        return (jnp.argmax(t, axis=-1).astype(jnp.uint8),)
