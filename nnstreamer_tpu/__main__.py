"""CLI — the gst-launch-1.0 / gst-inspect-1.0 parity surface.

    python -m nnstreamer_tpu 'videotestsrc num-buffers=16 ! tensor_converter \
        ! tensor_filter model=zoo://mobilenet_v2 ! tensor_sink'
    python -m nnstreamer_tpu --inspect                 # list elements
    python -m nnstreamer_tpu --inspect tensor_filter   # element detail
    python -m nnstreamer_tpu --models                  # list zoo models
    python -m nnstreamer_tpu --stats '...pipeline...'  # per-element stats
    python -m nnstreamer_tpu trace '...pipeline...'    # traced run: report
                                                       #  + Chrome trace JSON
    python -m nnstreamer_tpu trace --merge a.json b.json --out m.json
                                                       # merge traces onto
                                                       #  one timeline
    python -m nnstreamer_tpu serve --workers 2 --metrics-port 9100
                                                       # pool + /metrics
                                                       #  exposition endpoint
    python -m nnstreamer_tpu top http://127.0.0.1:9100/metrics
                                                       # live terminal view
                                                       #  over any /metrics
    python -m nnstreamer_tpu models list               # model store contents
    python -m nnstreamer_tpu models describe NAME      # versions/stats/swaps
    python -m nnstreamer_tpu models swap NAME [VER]    # hot swap
    python -m nnstreamer_tpu llm --requests 8          # continuous-batching
                                                       #  LLM serving demo
    python -m nnstreamer_tpu traffic --load-x 2        # open-loop overload
                                                       #  harness + SLO report
    python -m nnstreamer_tpu traffic --workers 2 --kill-at 1
                                                       # chaos-kill a pool
                                                       #  worker mid-flood
    python -m nnstreamer_tpu serve --workers 4         # supervised worker
                                                       #  pool (SIGTERM drains)
    python -m nnstreamer_tpu mesh --listen             # multi-host router
                                                       #  (pools join with
                                                       #  serve --join)
    python -m nnstreamer_tpu mesh --hosts 2            # partition-chaos
                                                       #  demo + SLO report
    python -m nnstreamer_tpu lint [--json]             # project static
                                                       #  analysis (nnlint)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _inspect(name: str | None) -> int:
    import nnstreamer_tpu.elements  # noqa: F401 (register built-ins)
    from nnstreamer_tpu.core.registry import PluginKind, registry

    if not name:
        print("elements:")
        for n in sorted(registry.names(PluginKind.ELEMENT)):
            cls = registry.get(PluginKind.ELEMENT, n)
            doc = (cls.__doc__ or "").strip().splitlines()
            print(f"  {n:24s} {doc[0] if doc else ''}")
        print("\ndecoder modes:")
        import nnstreamer_tpu.decoders  # noqa: F401

        for n in sorted(registry.names(PluginKind.DECODER)):
            print(f"  {n}")
        return 0
    cls = registry.get(PluginKind.ELEMENT, name)
    print(f"element {name} ({cls.__name__})")
    if cls.__doc__:
        print(cls.__doc__)
    print("properties:")
    for prop, pd in cls.PROPS.items():
        print(f"  {prop.replace('_', '-'):24s} default={pd.default!r}  {pd.doc}")
    return 0


def _models() -> int:
    from nnstreamer_tpu.models.zoo import list_models

    for m in list_models():
        print(f"zoo://{m}")
    return 0


def _trace_main(argv) -> int:
    """`trace` subcommand: run a pipeline with the tracer on, print the
    observability report, write a Chrome-trace JSON (Perfetto /
    chrome://tracing). The pipeline description needs no changes —
    tracing is a runner-level switch."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu trace",
        description="run a pipeline traced: element report + Chrome trace")
    ap.add_argument("pipeline", nargs="+",
                    help="pipeline description string (with --merge: two "
                         "or more Chrome-trace JSON files)")
    ap.add_argument("--out", default="trace.json", metavar="FILE",
                    help="Chrome-trace JSON output path (default trace.json)")
    ap.add_argument("--merge", action="store_true",
                    help="merge already-written trace JSONs onto one "
                         "timeline (distinct process track groups) "
                         "instead of running a pipeline")
    ap.add_argument("--timeout", type=float, default=None,
                    help="max run seconds")
    ap.add_argument("--no-optimize", action="store_true",
                    help="disable transform-into-filter fusion")
    args = ap.parse_args(argv)

    if args.merge:
        import os

        from nnstreamer_tpu.runtime.tracing import merge_chrome_traces

        docs = []
        for path in args.pipeline:
            with open(path) as f:
                docs.append(json.load(f))
        merged = merge_chrome_traces(
            docs, labels=[os.path.basename(p) for p in args.pipeline])
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"merged {len(docs)} trace(s) -> {args.out} "
              f"({len(merged['traceEvents'])} events)", file=sys.stderr)
        return 0
    if len(args.pipeline) != 1:
        print("trace takes one pipeline description (or --merge with "
              "trace files)", file=sys.stderr)
        return 2

    import nnstreamer_tpu as nns

    pipe = nns.parse_launch(args.pipeline[0])
    runner = nns.PipelineRunner(pipe, optimize=not args.no_optimize,
                                trace=True)
    interrupted = False
    try:
        runner.start()
        runner.wait(args.timeout)
    except KeyboardInterrupt:
        interrupted = True
        print("interrupted — writing partial trace", file=sys.stderr)
    finally:
        runner.stop()
    with open(args.out, "w") as f:
        json.dump(runner.tracer.to_chrome_trace(pipe.name), f)
    print(runner.report())
    print(f"chrome trace written to {args.out} "
          f"(load in Perfetto or chrome://tracing)", file=sys.stderr)
    return 130 if interrupted else 0


def _models_main(argv) -> int:
    """`models` subcommand: the model-store operator surface —
    list served names, describe one (versions/aliases/stats/swaps),
    trigger a hot swap."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu models",
        description="model store: list / describe NAME / swap NAME [VER]")
    sub = ap.add_subparsers(dest="cmd")
    sub.add_parser("list", help="list store models (zoo builtins seed @0)")
    p_desc = sub.add_parser("describe", help="versions, aliases, stats")
    p_desc.add_argument("name")
    p_swap = sub.add_parser("swap",
                            help="hot-swap NAME to VERSION (default latest)")
    p_swap.add_argument("name")
    p_swap.add_argument("version", nargs="?", default=None)
    p_swap.add_argument("--no-prewarm", action="store_true",
                        help="skip pre-warming attached backends (the hot "
                             "path then recompiles on first post-swap use)")
    args = ap.parse_args(argv)

    from nnstreamer_tpu.models.zoo import list_models
    from nnstreamer_tpu.serving.store import get_store

    store = get_store()
    if args.cmd in (None, "list"):
        seeded = set(store.names())
        for m in sorted(seeded | set(list_models())):
            e = store.entry(m)
            cur, epoch = e.state
            print(f"store://{m}  current=@{cur} epoch={epoch} "
                  f"versions={sorted(e.versions)}")
        return 0
    if args.cmd == "describe":
        print(json.dumps(store.describe(args.name), indent=2,
                         default=float))
        return 0
    report = store.update(args.name, args.version,
                          prewarm=not args.no_prewarm)
    print(json.dumps(report, indent=2, default=float))
    return 0


def _llm_main(argv) -> int:
    """`llm` subcommand: push N synthetic prompts through an
    appsrc → tensor_llm → tensor_sink pipeline and stream tokens as
    they arrive — the smallest end-to-end serving loop."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu llm",
        description="continuous-batching LLM serving demo (tensor_llm)")
    ap.add_argument("--model", default="store://transformer",
                    help="store:// ref or zoo name")
    ap.add_argument("--requests", type=int, default=8,
                    help="synthetic prompts to serve")
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--num-blocks", type=int, default=64)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--scheduling", default="continuous",
                    choices=("continuous", "static"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--stats", action="store_true",
                    help="print engine stats JSON at the end")
    args = ap.parse_args(argv)

    import numpy as np

    import nnstreamer_tpu as nns
    from nnstreamer_tpu.elements import AppSrc, TensorLLM, TensorSink
    from nnstreamer_tpu.tensor.buffer import TensorBuffer
    from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec

    src = AppSrc(name="src", spec=TensorsSpec(
        tensors=(), format=TensorFormat.FLEXIBLE))
    llm = TensorLLM(
        name="llm", model=args.model, max_batch=args.max_batch,
        num_blocks=args.num_blocks, block_size=args.block_size,
        max_len=args.max_len, max_new_tokens=args.max_new_tokens,
        temperature=args.temperature, scheduling=args.scheduling)

    def on_chunk(buf):
        m = buf.meta["llm"]
        toks = " ".join(str(int(t)) for t in np.asarray(buf.tensors[0]))
        tail = ""
        if m["done"]:
            ft = m.get("first_token_ms")
            tail = (f"   [done: {m['n_tokens']} tokens, "
                    f"first token {ft:.1f} ms]" if ft is not None
                    else "   [done]")
        print(f"{m['request_id']:>8s}  {toks}{tail}")

    sink = TensorSink(name="sink", new_data=on_chunk)
    pipe = nns.Pipeline()
    for e in (src, llm, sink):
        pipe.add(e)
    pipe.link(src, llm)
    pipe.link(llm, sink)
    runner = nns.PipelineRunner(pipe)
    runner.start()
    rng = np.random.default_rng(args.seed)
    vocab = 256
    try:
        for i in range(args.requests):
            plen = int(rng.integers(1, 17))
            prompt = rng.integers(0, vocab, size=plen).astype(np.int32)
            src.push(TensorBuffer(
                tensors=(prompt,), pts=i,
                meta={"llm": {"request_id": f"req{i}",
                              "seed": int(args.seed) + i}}))
        src.end()
        runner.wait(None)
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
        return 130
    finally:
        runner.stop()
    if args.stats:
        print(json.dumps(llm.extra_stats(), indent=2, default=float))
    return 0


def _serve_main(argv) -> int:
    """`serve` subcommand: run a supervised multi-process worker pool
    behind a query server until SIGTERM/SIGINT, then drain gracefully
    (serving/pool.py, docs/robustness.md). Each worker runs one copy of
    --pipeline (a mid-pipeline description, e.g. 'tensor_filter
    framework=xla model=store://m'); without --pipeline the workers
    echo after --service-ms, which gives a known-capacity pool for
    drills and demos."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu serve",
        description="supervised multi-process serving pool "
                    "(docs/robustness.md)")
    ap.add_argument("--workers", type=int, default=2,
                    help="worker processes (pipeline copies)")
    ap.add_argument("--pipeline", default=None,
                    help="mid-pipeline each worker runs between appsrc "
                         "and tensor_sink (default: echo)")
    ap.add_argument("--dims", default="8:1",
                    help="accepted input dims (HELLO contract)")
    ap.add_argument("--types", default="float32")
    ap.add_argument("--service-ms", type=float, default=5.0,
                    help="echo mode per-frame service time")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 picks a free port (printed at startup)")
    ap.add_argument("--id", type=int, default=0, help="server pair id")
    ap.add_argument("--max-pending", type=int, default=64)
    ap.add_argument("--max-inflight", type=int, default=0)
    ap.add_argument("--shed-policy", default="reject-newest",
                    choices=("reject-newest", "reject-oldest",
                             "deadline-drop"))
    ap.add_argument("--tenants", default=None, metavar="FILE",
                    help="tenant table JSON (docs/multitenant.md): "
                         "weighted-fair admission per tenant class; "
                         "when classes bind models, workers run in "
                         "multiplex mode and route tenant->model")
    ap.add_argument("--resident-models", type=int, default=0,
                    help="multiplex mode: max models holding live "
                         "compiled entries per worker (LRU eviction; "
                         "0 = unbounded)")
    ap.add_argument("--slo", default=None, metavar="FILE",
                    help="SLO spec JSON (docs/autotune.md): runs the "
                         "closed-loop autotuner against this pool's "
                         "admission queue, defending the declared p99 "
                         "budget by re-deriving max_pending from the "
                         "measured reply rate")
    ap.add_argument("--autotune-dry-run", action="store_true",
                    help="with --slo: record every decision (audit "
                         "ring, metrics, tracer) without actuating "
                         "any knob")
    ap.add_argument("--stats-every", type=float, default=0.0,
                    help="print pool stats JSON every N seconds")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve Prometheus text exposition on "
                         "http://HOST:PORT/metrics (0 picks a free "
                         "port; also turns on the pool tracer)")
    ap.add_argument("--metrics-host", default="127.0.0.1")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="write the merged multi-process Chrome trace "
                         "here at drain (also turns on the pool tracer)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="arm the SLO-breach flight recorder "
                         "(docs/observability.md): forensic bundles "
                         "dumped into DIR on SLO breach / conservation "
                         "mismatch / worker fence / watchdog (also "
                         "turns on the pool tracer + device profiler)")
    ap.add_argument("--join", default=None, metavar="HOST:PORT",
                    help="register this pool as a host of a mesh "
                         "router (python -m nnstreamer_tpu mesh "
                         "--listen); the pool keeps serving its own "
                         "port too")
    ap.add_argument("--join-name", default=None,
                    help="host name advertised to the router "
                         "(default host:port of this pool)")
    ap.add_argument("--zone", default="",
                    help="locality zone advertised to the router")
    args = ap.parse_args(argv)

    from nnstreamer_tpu.serving.pool import PooledQueryServer
    from nnstreamer_tpu.serving.worker import WorkerSpec

    tracer = None
    if args.metrics_port is not None or args.trace_out or args.flight_dir:
        from nnstreamer_tpu.runtime.tracing import Tracer

        tracer = Tracer()
    prof = None
    if args.metrics_port is not None or args.flight_dir:
        from nnstreamer_tpu.runtime import devprof

        prof = devprof.get().enable()
    table = None
    if args.tenants:
        from nnstreamer_tpu.serving.tenancy import TenantTable

        table = TenantTable.from_json(args.tenants)
    if table is not None and table.models():
        spec = WorkerSpec(kind="multiplex", dims=args.dims,
                          types=args.types, tenants=table.to_dict(),
                          resident_models=args.resident_models)
    elif args.pipeline:
        spec = WorkerSpec(kind="pipeline", pipeline=args.pipeline,
                          dims=args.dims, types=args.types)
    else:
        spec = WorkerSpec(kind="echo", service_ms=args.service_ms,
                          dims=args.dims, types=args.types)
    pqs = PooledQueryServer(
        spec, workers=args.workers, sid=args.id, host=args.host,
        port=args.port, max_pending=args.max_pending,
        max_inflight=args.max_inflight, shed_policy=args.shed_policy,
        tenants=table, tracer=tracer)
    pqs.install_signal_handlers()
    tuner = None
    if args.slo:
        from nnstreamer_tpu.serving.autotune import AutoTuner, SLOSpec

        def _shrink_victims(victims):
            # entries shed by a live max_pending shrink: each is owed
            # a BUSY, same contract as every other admission victim
            for v in victims:
                try:
                    pqs.qs.send_busy(v.meta.get("client_id"), v.pts,
                                     "bound_shrink")
                except Exception:
                    pass

        tuner = AutoTuner(
            SLOSpec.from_json(args.slo), admission=pqs.qs.frames,
            tracer=tracer, dry_run=args.autotune_dry_run,
            on_victims=_shrink_victims).start()
        print(f"slo autotuner active "
              f"(dry_run={bool(args.autotune_dry_run)})",
              file=sys.stderr)
    def collect():
        from nnstreamer_tpu.serving.metrics import metrics_snapshot

        s = pqs.stats()
        return metrics_snapshot(
            tracer=tracer, admission=s.pop("admission"), pool=s,
            autotune=tuner.stats() if tuner is not None else None,
            devprof=prof.stats() if prof is not None else None)

    msrv = None
    if args.metrics_port is not None:
        from nnstreamer_tpu.serving.metrics import MetricsServer

        msrv = MetricsServer(collect, host=args.metrics_host,
                             port=args.metrics_port,
                             health=lambda: {"pool": pqs.stats()["pool"]})
        print(f"metrics on http://{args.metrics_host}:{msrv.port}"
              f"/metrics", file=sys.stderr)
    flight = None
    if args.flight_dir:
        from nnstreamer_tpu.runtime.flightrec import FlightRecorder
        from nnstreamer_tpu.serving.metrics import render_prometheus

        def _flight_env():
            return {"cmd": "serve", "argv": list(argv),
                    "workers": args.workers, "port": pqs.port,
                    "devprof": prof.stats() if prof is not None else None}

        flight = FlightRecorder(args.flight_dir).attach(
            tracer=tracer, autotune=tuner,
            prom=lambda: render_prometheus(collect()),
            env=_flight_env)
        flight.run_background(
            lambda: {"admission": pqs.stats().get("admission")})
        print(f"flight recorder armed -> {args.flight_dir}",
              file=sys.stderr)
    agent = None
    if args.join:
        from nnstreamer_tpu.serving.mesh import pool_join

        rhost, _, rport = args.join.rpartition(":")
        agent = pool_join(
            pqs, rhost or "127.0.0.1", int(rport),
            name=args.join_name or f"{args.host}:{pqs.port}",
            zone=args.zone)
        print(f"joined mesh router {args.join} as "
              f"{agent.name!r}", file=sys.stderr)
    print(f"pool serving on {args.host}:{pqs.port} "
          f"({args.workers} worker(s); SIGTERM/^C drains)",
          file=sys.stderr)
    last_stats = time.monotonic()
    try:
        while not pqs.pool.closed:
            time.sleep(0.2)
            if args.stats_every and \
                    time.monotonic() - last_stats >= args.stats_every:
                last_stats = time.monotonic()
                print(json.dumps(pqs.stats(), default=float),
                      file=sys.stderr)
    except KeyboardInterrupt:
        pass
    finally:
        if tuner is not None:
            tuner.stop()
        if flight is not None:
            flight.close()
        if agent is not None:
            agent.stop()
        pqs.close()
        if msrv is not None:
            msrv.close()
        if args.trace_out and tracer is not None:
            with open(args.trace_out, "w") as f:
                json.dump(tracer.to_chrome_trace("serve"), f)
            print(f"chrome trace written to {args.trace_out}",
                  file=sys.stderr)
    return 0


def _mesh_main(argv) -> int:
    """`mesh` subcommand. Two modes:

    --listen: run a MeshRouter until ^C — clients dial it like any
    query server; pools join with `serve --join HOST:PORT`.

    default (demo): the chaos acceptance drill from docs/robustness.md —
    spin up N local pool hosts behind one router, flood it open-loop
    above aggregate capacity while one host is blackholed mid-flood,
    and print the SLO + conservation report. Exit 0 iff nothing was
    lost and the per-host counters conserve."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu mesh",
        description="multi-host serving mesh: router (--listen) or "
                    "partition-chaos demo (docs/robustness.md)")
    ap.add_argument("--listen", action="store_true",
                    help="run a router until ^C instead of the demo")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="router port (0 picks a free one, printed)")
    ap.add_argument("--id", type=int, default=0, help="server pair id")
    ap.add_argument("--dims", default="8:1",
                    help="accepted input dims (HELLO contract)")
    ap.add_argument("--types", default="float32")
    ap.add_argument("--max-pending", type=int, default=64)
    ap.add_argument("--shed-policy", default="reject-newest",
                    choices=("reject-newest", "reject-oldest",
                             "deadline-drop"))
    ap.add_argument("--lease-s", type=float, default=2.0,
                    help="host lease: silent for this long => fenced")
    ap.add_argument("--max-redeliver", type=int, default=1,
                    help="cross-host re-offers per frame after a fence")
    ap.add_argument("--zone", default="",
                    help="router zone (locality-aware routing)")
    ap.add_argument("--stats-every", type=float, default=0.0,
                    help="--listen: print router stats JSON every N s")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="--listen: Prometheus exposition with per-host "
                         "series on http://HOST:PORT/metrics")
    # demo mode
    ap.add_argument("--hosts", type=int, default=2,
                    help="demo: local pool hosts to spin up")
    ap.add_argument("--workers-per-host", type=int, default=1)
    ap.add_argument("--pattern", default="poisson",
                    choices=("poisson", "bursty"))
    ap.add_argument("--load-x", type=float, default=1.5,
                    help="demo: offered load vs aggregate capacity")
    ap.add_argument("--requests", type=int, default=300)
    ap.add_argument("--service-ms", type=float, default=20.0)
    ap.add_argument("--blackhole-at", type=float, default=None,
                    help="demo: partition one host at t seconds "
                         "(default: the median arrival)")
    ap.add_argument("--heal-after", type=float, default=None,
                    help="demo: heal the partition after N more "
                         "seconds and wait for the host to rejoin")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget-ms", type=float, default=250.0)
    ap.add_argument("--json", action="store_true",
                    help="print the raw report JSON only")
    args = ap.parse_args(argv)

    if args.listen:
        from nnstreamer_tpu.serving.mesh import MeshRouter

        router = MeshRouter(
            host=args.host, port=args.port, sid=args.id,
            dims=args.dims, types=args.types,
            max_pending=args.max_pending, shed_policy=args.shed_policy,
            lease_s=args.lease_s, max_redeliver=args.max_redeliver,
            zone=args.zone)
        msrv = None
        if args.metrics_port is not None:
            from nnstreamer_tpu.serving.metrics import (
                MetricsServer, metrics_snapshot)

            def collect():
                s = router.stats()
                return metrics_snapshot(admission=s.get("admission"),
                                        mesh=s)

            msrv = MetricsServer(collect, host=args.host,
                                 port=args.metrics_port,
                                 health=lambda: router.stats()["mesh"])
            print(f"metrics on http://{args.host}:{msrv.port}/metrics",
                  file=sys.stderr)
        print(f"mesh router on {args.host}:{router.port} "
              f"(lease {args.lease_s}s; join pools with: python -m "
              f"nnstreamer_tpu serve --join {args.host}:{router.port}; "
              f"^C stops)", file=sys.stderr)
        last = time.monotonic()
        try:
            while True:
                time.sleep(0.2)
                if args.stats_every and \
                        time.monotonic() - last >= args.stats_every:
                    last = time.monotonic()
                    print(json.dumps(router.stats(), default=float),
                          file=sys.stderr)
        except KeyboardInterrupt:
            pass
        finally:
            router.close()
            if msrv is not None:
                msrv.close()
        return 0

    from nnstreamer_tpu.traffic import run_against_mesh

    report = run_against_mesh(
        hosts=args.hosts, workers_per_host=args.workers_per_host,
        pattern=args.pattern, load_x=args.load_x, n=args.requests,
        service_ms=args.service_ms, max_pending=args.max_pending,
        p99_budget_ms=args.budget_ms, seed=args.seed,
        lease_s=args.lease_s, max_redeliver=args.max_redeliver,
        blackhole_at_s=args.blackhole_at, heal_after_s=args.heal_after)
    if args.json:
        print(json.dumps(report, default=float))
    else:
        report.pop("queue_depth_timeline", None)
        print(json.dumps(report, indent=2, default=float))
        ex = (report.get("redelivered_examples") or [None])[0]
        if ex:
            print(f"cross-host redelivery: pts={ex['pts']} "
                  f"trace={ex['trace_id']} hosts={ex['hosts']}",
                  file=sys.stderr)
    ok = report.get("lost", 1) == 0 and report.get("conserved", False)
    return 0 if ok else 1


def _top_main(argv) -> int:
    """`top` subcommand: live terminal view over any /metrics
    exposition endpoint (serving/metrics.py) — counters as rates,
    gauges as current values, refreshed in place."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu top",
        description="live terminal view over a /metrics endpoint")
    ap.add_argument("url", nargs="?", default=None,
                    help="endpoint URL (or use --port for localhost)")
    ap.add_argument("--port", type=int, default=None,
                    help="shorthand for http://127.0.0.1:PORT/metrics")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh seconds")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = until ^C)")
    args = ap.parse_args(argv)

    url = args.url
    if url is None and args.port is not None:
        url = f"http://127.0.0.1:{args.port}/metrics"
    if url is None:
        print("top needs a URL or --port", file=sys.stderr)
        return 2

    from nnstreamer_tpu.serving.metrics import top_view

    try:
        top_view(url, interval_s=args.interval,
                 iterations=args.iterations)
    except KeyboardInterrupt:
        pass
    return 0


def _traffic_main(argv) -> int:
    """`traffic` subcommand: open-loop load against a bounded query
    server (a self-contained echo server by default, or --host/--port
    for a live one) and print the latency-SLO report."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu traffic",
        description="open-loop traffic harness: Poisson/bursty load, "
                    "admission-control SLO report (docs/traffic.md)")
    ap.add_argument("--pattern", default="poisson",
                    choices=("poisson", "bursty"))
    ap.add_argument("--load-x", type=float, default=2.0,
                    help="offered load as a multiple of server capacity "
                         "(self-contained mode; default 2.0)")
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--service-ms", type=float, default=5.0,
                    help="echo server's per-frame service time")
    ap.add_argument("--max-pending", type=int, default=16,
                    help="server admission queue bound")
    ap.add_argument("--max-inflight", type=int, default=0)
    ap.add_argument("--shed-policy", default="reject-newest",
                    choices=("reject-newest", "reject-oldest",
                             "deadline-drop"))
    ap.add_argument("--budget-ms", type=float, default=None,
                    help="p99 latency budget for goodput (default: a "
                         "full queue's wait + one service time)")
    ap.add_argument("--host", default=None,
                    help="attack a LIVE server instead (with --port, "
                         "--dims; --rate becomes absolute rps)")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--dims", default="8:1")
    ap.add_argument("--types", default="float32")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="absolute offered rps in --host mode")
    ap.add_argument("--seed", type=int, default=0,
                    help="rng seed for the arrival process AND the "
                         "chaos-kill schedule (reproducible runs; the "
                         "report records it)")
    ap.add_argument("--workers", type=int, default=0,
                    help="serve from a supervised worker POOL of N "
                         "processes instead of the in-process echo "
                         "server (enables --kill-at chaos mode)")
    ap.add_argument("--kill-at", type=float, default=None,
                    help="SIGKILL one rng-chosen pool worker at t "
                         "seconds into the send window (default: the "
                         "median arrival; needs --workers)")
    ap.add_argument("--kills", type=int, default=1,
                    help="number of staggered worker kills (--workers)")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="multi-tenant mode: N equal-weight tenant "
                         "classes behind a weighted-fair admission "
                         "front over a worker pool; tenant t0 floods "
                         "at --flood x its fair share, the others "
                         "offer 0.5x theirs; report gains per-tenant "
                         "groups + per-class conservation")
    ap.add_argument("--flood", type=float, default=3.0, metavar="K",
                    help="flooding tenant's offered load as a "
                         "multiple of its fair share (--tenants)")
    ap.add_argument("--autotune", action="store_true",
                    help="SLO-autotuner drill: open-loop ramp "
                         "0.5→2.5x capacity against a deliberately "
                         "mis-set bounded server, closed-loop tuned "
                         "vs the same static config on the same trace "
                         "(docs/autotune.md)")
    ap.add_argument("--autotune-dry-run", action="store_true",
                    help="with --autotune: the controller records "
                         "every decision without actuating any knob")
    ap.add_argument("--json", action="store_true",
                    help="print the raw report JSON only")
    ap.add_argument("--trace", action="store_true",
                    help="give every request a trace context and print "
                         "the per-hop latency decomposition of the "
                         "worst-p99 request")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="with --workers: run the pool traced and "
                         "write the merged multi-process Chrome trace "
                         "here (implies --trace)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="post-run forensic scan: if the drill "
                         "breached its p99 budget or broke admission "
                         "conservation, dump a flight bundle into DIR "
                         "(docs/observability.md)")
    args = ap.parse_args(argv)

    import numpy as np

    from nnstreamer_tpu.traffic import (
        bursty_arrivals, poisson_arrivals, run_against_echo,
        run_against_pool, run_open_loop)

    if args.trace_out:
        args.trace = True
    if args.autotune:
        from nnstreamer_tpu.traffic import run_autotune_ramp

        kw = dict(n_per_step=max(20, args.requests // 5),
                  service_ms=args.service_ms,
                  p99_budget_ms=args.budget_ms, seed=args.seed)
        static = run_autotune_ramp(tuned=False, **kw)
        tuned = run_autotune_ramp(
            tuned=True, dry_run=args.autotune_dry_run, **kw)
        report = {"static": static, "tuned": tuned,
                  "goodput_gain_rps": round(
                      tuned["goodput_rps"] - static["goodput_rps"], 2)}
        if args.json:
            print(json.dumps(report, default=float))
        else:
            for r in (static, tuned):
                r.pop("queue_depth_timeline", None)
            print(json.dumps(report, indent=2, default=float))
        ok = (static["lost"] == 0 and tuned["lost"] == 0
              and tuned["conservation_final"]
              and all(tuned.get("conservation_after_apply") or [True]))
        return 0 if ok else 1
    if args.tenants > 0:
        from nnstreamer_tpu.traffic import run_multitenant

        if args.tenants < 2:
            print("--tenants needs N >= 2", file=sys.stderr)
            return 2
        workers = args.workers or 2
        capacity = workers * 1e3 / args.service_ms
        fair = capacity / args.tenants
        names = [f"t{k}" for k in range(args.tenants)]
        budget = args.budget_ms or \
            (args.max_pending + 2) * args.service_ms
        rate_hz = {nm: (args.flood if k == 0 else 0.5) * fair
                   for k, nm in enumerate(names)}
        per = max(1, args.requests // args.tenants)
        n_per = {nm: max(1, int(round(per * rate_hz[nm] / fair)))
                 for nm in names}
        report = run_multitenant(
            tenants={nm: {"weight": 1.0, "deadline_ms": budget}
                     for nm in names},
            n_per_tenant=n_per, rate_hz=rate_hz,
            workers=workers, service_ms=args.service_ms,
            max_pending=args.max_pending,
            shed_policy=args.shed_policy
            if args.shed_policy != "reject-newest" else "reject-oldest",
            p99_budget_ms=budget, seed=args.seed)
        if args.json:
            print(json.dumps(report, default=float))
        else:
            report.pop("queue_depth_timeline", None)
            print(json.dumps(report, indent=2, default=float))
        ok = report["lost"] == 0 and report["conserved"]
        return 0 if ok else 1
    if args.workers > 0:
        tracer = None
        pool_kw = {}
        if args.trace_out:
            from nnstreamer_tpu.runtime.tracing import Tracer

            tracer = Tracer()
            pool_kw["tracer"] = tracer
        report = run_against_pool(
            pattern=args.pattern, load_x=args.load_x, n=args.requests,
            service_ms=args.service_ms, workers=args.workers,
            max_pending=args.max_pending, max_inflight=args.max_inflight,
            shed_policy=args.shed_policy,
            p99_budget_ms=args.budget_ms or 90.0, seed=args.seed,
            kill_at_s=args.kill_at, kills=args.kills,
            trace=args.trace, **pool_kw)
        if tracer is not None:
            with open(args.trace_out, "w") as f:
                json.dump(tracer.to_chrome_trace("traffic"), f)
            print(f"chrome trace written to {args.trace_out} "
                  f"(load in Perfetto or chrome://tracing)",
                  file=sys.stderr)
    elif args.host is not None:
        if args.port is None:
            print("--host needs --port", file=sys.stderr)
            return 2
        from nnstreamer_tpu.tensor.buffer import TensorBuffer
        from nnstreamer_tpu.tensor.info import TensorsSpec

        rng = np.random.default_rng(args.seed)
        if args.pattern == "poisson":
            arrivals = poisson_arrivals(args.rate, args.requests, rng)
        else:
            arrivals = bursty_arrivals(
                args.requests, rate_high_hz=2 * args.rate,
                rate_low_hz=max(args.rate / 4, 0.5), rng=rng)
        spec = TensorsSpec.from_strings(args.dims, args.types)
        x = np.zeros(spec.tensors[0].shape, spec.tensors[0].dtype.np_dtype)
        report = run_open_loop(
            args.host, args.port, dims=args.dims, types=args.types,
            arrivals=arrivals,
            make_frame=lambda i: TensorBuffer.of(x, pts=i),
            p99_budget_ms=args.budget_ms or 250.0, trace=args.trace)
        report["seed"] = args.seed
    else:
        report = run_against_echo(
            pattern=args.pattern, load_x=args.load_x, n=args.requests,
            service_ms=args.service_ms, max_pending=args.max_pending,
            max_inflight=args.max_inflight, shed_policy=args.shed_policy,
            p99_budget_ms=args.budget_ms, seed=args.seed,
            trace=args.trace)
    if args.flight_dir:
        from nnstreamer_tpu.runtime.flightrec import FlightRecorder

        rec = FlightRecorder(args.flight_dir)
        rec.attach(env=lambda: {"cmd": "traffic", "report": report})
        rec.tick({"report_summary": {
            k: report.get(k) for k in ("goodput_rps", "lost",
                                       "conserved", "p99_budget_ms")}})
        lat = report.get("latency_ms") or {}
        sig = {"p99_ms": lat.get("p99"),
               "p99_budget_ms": report.get("p99_budget_ms"),
               "admission": report.get("admission")}
        # two scans: the conservation predicate needs two consecutive
        # mismatched reads before it trusts a final, settled ledger
        fired = rec.scan(**sig)
        fired += [k for k in rec.scan(**sig) if k not in fired]
        for kind in fired:
            print(f"flight bundle dumped ({kind}) -> {args.flight_dir}",
                  file=sys.stderr)
    if args.json:
        print(json.dumps(report, default=float))
        return 0
    tl = report.pop("queue_depth_timeline", None)
    print(json.dumps(report, indent=2, default=float))
    hb = report.get("hop_breakdown")
    if hb:
        spans = hb.get("spans", {})
        stages = [(k.replace("_ms", "").replace("_", " "), spans[k])
                  for k in ("admission_wait_ms", "route_ms",
                            "worker_queue_ms", "service_ms", "reply_ms")
                  if spans.get(k) is not None]
        parts = " + ".join(f"{name} {v:.2f}ms" for name, v in stages)
        print(f"worst-p99 request (pts={hb['pts']}, "
              f"trace={hb.get('trace_id')}): {hb['latency_ms']:.2f}ms"
              + (f" = {parts}" if parts else "")
              + (f" (+{spans['retries']} retry)"
                 if spans.get("retries") else "")
              + (f" (+{spans['redeliveries']} redelivery)"
                 if spans.get("redeliveries") else ""),
              file=sys.stderr)
    if tl:
        # crude depth-over-time sparkline so overload is visible at a
        # glance without loading the JSON anywhere
        peak = max(d for _, d in tl) or 1
        blocks = " ▁▂▃▄▅▆▇█"
        line = "".join(blocks[min(8, round(8 * d / peak))] for _, d in tl)
        print(f"queue depth (peak {peak}): |{line}|", file=sys.stderr)
    lost = report.get("lost", 0)
    return 0 if lost == 0 else 1


def _flight_main(argv) -> int:
    """`flight` subcommand: list / inspect the forensic bundles a
    flight recorder (runtime/flightrec.py) dumped into a directory."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu flight",
        description="inspect SLO-breach flight-recorder bundles "
                    "(docs/observability.md)")
    ap.add_argument("dir", help="flight directory (serve --flight-dir)")
    ap.add_argument("--inspect", default=None, metavar="NAME",
                    help="print one bundle's parsed artifacts "
                         "(bundle dir name, e.g. flight-0001-slo_breach)")
    ap.add_argument("--json", action="store_true",
                    help="print raw JSON instead of the table")
    args = ap.parse_args(argv)

    from nnstreamer_tpu.runtime.flightrec import list_bundles, load_bundle

    if args.inspect:
        bundle = load_bundle(os.path.join(args.dir, args.inspect))
        # scenario_violation bundles carry the failing spec in the
        # cause — surface the repro recipe before the raw dump
        cause = (bundle.get("cause") or {}).get("cause") or {}
        spec = cause.get("scenario_spec")
        if spec and not args.json:
            print(f"scenario {cause.get('scenario')!r} "
                  f"seed={cause.get('seed')} — "
                  f"{len(cause.get('violations') or [])} violation(s); "
                  f"replay: python -m nnstreamer_tpu scenario run "
                  f"SPEC.json (spec below in cause.scenario_spec)",
                  file=sys.stderr)
        print(json.dumps(bundle, indent=None if args.json else 2,
                         default=str))
        return 0
    bundles = list_bundles(args.dir)
    if args.json:
        print(json.dumps(bundles, default=str))
        return 0
    if not bundles:
        print(f"no flight bundles in {args.dir}", file=sys.stderr)
        return 1
    print(f"{'bundle':<36} {'kind':<16} {'when':<20} cause")
    print("-" * 100)
    for b in bundles:
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(b.get("wall_time") or 0))
        cause = json.dumps(b.get("cause") or {}, default=str)
        if len(cause) > 40:
            cause = cause[:37] + "..."
        print(f"{b['name']:<36} {str(b.get('kind')):<16} {when:<20} "
              f"{cause}")
    return 0


def _scenario_load(ref: str, seed=None):
    """Resolve `ref` to a ScenarioSpec: a builtin catalog name, a spec
    JSON file, or a saved `scenario run` result JSON (spec embedded)."""
    from nnstreamer_tpu.scenario import ScenarioSpec, builtin_specs

    specs = builtin_specs()
    if ref in specs:
        spec = specs[ref]
    else:
        with open(ref, "r", encoding="utf-8") as f:
            d = json.load(f)
        if isinstance(d.get("spec"), dict):   # a saved result
            d = d["spec"]
        spec = ScenarioSpec.from_dict(d)
    if seed is not None:
        import dataclasses

        spec = dataclasses.replace(spec, seed=int(seed))
    return spec


def _scenario_emit(result: dict, out, full: bool) -> None:
    """Print a result (stdout or --out FILE); per-reply trace contexts
    are dropped unless --full — they dwarf the ledger."""
    slim = dict(result)
    if not full and isinstance(slim.get("report"), dict):
        slim["report"] = {k: v for k, v in slim["report"].items()
                          if k != "traces"}
    text = json.dumps(slim, indent=2, default=str)
    if out:
        with open(out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    else:
        print(text)


def _scenario_main(argv) -> int:
    """`scenario` subcommand: run / replay / shrink / list seeded
    adversarial world drills (docs/scenarios.md)."""
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu scenario",
        description="composable seeded scenario drills: declarative "
                    "arrival+fault programs against a real worker pool "
                    "or mesh, one property checker, deterministic "
                    "replay and shrinking (docs/scenarios.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="run a scenario; exit 0 iff all "
                                      "invariants hold")
    runp.add_argument("spec", help="builtin name (see `scenario list`) "
                                   "or spec/result JSON file")
    runp.add_argument("--seed", type=int, default=None,
                      help="override the root seed")
    runp.add_argument("--flight-dir", default=None, metavar="DIR",
                      help="dump a flight bundle here on violation")
    runp.add_argument("--out", default=None, metavar="FILE",
                      help="write the result JSON here (else stdout)")
    runp.add_argument("--full", action="store_true",
                      help="keep per-reply trace contexts in the JSON")
    rep = sub.add_parser("replay", help="re-run a saved result's spec "
                                        "under the same seed and demand "
                                        "bit-equal ledger totals")
    rep.add_argument("result", help="result JSON from `scenario run`")
    rep.add_argument("--out", default=None, metavar="FILE")
    rep.add_argument("--full", action="store_true")
    shr = sub.add_parser("shrink", help="ddmin a failing scenario to a "
                                        "minimal still-failing repro")
    shr.add_argument("spec", help="builtin name or spec/result JSON")
    shr.add_argument("--max-runs", type=int, default=40,
                     help="live-run budget for the search (default 40)")
    shr.add_argument("--out", default=None, metavar="FILE",
                     help="write the minimal spec JSON here")
    sub.add_parser("list", help="list the builtin drill catalog")
    args = ap.parse_args(argv)

    from nnstreamer_tpu.scenario import builtin_specs

    if args.cmd == "list":
        print(f"{'name':<16} {'topology':<22} {'arrivals':<9} "
              f"{'faults':<7} size")
        print("-" * 62)
        for name, s in builtin_specs().items():
            topo = (f"{s.topology.kind}"
                    f"({s.topology.hosts}x{s.topology.workers}w)")
            print(f"{name:<16} {topo:<22} {len(s.arrivals):<9} "
                  f"{len(s.faults):<7} {s.size()}")
        return 0

    from nnstreamer_tpu.scenario import run_scenario

    if args.cmd == "run":
        spec = _scenario_load(args.spec, args.seed)
        result = run_scenario(spec, flight_dir=args.flight_dir)
        check = result.get("check") or {}
        _scenario_emit(result, args.out, args.full)
        for v in check.get("violations") or []:
            print(f"VIOLATION [{v['invariant']}] {v['detail']}",
                  file=sys.stderr)
        print(f"scenario {spec.name!r} seed={spec.seed}: "
              f"{result['totals']} "
              f"{'OK' if check.get('ok') else 'FAIL'}",
              file=sys.stderr)
        return 0 if check.get("ok") else 1

    if args.cmd == "replay":
        from nnstreamer_tpu.scenario import replay_scenario

        with open(args.result, "r", encoding="utf-8") as f:
            prev = json.load(f)
        result = replay_scenario(prev)
        _scenario_emit(result, args.out, args.full)
        match = result.get("replay_match")
        ok = bool((result.get("check") or {}).get("ok"))
        if match is False:
            print(f"replay DIVERGED: {result.get('replay_diff')}",
                  file=sys.stderr)
        else:
            print(f"replay totals match: {result['totals']}",
                  file=sys.stderr)
        return 0 if (match is not False and ok) else 1

    # shrink
    from nnstreamer_tpu.scenario import ShrinkBudgetExceeded, shrink

    spec = _scenario_load(args.spec)

    def fails(candidate) -> bool:
        r = run_scenario(candidate)
        return not (r.get("check") or {}).get("ok", False)

    try:
        minimal, stats = shrink(spec, fails, max_runs=args.max_runs)
    except ValueError as e:
        print(f"shrink: {e}", file=sys.stderr)
        return 1
    except ShrinkBudgetExceeded as e:
        print(f"shrink: {e}", file=sys.stderr)
        return 1
    text = minimal.to_json()
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    else:
        print(text)
    print(f"shrunk {spec.name!r}: size {stats['initial_size']} -> "
          f"{stats['final_size']} in {stats['runs']} run(s)",
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "trace":
        return _trace_main(argv[1:])
    if argv and argv[0] == "models":
        return _models_main(argv[1:])
    if argv and argv[0] == "llm":
        return _llm_main(argv[1:])
    if argv and argv[0] == "traffic":
        return _traffic_main(argv[1:])
    if argv and argv[0] == "serve":
        return _serve_main(argv[1:])
    if argv and argv[0] == "mesh":
        return _mesh_main(argv[1:])
    if argv and argv[0] == "top":
        return _top_main(argv[1:])
    if argv and argv[0] == "flight":
        return _flight_main(argv[1:])
    if argv and argv[0] == "scenario":
        return _scenario_main(argv[1:])
    if argv and argv[0] == "lint":
        from nnstreamer_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu",
        description="TPU-native streaming AI pipelines (gst-launch parity)")
    ap.add_argument("pipeline", nargs="?", help="pipeline description string")
    ap.add_argument("--inspect", nargs="?", const="", default=None,
                    metavar="ELEMENT", help="list elements / element detail")
    ap.add_argument("--models", action="store_true", help="list zoo models")
    ap.add_argument("--timeout", type=float, default=None,
                    help="max run seconds")
    ap.add_argument("--stats", action="store_true",
                    help="print per-element stats JSON after EOS")
    ap.add_argument("--no-optimize", action="store_true",
                    help="disable transform-into-filter fusion")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture an xprof/TensorBoard device trace of the "
                         "run into DIR (jax.profiler)")
    ap.add_argument("--broker", nargs="?", const=1883, default=None,
                    type=int, metavar="PORT",
                    help="run a standalone EdgeBroker (discovery + pub/sub "
                         "+ clock service) on PORT (default 1883)")
    ap.add_argument("--bind", default="0.0.0.0",
                    help="bind address for --broker (default 0.0.0.0)")
    args = ap.parse_args(argv)

    if args.broker is not None:
        from nnstreamer_tpu.edge.broker import EdgeBroker

        broker = EdgeBroker(args.bind, args.broker)
        print(f"edge broker listening on {args.bind}:{broker.port} "
              f"(mqtt 3.1.1 on :{broker.mqtt_port}; ^C to stop)",
              file=sys.stderr)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            broker.close()
            return 0
    if args.inspect is not None:
        return _inspect(args.inspect or None)
    if args.models:
        return _models()
    if not args.pipeline:
        ap.print_help()
        return 2

    import contextlib

    import nnstreamer_tpu as nns

    profile_cm = contextlib.nullcontext()
    if args.profile:
        import jax

        profile_cm = jax.profiler.trace(args.profile)

    pipe = nns.parse_launch(args.pipeline)
    runner = nns.PipelineRunner(pipe, optimize=not args.no_optimize)
    try:
        with profile_cm:
            runner.start()
            runner.wait(args.timeout)
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
        return 130
    finally:
        runner.stop()
    if args.profile:
        print(f"device trace written to {args.profile} "
              f"(view with TensorBoard / xprof)", file=sys.stderr)
    if args.stats:
        print(json.dumps(runner.stats(), indent=2, default=float))
    return 0


if __name__ == "__main__":
    sys.exit(main())
