"""Built-in TFLite custom-op lowerings.

`TFLite_Detection_PostProcess` is the op the reference's flagship
query-server demo model ends in
(`gst/nnstreamer/tensor_query/README.md:46-53`; the interpreter resolves
it from its stock custom-op table, `tensor_filter_tensorflow_lite.cc`).
Round-2 VERDICT missing #2: the importer rejected any detection
`.tflite` because of it. The lowering here reproduces the kernel's
fast-NMS path (tensorflow/lite/kernels/detection_postprocess.cc,
use_regular_nms=false — the exported-model default) as dense XLA:

inputs  (box_encodings [1,N,4], class_predictions [1,N,C(+1)],
         anchors [N,4: ycenter,xcenter,h,w])
options (flexbuffer map: max_detections, num_classes, y/x/h/w_scale,
         nms_score_threshold, nms_iou_threshold, …)
outputs (boxes [1,D,4: ymin,xmin,ymax,xmax] normalized,
         classes [1,D] float 0-based (background column dropped),
         scores [1,D], num_detections [1] float)

Box decode: center/size deltas scaled by y/x/h/w_scale against the
anchor; selection: per-anchor max class score → score threshold →
descending-score greedy class-agnostic NMS (reusing the device decoder's
`greedy_nms_mask`) → top max_detections, zero-padded.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio.tflite import register_tflite_custom_op


@register_tflite_custom_op("TFLite_Detection_PostProcess")
def detection_postprocess(op, inputs, opts, jnp):
    from jax import lax

    from nnstreamer_tpu.decoders.device import greedy_nms_mask

    if len(inputs) != 3:
        raise BackendError(
            f"TFLite_Detection_PostProcess expects (boxes, scores, "
            f"anchors), got {len(inputs)} inputs")
    boxes_enc, scores_in, anchors = inputs
    num_classes = int(opts.get("num_classes", 1))
    max_det = int(opts.get("max_detections", 10))
    score_thresh = float(opts.get("nms_score_threshold", 0.0))
    iou_thresh = float(opts.get("nms_iou_threshold", 0.5))
    y_scale = float(opts.get("y_scale", 10.0))
    x_scale = float(opts.get("x_scale", 10.0))
    h_scale = float(opts.get("h_scale", 5.0))
    w_scale = float(opts.get("w_scale", 5.0))
    if opts.get("use_regular_nms", False):
        raise BackendError(
            "TFLite_Detection_PostProcess: use_regular_nms=true "
            "(per-class NMS) is not lowered; re-export with the default "
            "fast NMS")

    d = boxes_enc.reshape(-1, 4).astype(jnp.float32)
    a = anchors.reshape(-1, 4).astype(jnp.float32)
    n = d.shape[0]
    ycenter = d[:, 0] / y_scale * a[:, 2] + a[:, 0]
    xcenter = d[:, 1] / x_scale * a[:, 3] + a[:, 1]
    half_h = 0.5 * jnp.exp(d[:, 2] / h_scale) * a[:, 2]
    half_w = 0.5 * jnp.exp(d[:, 3] / w_scale) * a[:, 3]
    boxes = jnp.stack([ycenter - half_h, xcenter - half_w,
                       ycenter + half_h, xcenter + half_w], axis=1)

    sc = scores_in.reshape(n, -1).astype(jnp.float32)
    offset = sc.shape[1] - num_classes        # background column if any
    if offset not in (0, 1):
        raise BackendError(
            f"class_predictions has {sc.shape[1]} columns for "
            f"{num_classes} classes (expected num_classes or +1)")
    sc = sc[:, offset:]
    cls = jnp.argmax(sc, axis=-1)
    score = jnp.take_along_axis(sc, cls[:, None], axis=1)[:, 0]

    # candidate cap keeps NMS O(K²) with K static; the kernel sorts all
    # candidates, but anything beyond the cap cannot reach the top
    # max_det picks in practice (cap >= 10× max_det)
    k = min(n, max(100, 10 * max_det))
    s_top, i_top = lax.top_k(score, k)
    b_top = boxes[i_top]
    c_top = cls[i_top].astype(jnp.float32)
    s_top = jnp.where(s_top >= score_thresh, s_top, 0.0)
    keep = greedy_nms_mask(b_top, iou_thresh)
    s_kept = jnp.where(keep & (s_top > 0.0), s_top, 0.0)
    out_k = min(max_det, k)
    s_fin, i_fin = lax.top_k(s_kept, out_k)
    valid = s_fin > 0.0
    b_fin = jnp.where(valid[:, None], b_top[i_fin], 0.0)
    c_fin = jnp.where(valid, c_top[i_fin], 0.0)
    s_out = jnp.where(valid, s_fin, 0.0)
    pad = max_det - out_k
    if pad:
        b_fin = jnp.pad(b_fin, ((0, pad), (0, 0)))
        c_fin = jnp.pad(c_fin, ((0, pad),))
        s_out = jnp.pad(s_out, ((0, pad),))
    count = jnp.sum(valid.astype(jnp.float32))
    return (b_fin[None], c_fin[None], s_out[None], count[None])
