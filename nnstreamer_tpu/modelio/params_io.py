"""Serialized params format (.npz) for zoo / python-defined models.

The reference's tensor_filter loads weight *files* per framework; for
models defined in this framework (zoo or user python), the equivalent is
an `.npz` archive holding the params pytree plus a JSON header naming the
architecture that rebuilds the forward fn:

    save_params("m.npz", "zoo://mobilenet_v2?width=1.0", params)
    ... tensor_filter model=m.npz ...

The arch string is any model reference the XLA backend resolves
(`zoo://name?args` or `pkg.module:attr`), so loading = resolve arch for
the fn + substitute the stored params. Pytree structure (nested
dict/list/tuple with array leaves) is preserved exactly.
"""

from __future__ import annotations

import json
from typing import Any, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError

_FORMAT = "nnstreamer-tpu-params-v1"


def _flatten(tree: Any, out: list) -> Any:
    """Structure skeleton with leaves replaced by param indices."""
    if isinstance(tree, dict):
        return {"__kind__": "dict",
                "items": {k: _flatten(v, out) for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        kind = "list" if isinstance(tree, list) else "tuple"
        return {"__kind__": kind, "items": [_flatten(v, out) for v in tree]}
    if tree is None:
        return {"__kind__": "none"}
    idx = len(out)
    out.append(np.asarray(tree))
    return {"__kind__": "leaf", "index": idx}


def _unflatten(skel: Any, leaves: list) -> Any:
    kind = skel["__kind__"]
    if kind == "dict":
        return {k: _unflatten(v, leaves) for k, v in skel["items"].items()}
    if kind == "list":
        return [_unflatten(v, leaves) for v in skel["items"]]
    if kind == "tuple":
        return tuple(_unflatten(v, leaves) for v in skel["items"])
    if kind == "none":
        return None
    return leaves[skel["index"]]


def save_params(path: str, arch: str, params: Any) -> None:
    """Write params + the arch reference that rebuilds the forward fn."""
    leaves: list = []
    skel = _flatten(params, leaves)
    meta = json.dumps({"format": _FORMAT, "arch": arch, "tree": skel})
    arrays = {f"p{i}": a for i, a in enumerate(leaves)}
    np.savez(path, __meta__=np.frombuffer(meta.encode(), np.uint8), **arrays)


def load_params(path: str) -> Tuple[str, Any]:
    """→ (arch reference, params pytree)."""
    with np.load(path, allow_pickle=False) as z:
        if "__meta__" not in z:
            raise BackendError(
                f"{path!r} is not a {_FORMAT} archive (no __meta__ header); "
                f"write it with nnstreamer_tpu.modelio.save_params")
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta.get("format") != _FORMAT:
            raise BackendError(
                f"{path!r}: unknown params format {meta.get('format')!r}")
        leaves = [z[f"p{i}"] for i in range(_count_leaves(meta["tree"]))]
    return meta["arch"], _unflatten(meta["tree"], leaves)


def _count_leaves(skel: Any) -> int:
    kind = skel["__kind__"]
    if kind == "dict":
        return sum(_count_leaves(v) for v in skel["items"].values())
    if kind in ("list", "tuple"):
        return sum(_count_leaves(v) for v in skel["items"])
    return 1 if kind == "leaf" else 0
