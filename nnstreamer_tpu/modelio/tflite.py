"""TFLite model ingestion → one fused XLA computation.

This is the TPU-native answer to the reference's TFLite filter subplugin
(`ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:154`
`TFLiteInterpreter`): instead of handing the file to an interpreter that
executes op-by-op on CPU, the flatbuffer graph is parsed (via the
self-contained reader in `flatbuf.py`, no TFLite/TF dependency) and
lowered to a single jax-traceable function. XLA then fuses the whole
network — including the input dequantize and output quantize steps — into
one TPU program.

Quantization strategy ("dequantize → bf16"): quantized (uint8/int8)
weights are dequantized **once at load time** with their per-tensor or
per-channel scale/zero-point; activations run in a float compute dtype
(bf16 on TPU, f32 accumulation in the MXU via preferred_element_type).
Integer saturation semantics are approximated by clamping each op output
to its tensor's representable quantized range — this subsumes fused
ReLU/ReLU6 activations, whose bounds are baked into those ranges by the
TFLite converter. Graph inputs/outputs keep their stored (possibly
integer) dtype so pipeline specs match the reference's contract; the
final output is re-quantized with the stored scale/zero-point.

Op coverage targets the reference's own test models
(mobilenet_v2_1.0_224_quant / deeplabv3 / add: CONV_2D,
DEPTHWISE_CONV_2D, ADD, AVERAGE_POOL_2D, RESHAPE, …) plus the common
CNN vocabulary, **control flow** (WHILE with cond/body subgraphs →
`lax.while_loop`, covering converter-emitted LSTM/RNN loops), and
**custom ops** via `register_tflite_custom_op` (built-in:
`TFLite_Detection_PostProcess`, `tflite_custom.py` — the op the
reference's query-server SSD demo model ends in). Unsupported ops fail
loudly with the op name.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.modelio.flatbuf import Reader

log = get_logger("modelio.tflite")

# -- TFLite schema constants (schema.fbs, stable public format) -----------

# Model table field ids
_MODEL_OPERATOR_CODES = 1
_MODEL_SUBGRAPHS = 2
_MODEL_BUFFERS = 4
# OperatorCode
_OPCODE_DEPRECATED_BUILTIN = 0
_OPCODE_CUSTOM = 1
_OPCODE_BUILTIN = 3
# SubGraph
_SG_TENSORS = 0
_SG_INPUTS = 1
_SG_OUTPUTS = 2
_SG_OPERATORS = 3
# Tensor
_T_SHAPE = 0
_T_TYPE = 1
_T_BUFFER = 2
_T_NAME = 3
_T_QUANT = 4
# QuantizationParameters
_Q_SCALE = 2
_Q_ZERO_POINT = 3
_Q_QUANTIZED_DIM = 6
# Operator
_OP_OPCODE_INDEX = 0
_OP_INPUTS = 1
_OP_OUTPUTS = 2
_OP_OPTIONS = 4
# Buffer
_BUF_DATA = 0

# TensorType enum → numpy dtype. RESOURCE(13)/VARIANT(14) are opaque
# handles (LSTM/RNN state variables); they carry no data and map to a
# placeholder dtype — ops that consume them handle the state explicitly.
_TENSOR_TYPES: Dict[int, np.dtype] = {
    0: np.dtype(np.float32), 1: np.dtype(np.float16), 2: np.dtype(np.int32),
    3: np.dtype(np.uint8), 4: np.dtype(np.int64), 6: np.dtype(np.bool_),
    7: np.dtype(np.int16), 9: np.dtype(np.int8), 10: np.dtype(np.float64),
    13: np.dtype(np.int32), 14: np.dtype(np.int32),
}

# BuiltinOperator enum values used below
OP = dict(
    ADD=0, AVERAGE_POOL_2D=1, CONCATENATION=2, CONV_2D=3,
    DEPTHWISE_CONV_2D=4, DEPTH_TO_SPACE=5, DEQUANTIZE=6, FLOOR=8,
    FULLY_CONNECTED=9, L2_NORMALIZATION=11, LOGISTIC=14,
    MAX_POOL_2D=17, MUL=18, RELU=19, RELU6=21, RESHAPE=22,
    RESIZE_BILINEAR=23, SOFTMAX=25, SPACE_TO_DEPTH=26, TANH=28, PAD=34,
    GATHER=36, TRANSPOSE=39,
    MEAN=40, SUB=41, DIV=42, SQUEEZE=43, STRIDED_SLICE=45, EXP=47,
    SPLIT=49, LOG_SOFTMAX=50, CAST=53, MAXIMUM=55, ARG_MAX=56,
    MINIMUM=57,
    LESS=58, NEG=59, GREATER=61, GREATER_EQUAL=62, LESS_EQUAL=63,
    SELECT=64, SLICE=65, SIN=66, TRANSPOSE_CONV=67, TILE=69,
    EXPAND_DIMS=70, EQUAL=71, LOG=73, SUM=74, SQRT=75, RSQRT=76,
    POW=78, ARG_MIN=79, REDUCE_PROD=81, REDUCE_MAX=82, PACK=83,
    LOGICAL_AND=86, UNPACK=88, REDUCE_MIN=89,
    LEAKY_RELU=98, SQUARED_DIFFERENCE=99, MIRROR_PAD=100, ABS=101,
    CEIL=104, COS=108, ELU=111,
    RESIZE_NEAREST_NEIGHBOR=97, HARD_SWISH=117, QUANTIZE=114,
    WHILE=119, SELECT_V2=123, BATCH_MATMUL=126, GELU=150,
)
_OP_NAMES = {v: k for k, v in OP.items()}

#: custom-op registry: name → fn(op, inputs_tuple, opts_dict, jnp) →
#: tuple of outputs. Register via `register_tflite_custom_op`.
TFLITE_CUSTOM_OPS: Dict[str, Callable] = {}


def register_tflite_custom_op(name: str):
    """Decorator registering a jax lowering for a TFLite custom op (the
    subplugin-style extension point the reference exposes through each
    NN framework's own custom-op resolver)."""
    def deco(fn):
        TFLITE_CUSTOM_OPS[name] = fn
        return fn
    return deco


def _decode_flexbuffer_map(data: bytes) -> Dict[str, Any]:
    """Custom-op options arrive as a FlexBuffers map (converter
    convention); decode to a plain dict. Undecodable options raise —
    running a custom op with default options would be silently wrong."""
    if not data:
        return {}
    try:
        from nnstreamer_tpu.interop.flexbuf_read import flexbuf_loads

        root = flexbuf_loads(data)
        if not isinstance(root, dict):
            raise ValueError("custom_options root is not a map")
        # scalar/str options only (converter convention for op options)
        return {k: v for k, v in root.items()
                if isinstance(v, (bool, int, float, str))}
    except Exception as e:
        raise BackendError(
            f"undecodable TFLite custom_options ({e}); cannot run the "
            f"custom op with defaults") from None

# ActivationFunctionType
_ACT_NONE, _ACT_RELU, _ACT_RELU_N1_1, _ACT_RELU6 = 0, 1, 2, 3
# Padding enum
_PAD_SAME, _PAD_VALID = 0, 1


@dataclass
class TensorDef:
    index: int
    shape: Tuple[int, ...]
    dtype: np.dtype
    name: str
    buffer: Optional[np.ndarray]          # raw constant data or None
    scale: Optional[np.ndarray] = None    # quant scales ([] ⇒ not quantized)
    zero_point: Optional[np.ndarray] = None
    qdim: int = 0

    @property
    def quantized(self) -> bool:
        return (self.scale is not None and self.scale.size > 0
                and self.dtype.kind in "iu")


@dataclass
class OpDef:
    code: int
    name: str
    inputs: List[int]
    outputs: List[int]
    opts: Optional[int]          # options table position in the flatbuffer
    custom_name: Optional[str] = None
    custom_options: bytes = b""


@dataclass
class Subgraph:
    """One TFLite subgraph (main graph or a control-flow body)."""

    tensors: List[TensorDef]
    ops: List[OpDef]
    inputs: List[int]
    outputs: List[int]


@dataclass
class TFLiteGraph:
    reader: Reader
    tensors: List[TensorDef]     # = subgraphs[0].tensors
    ops: List[OpDef]             # = subgraphs[0].ops
    inputs: List[int]
    outputs: List[int]
    path: str = ""
    subgraphs: List[Subgraph] = field(default_factory=list)


# Operator.custom_options (schema field id 5)
_OP_CUSTOM_OPTIONS = 5


def parse_tflite(path: str) -> TFLiteGraph:
    """Parse a .tflite flatbuffer into a graph description (host-side).

    All subgraphs are parsed — control-flow ops (WHILE) reference the
    extra subgraphs as their cond/body."""
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < 8 or buf[4:8] != b"TFL3":
        raise BackendError(
            f"{path!r} is not a TFLite flatbuffer (missing TFL3 identifier)")
    from nnstreamer_tpu.modelio.protowire import wire_context

    with wire_context(f"tflite {path!r}", BackendError):
        return _parse_tflite_buf(buf, path)


def _parse_tflite_buf(buf: bytes, path: str) -> TFLiteGraph:
    r = Reader(buf)
    model = r.root()

    # operator codes: deprecated_builtin_code (int8) was the original field;
    # values >=127 moved to the int32 builtin_code field (schema v3a)
    codes: List[Tuple[int, Optional[str]]] = []
    for oc in r.field_vec_tables(model, _MODEL_OPERATOR_CODES):
        dep = r.field_scalar(oc, _OPCODE_DEPRECATED_BUILTIN, "<b", 0)
        full = r.field_scalar(oc, _OPCODE_BUILTIN, "<i", 0)
        codes.append((max(dep, full), r.field_string(oc, _OPCODE_CUSTOM)))

    buffers = r.field_vec_tables(model, _MODEL_BUFFERS)
    raw_subgraphs = r.field_vec_tables(model, _MODEL_SUBGRAPHS)
    if not raw_subgraphs:
        raise BackendError(f"{path!r}: no subgraphs")

    def parse_sg(sg) -> Subgraph:
        tensors: List[TensorDef] = []
        for i, tpos in enumerate(r.field_vec_tables(sg, _SG_TENSORS)):
            shape_v = r.field_vec_scalars(tpos, _T_SHAPE, np.int32)
            shape = tuple(int(d) for d in shape_v) \
                if shape_v is not None else ()
            ttype = r.field_scalar(tpos, _T_TYPE, "<b", 0)
            dtype = _TENSOR_TYPES.get(ttype)
            if dtype is None:
                raise BackendError(
                    f"{path!r}: tensor {i} has unsupported TensorType "
                    f"{ttype}")
            buf_idx = r.field_scalar(tpos, _T_BUFFER, "<I", 0)
            data = None
            if buf_idx and buf_idx < len(buffers):
                raw = r.field_vec_scalars(buffers[buf_idx], _BUF_DATA,
                                          np.uint8)
                if raw is not None and raw.size:
                    data = raw.view(dtype).reshape(
                        shape if shape else (-1,))
            scale = zp = None
            qdim = 0
            q = r.field_table(tpos, _T_QUANT)
            if q is not None:
                scale = r.field_vec_scalars(q, _Q_SCALE, np.float32)
                zp = r.field_vec_scalars(q, _Q_ZERO_POINT, np.int64)
                qdim = r.field_scalar(q, _Q_QUANTIZED_DIM, "<i", 0)
            tensors.append(TensorDef(
                index=i, shape=shape, dtype=dtype,
                name=r.field_string(tpos, _T_NAME) or f"t{i}",
                buffer=data, scale=scale, zero_point=zp, qdim=qdim))

        ops: List[OpDef] = []
        for opos in r.field_vec_tables(sg, _SG_OPERATORS):
            idx = r.field_scalar(opos, _OP_OPCODE_INDEX, "<I", 0)
            code, custom = codes[idx]
            ins = r.field_vec_scalars(opos, _OP_INPUTS, np.int32)
            outs = r.field_vec_scalars(opos, _OP_OUTPUTS, np.int32)
            copts = r.field_vec_scalars(opos, _OP_CUSTOM_OPTIONS, np.uint8)
            ops.append(OpDef(
                code=code, name=_OP_NAMES.get(code, f"builtin_{code}"),
                inputs=[int(x) for x in (ins if ins is not None else [])],
                outputs=[int(x) for x in
                         (outs if outs is not None else [])],
                opts=r.field_table(opos, _OP_OPTIONS), custom_name=custom,
                custom_options=(copts.tobytes()
                                if copts is not None else b"")))

        g_in = r.field_vec_scalars(sg, _SG_INPUTS, np.int32)
        g_out = r.field_vec_scalars(sg, _SG_OUTPUTS, np.int32)
        return Subgraph(
            tensors=tensors, ops=ops,
            inputs=[int(x) for x in (g_in if g_in is not None else [])],
            outputs=[int(x) for x in (g_out if g_out is not None else [])])

    sgs = [parse_sg(sg) for sg in raw_subgraphs]
    main = sgs[0]
    return TFLiteGraph(
        reader=r, tensors=main.tensors, ops=main.ops,
        inputs=main.inputs, outputs=main.outputs,
        path=path, subgraphs=sgs)


def _is_float(dtype) -> bool:
    """True for any float dtype incl. ml_dtypes bfloat16 (whose numpy
    `kind` is 'V', so `kind == 'f'` checks silently miss it)."""
    import jax.numpy as jnp

    return jnp.issubdtype(dtype, jnp.floating)


# -- load-time weight dequantization --------------------------------------

def _dequantize_const(t: TensorDef) -> np.ndarray:
    """Dequantize a constant tensor to float32 (per-tensor or per-channel)."""
    data = t.buffer.astype(np.float32)
    scale = t.scale.astype(np.float32)
    zp = t.zero_point.astype(np.float32) if t.zero_point is not None else \
        np.zeros_like(scale)
    if scale.size == 1:
        return (data - zp[0]) * scale[0]
    bshape = [1] * data.ndim
    bshape[t.qdim] = scale.size
    return (data - zp.reshape(bshape)) * scale.reshape(bshape)


def _qrange(t: TensorDef) -> Tuple[float, float]:
    """Float range representable by a quantized tensor (for saturation)."""
    info = np.iinfo(t.dtype)
    s = float(t.scale[0])
    z = float(t.zero_point[0]) if t.zero_point is not None else 0.0
    return (info.min - z) * s, (info.max - z) * s


# -- lowering ---------------------------------------------------------------

@dataclass
class LoweredModel:
    fn: Callable          # fn(params, *inputs) -> tuple of outputs
    params: Dict[str, Any]
    in_shapes: List[Tuple[int, ...]]
    in_dtypes: List[np.dtype]
    out_shapes: List[Tuple[int, ...]]
    out_dtypes: List[np.dtype]
    name: str = ""


def lower_tflite(graph: TFLiteGraph, batch: Optional[int] = None,
                 compute_dtype: str = "bfloat16",
                 quantize_output: bool = True) -> LoweredModel:
    """Lower a parsed graph to a jax-traceable fn + params pytree.

    batch: override the file's (usually 1) leading batch dimension.
    compute_dtype: activation dtype ("bfloat16" on TPU, "float32" exact).
    quantize_output: re-quantize integer graph outputs (spec parity with
      the file); False emits dequantized float outputs.
    """
    import jax
    import jax.numpy as jnp

    orig_batch = None
    if batch is not None and graph.inputs:
        in0 = graph.tensors[graph.inputs[0]]
        orig_batch = in0.shape[0] if in0.shape else None

    def bshape(shape: Tuple[int, ...]) -> Tuple[int, ...]:
        if batch is not None and shape and shape[0] == orig_batch:
            return (batch,) + shape[1:]
        return shape

    # params: all dequantized / raw constants, keyed by (subgraph, index).
    # Shape-only constants (reshape targets, pad widths, reduce axes) stay
    # host-side: they must be static at trace time.
    subgraphs = graph.subgraphs or [Subgraph(
        tensors=graph.tensors, ops=graph.ops,
        inputs=graph.inputs, outputs=graph.outputs)]
    params: Dict[str, Any] = {}
    static_by_sg: List[Dict[int, np.ndarray]] = []
    for si, sg in enumerate(subgraphs):
        static_consts: Dict[int, np.ndarray] = {}
        consumed_as_static = _static_input_indices(sg)
        for t in sg.tensors:
            if t.buffer is None:
                continue
            if t.index in consumed_as_static:
                # shape/axis constants must be host-side at trace time —
                # but the same tensor may ALSO feed a runtime op input
                # (e.g. a scalar used as both SPLIT axis and ADD step),
                # so it stays available as a param too
                static_consts[t.index] = np.asarray(t.buffer)
            arr = _dequantize_const(t) if t.quantized \
                else np.asarray(t.buffer)
            params[_pkey(si, t.index)] = arr
        static_by_sg.append(static_consts)

    cdt = jnp.dtype(compute_dtype)
    tensors = graph.tensors

    def run_sg(si: int, p, in_vals: Tuple) -> Tuple:
        """Evaluate one subgraph given its input values (used for the
        main graph and recursively for WHILE cond/body graphs)."""
        sg = subgraphs[si]
        vals: Dict[int, Any] = dict(zip(sg.inputs, in_vals))

        def get(i):
            if i in vals:
                return vals[i]
            key = _pkey(si, i)
            if key in p:
                arr = jnp.asarray(p[key])
                return arr.astype(cdt) if _is_float(arr.dtype) else arr
            raise BackendError(
                f"op input tensor {i} ({sg.tensors[i].name!r}) has no "
                f"value (dynamic graph order not supported)")

        ctx = dict(run_sg=lambda si2, c: run_sg(si2, p, c), sg_index=si)
        for op in sg.ops:
            out = _eval_op(graph, sg, op, get, static_by_sg[si], jnp,
                           cdt, ctx)
            outs = out if isinstance(out, tuple) else (out,)
            for oi, o in zip(op.outputs, outs):
                ot = sg.tensors[oi]
                if ot.quantized and _is_float(o.dtype):
                    lo, hi = _qrange(ot)
                    o = jnp.clip(o, lo, hi)
                vals[oi] = o
        return tuple(vals[i] for i in sg.outputs)

    def fn(p, *inputs):
        if len(inputs) != len(graph.inputs):
            raise BackendError(
                f"model {graph.path!r} expects {len(graph.inputs)} inputs, "
                f"got {len(inputs)}")
        staged = []
        for idx, x in zip(graph.inputs, inputs):
            t = tensors[idx]
            x = jnp.asarray(x)
            if t.quantized:
                s = float(t.scale[0])
                z = float(t.zero_point[0]) if t.zero_point is not None else 0.0
                x = (x.astype(jnp.float32) - z) * s
            elif t.dtype == np.bool_:
                x = x.astype(jnp.bool_)     # uint8 on the wire → bool
            staged.append(x.astype(cdt) if _is_float(x.dtype) else x)
        outs = run_sg(0, p, tuple(staged))

        results = []
        for idx, y in zip(graph.outputs, outs):
            t = tensors[idx]
            if t.quantized and quantize_output:
                s = float(t.scale[0])
                z = float(t.zero_point[0]) if t.zero_point is not None else 0.0
                info = np.iinfo(t.dtype)
                q = jnp.round(y.astype(jnp.float32) / s) + z
                y = jnp.clip(q, info.min, info.max).astype(t.dtype)
            elif _is_float(y.dtype):
                y = y.astype(jnp.float32)
            elif y.dtype == jnp.bool_:
                y = y.astype(jnp.uint8)     # bool → uint8 on the wire
            results.append(y)
        return tuple(results)

    def io_dtype(t: TensorDef, is_out: bool) -> np.dtype:
        if t.quantized and (not is_out or quantize_output):
            return t.dtype
        if t.dtype == np.int64 and not jax.config.jax_enable_x64:
            # argmax/argmin-style int64 outputs truncate to int32 under
            # default JAX; the declared spec must match the arrays the
            # traced fn actually produces (buffer sizing reads it)
            return np.dtype(np.int32)
        if t.dtype == np.bool_:
            # the tensor type system (like the reference's) has no bool:
            # bool tensors ride the wire as uint8
            return np.dtype(np.uint8)
        return np.dtype(np.float32) if t.dtype.kind == "f" or t.quantized \
            else t.dtype

    return LoweredModel(
        fn=fn, params=params,
        in_shapes=[bshape(tensors[i].shape) for i in graph.inputs],
        in_dtypes=[io_dtype(tensors[i], False) for i in graph.inputs],
        out_shapes=[bshape(tensors[i].shape) for i in graph.outputs],
        out_dtypes=[io_dtype(tensors[i], True) for i in graph.outputs],
        name=os.path.basename(graph.path))


def _pkey(si: int, idx: int) -> str:
    """Params-dict key for tensor `idx` of subgraph `si` (subgraph 0
    keeps the historical bare key)."""
    return f"t{idx}" if si == 0 else f"s{si}t{idx}"


def _static_input_indices(graph) -> set:
    """Tensor indices consumed as static shape/axis/padding arguments
    (accepts a TFLiteGraph or a Subgraph)."""
    static = set()
    for op in graph.ops:
        ins = op.inputs
        if op.code == OP["RESHAPE"] and len(ins) > 1:
            static.add(ins[1])
        elif op.code in (OP["MEAN"], OP["SUM"]) and len(ins) > 1:
            static.add(ins[1])
        elif op.code == OP["PAD"] and len(ins) > 1:
            static.add(ins[1])
        elif op.code == OP["TRANSPOSE"] and len(ins) > 1:
            static.add(ins[1])
        elif op.code == OP["ARG_MAX"] and len(ins) > 1:
            static.add(ins[1])
        elif op.code == OP["EXPAND_DIMS"] and len(ins) > 1:
            static.add(ins[1])
        elif op.code in (OP["RESIZE_BILINEAR"],
                         OP["RESIZE_NEAREST_NEIGHBOR"]) and len(ins) > 1:
            static.add(ins[1])
        elif op.code in (OP["SLICE"], OP["STRIDED_SLICE"]):
            static.update(ins[1:])
        elif op.code == OP["SPLIT"] and len(ins) > 1:
            static.add(ins[0])          # axis
        elif op.code in (OP["TILE"], OP["MIRROR_PAD"]) and len(ins) > 1:
            static.add(ins[1])          # multiples / pads
        elif op.code in (OP["REDUCE_MAX"], OP["REDUCE_MIN"],
                         OP["REDUCE_PROD"], OP["ARG_MIN"]) \
                and len(ins) > 1:
            static.add(ins[1])          # axes
        elif op.code == OP["TRANSPOSE_CONV"]:
            static.add(ins[0])          # output_shape
    return static


def _resize(jnp, x, oh: int, ow: int, bilinear: bool,
            align_corners: bool, half_pixel: bool):
    """TFLite-exact NHWC spatial resize via gather + lerp.

    TFLite has three coordinate maps (kernels/internal resize impls):
    align_corners: src = i*(in-1)/(out-1); half_pixel_centers:
    src = (i+0.5)*in/out - 0.5; legacy/default: src = i*in/out.
    jax.image.resize only offers the half-pixel map, so do it by hand —
    gathers on constant indices fold into cheap XLA ops.
    """
    import numpy as onp

    b, h, w, c = x.shape

    def coords(out_n: int, in_n: int) -> onp.ndarray:
        i = onp.arange(out_n, dtype=onp.float64)
        if align_corners and out_n > 1:
            return i * (in_n - 1) / (out_n - 1)
        if half_pixel:
            return onp.maximum((i + 0.5) * in_n / out_n - 0.5, 0.0)
        return i * in_n / out_n

    ys, xs = coords(oh, h), coords(ow, w)
    if not bilinear:
        # TFLite nearest rounds half away from zero (std::round, not
        # numpy's half-to-even) when align_corners, else floors
        yi = onp.minimum(onp.floor(ys + 0.5) if align_corners
                         else onp.floor(ys), h - 1).astype(onp.int32)
        xi = onp.minimum(onp.floor(xs + 0.5) if align_corners
                         else onp.floor(xs), w - 1).astype(onp.int32)
        return jnp.take(jnp.take(x, yi, axis=1), xi, axis=2)

    y0 = onp.clip(onp.floor(ys).astype(onp.int32), 0, h - 1)
    x0 = onp.clip(onp.floor(xs).astype(onp.int32), 0, w - 1)
    y1 = onp.minimum(y0 + 1, h - 1)
    x1 = onp.minimum(x0 + 1, w - 1)
    wy = jnp.asarray((ys - y0), x.dtype).reshape(1, oh, 1, 1)
    wx = jnp.asarray((xs - x0), x.dtype).reshape(1, 1, ow, 1)
    top = jnp.take(x, y0, axis=1)
    bot = jnp.take(x, y1, axis=1)
    tl, tr = jnp.take(top, x0, axis=2), jnp.take(top, x1, axis=2)
    bl, br = jnp.take(bot, x0, axis=2), jnp.take(bot, x1, axis=2)
    t = tl * (1 - wx) + tr * wx
    bm = bl * (1 - wx) + br * wx
    return t * (1 - wy) + bm * wy


# -- per-op evaluation ------------------------------------------------------

def _act(jnp, x, act: int):
    if act == _ACT_NONE:
        return x
    if act == _ACT_RELU:
        return jnp.maximum(x, 0)
    if act == _ACT_RELU_N1_1:
        return jnp.clip(x, -1, 1)
    if act == _ACT_RELU6:
        return jnp.clip(x, 0, 6)
    raise BackendError(f"unsupported fused activation {act}")


def _pad_str(padding: int) -> str:
    return "SAME" if padding == _PAD_SAME else "VALID"


def _eval_op(graph: TFLiteGraph, sg: "Subgraph", op: OpDef, get,
             static_consts, jnp, cdt, ctx=None):
    import jax
    from jax import lax

    r = graph.reader
    o = op.opts
    code = op.code
    tensors = sg.tensors
    ctx = ctx or {}

    if op.custom_name:
        impl = TFLITE_CUSTOM_OPS.get(op.custom_name)
        if impl is None:
            raise BackendError(
                f"TFLite custom op {op.custom_name!r} in {graph.path!r} "
                f"has no registered lowering; register one with "
                f"modelio.tflite.register_tflite_custom_op")
        opts = _decode_flexbuffer_map(op.custom_options)
        return impl(op, tuple(get(i) for i in op.inputs if i >= 0),
                    opts, jnp)

    def opt_i(fid, default=0):
        return r.field_scalar(o, fid, "<i", default) if o is not None \
            else default

    def opt_b(fid, default=0):
        return r.field_scalar(o, fid, "<b", default) if o is not None \
            else default

    def opt_f(fid, default=0.0):
        return r.field_scalar(o, fid, "<f", default) if o is not None \
            else default

    def static(i):
        if i in static_consts:
            return static_consts[i]
        t = tensors[i]
        if t.buffer is not None:
            return np.asarray(t.buffer)
        raise BackendError(
            f"{op.name}: input tensor {i} must be a compile-time constant")

    if code == OP["CONV_2D"]:
        x = get(op.inputs[0])
        w = get(op.inputs[1])                      # OHWI
        stride = (opt_i(2, 1), opt_i(1, 1))        # (h, w)
        dil = (opt_i(5, 1), opt_i(4, 1))
        y = lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 3, 0)),     # → HWIO
            window_strides=stride, padding=_pad_str(opt_b(0)),
            rhs_dilation=dil,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2]).astype(jnp.float32)
        return _act(jnp, y.astype(cdt), opt_b(3))

    if code == OP["DEPTHWISE_CONV_2D"]:
        x = get(op.inputs[0])
        w = get(op.inputs[1])                      # [1, H, W, C*mult]
        stride = (opt_i(2, 1), opt_i(1, 1))
        dil = (opt_i(6, 1), opt_i(5, 1))
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 0, 3)),     # → (H, W, 1, C*mult)
            window_strides=stride, padding=_pad_str(opt_b(0)),
            rhs_dilation=dil, feature_group_count=c_in,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2]).astype(jnp.float32)
        return _act(jnp, y.astype(cdt), opt_b(4))

    if code == OP["FULLY_CONNECTED"]:
        x = get(op.inputs[0])
        w = get(op.inputs[1])                      # [out, in]
        if x.ndim != 2:
            # TFLite batch = total_size / in_features, not the leading dim
            x = x.reshape((-1, w.shape[-1]))
        y = jnp.dot(x, w.T, preferred_element_type=jnp.float32)
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2]).astype(jnp.float32)
        return _act(jnp, y.astype(cdt), opt_b(0))

    if code in (OP["AVERAGE_POOL_2D"], OP["MAX_POOL_2D"]):
        x = get(op.inputs[0])
        stride = (1, opt_i(2, 1), opt_i(1, 1), 1)
        window = (1, opt_i(4, 1), opt_i(3, 1), 1)
        padding = _pad_str(opt_b(0))
        if code == OP["MAX_POOL_2D"]:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, stride,
                                  padding)
        else:
            s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add,
                                  window, stride, padding)
            ones = jnp.ones(x.shape[1:3], jnp.float32)[None, :, :, None]
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride,
                                    padding)
            y = (s / cnt).astype(cdt)
        return _act(jnp, y, opt_b(5))

    if code in (OP["ADD"], OP["MUL"], OP["SUB"], OP["DIV"],
                OP["MAXIMUM"], OP["MINIMUM"]):
        a, b = get(op.inputs[0]), get(op.inputs[1])
        f = {OP["ADD"]: jnp.add, OP["MUL"]: jnp.multiply,
             OP["SUB"]: jnp.subtract, OP["DIV"]: jnp.divide,
             OP["MAXIMUM"]: jnp.maximum, OP["MINIMUM"]: jnp.minimum}[code]
        act = opt_b(0) if code in (OP["ADD"], OP["MUL"], OP["SUB"],
                                   OP["DIV"]) else _ACT_NONE
        return _act(jnp, f(a, b), act)

    if code == OP["RESHAPE"]:
        x = get(op.inputs[0])
        if len(op.inputs) > 1:
            shape = [int(d) for d in static(op.inputs[1]).ravel()]
        else:
            shape = [int(d) for d in
                     (r.field_vec_scalars(o, 0, np.int32) or [])]
        out_t = tensors[op.outputs[0]]
        if len(shape) == len(out_t.shape) and shape and \
                x.shape[0] != shape[0] and shape[0] == out_t.shape[0]:
            shape[0] = -1          # batch-override: keep runtime batch
        return x.reshape(shape)

    if code == OP["SQUEEZE"]:
        x = get(op.inputs[0])
        dims = r.field_vec_scalars(o, 0, np.int32) if o is not None else None
        if dims is None or len(dims) == 0:
            return jnp.squeeze(x)
        return jnp.squeeze(x, axis=tuple(int(d) for d in dims))

    if code == OP["EXPAND_DIMS"]:
        x = get(op.inputs[0])
        axis = int(static(op.inputs[1]).ravel()[0])
        return jnp.expand_dims(x, axis)

    if code == OP["SOFTMAX"]:
        x = get(op.inputs[0])
        beta = opt_f(0, 1.0)
        return jax.nn.softmax(x.astype(jnp.float32) * beta,
                              axis=-1).astype(cdt)

    if code == OP["LOG_SOFTMAX"]:
        x = get(op.inputs[0])
        return jax.nn.log_softmax(x.astype(jnp.float32), axis=-1).astype(cdt)

    if code in (OP["MEAN"], OP["SUM"]):
        x = get(op.inputs[0])
        axes = tuple(int(a) for a in static(op.inputs[1]).ravel())
        keep = bool(opt_b(0))
        red = jnp.mean if code == OP["MEAN"] else jnp.sum
        return red(x, axis=axes, keepdims=keep)

    if code == OP["PAD"]:
        x = get(op.inputs[0])
        pads = static(op.inputs[1]).reshape(-1, 2)
        return jnp.pad(x, [(int(a), int(b)) for a, b in pads])

    if code == OP["CONCATENATION"]:
        axis = opt_i(0, 0)
        return _act(jnp,
                    jnp.concatenate([get(i) for i in op.inputs], axis=axis),
                    opt_b(1))

    if code == OP["TRANSPOSE"]:
        x = get(op.inputs[0])
        perm = [int(p) for p in static(op.inputs[1]).ravel()]
        return jnp.transpose(x, perm)

    if code in (OP["RESIZE_BILINEAR"], OP["RESIZE_NEAREST_NEIGHBOR"]):
        x = get(op.inputs[0])
        hw = static(op.inputs[1]).ravel()
        # ResizeBilinearOptions/ResizeNearestNeighborOptions:
        # align_corners(2 / 0), half_pixel_centers(3 / 1)
        if code == OP["RESIZE_BILINEAR"]:
            align, half_pixel = bool(opt_b(2)), bool(opt_b(3))
        else:
            align, half_pixel = bool(opt_b(0)), bool(opt_b(1))
        return _resize(jnp, x, int(hw[0]), int(hw[1]),
                       bilinear=code == OP["RESIZE_BILINEAR"],
                       align_corners=align, half_pixel=half_pixel)

    if code == OP["LOGISTIC"]:
        return jax.nn.sigmoid(get(op.inputs[0]))
    if code == OP["RELU"]:
        return jnp.maximum(get(op.inputs[0]), 0)
    if code == OP["RELU6"]:
        return jnp.clip(get(op.inputs[0]), 0, 6)
    if code == OP["TANH"]:
        return jnp.tanh(get(op.inputs[0]))
    if code == OP["HARD_SWISH"]:
        x = get(op.inputs[0])
        return x * jnp.clip(x + 3.0, 0, 6) / 6.0
    if code == OP["LEAKY_RELU"]:
        x = get(op.inputs[0])
        return jnp.where(x >= 0, x, x * opt_f(0, 0.01))
    if code == OP["ABS"]:
        return jnp.abs(get(op.inputs[0]))

    if code in (OP["DEQUANTIZE"], OP["QUANTIZE"]):
        # activations already live in the float compute domain; quant
        # boundaries are handled at graph inputs/outputs
        return get(op.inputs[0])

    if code == OP["ARG_MAX"]:
        x = get(op.inputs[0])
        axis = int(static(op.inputs[1]).ravel()[0])
        out_dt = tensors[op.outputs[0]].dtype
        return jnp.argmax(x, axis=axis).astype(out_dt)

    if code == OP["SLICE"]:
        x = get(op.inputs[0])
        begin = [int(v) for v in static(op.inputs[1]).ravel()]
        size = [int(v) for v in static(op.inputs[2]).ravel()]
        size = [x.shape[i] - begin[i] if s == -1 else s
                for i, s in enumerate(size)]
        return lax.slice(x, begin, [b + s for b, s in zip(begin, size)])

    if code == OP["PACK"]:
        axis = opt_i(1, 0)
        return jnp.stack([get(i) for i in op.inputs], axis=axis)

    if code == OP["STRIDED_SLICE"]:
        x = get(op.inputs[0])
        begin = [int(v) for v in static(op.inputs[1]).ravel()]
        end = [int(v) for v in static(op.inputs[2]).ravel()]
        strides = [int(v) for v in static(op.inputs[3]).ravel()]
        bm, em = opt_i(0, 0), opt_i(1, 0)
        ellipsis_m, new_axis_m = opt_i(2, 0), opt_i(3, 0)
        shrink_m = opt_i(4, 0)
        if ellipsis_m or new_axis_m:
            raise BackendError(
                "STRIDED_SLICE ellipsis/new-axis masks not supported")
        idx = []
        shrink_axes = []
        for i in range(len(begin)):
            if shrink_m & (1 << i):
                b = begin[i]
                idx.append(slice(b, None if b == -1 else b + 1, 1))
                shrink_axes.append(i)
                continue
            b = None if bm & (1 << i) else begin[i]
            e = None if em & (1 << i) else end[i]
            idx.append(slice(b, e, strides[i]))
        y = x[tuple(idx)]
        if shrink_axes:
            y = jnp.squeeze(y, axis=tuple(shrink_axes))
        return y

    if code == OP["GATHER"]:
        x = get(op.inputs[0])
        idx = get(op.inputs[1])
        axis = opt_i(0, 0)
        return jnp.take(x, idx, axis=axis)

    if code == OP["SPLIT"]:
        axis = int(static(op.inputs[0]).ravel()[0])
        x = get(op.inputs[1])
        n = opt_i(0, len(op.outputs)) or len(op.outputs)
        return tuple(jnp.split(x, n, axis=axis))

    if code in (OP["LESS"], OP["GREATER"], OP["GREATER_EQUAL"],
                OP["LESS_EQUAL"], OP["EQUAL"]):
        a, b = get(op.inputs[0]), get(op.inputs[1])
        f = {OP["LESS"]: jnp.less, OP["GREATER"]: jnp.greater,
             OP["GREATER_EQUAL"]: jnp.greater_equal,
             OP["LESS_EQUAL"]: jnp.less_equal, OP["EQUAL"]: jnp.equal}[code]
        return f(a, b)

    if code == OP["LOGICAL_AND"]:
        return jnp.logical_and(get(op.inputs[0]), get(op.inputs[1]))

    if code == OP["BATCH_MATMUL"]:
        a, b = get(op.inputs[0]), get(op.inputs[1])
        if opt_b(0):                       # adj_x
            a = jnp.swapaxes(a, -1, -2)
        if opt_b(1):                       # adj_y
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b, preferred_element_type=jnp.float32) \
            .astype(a.dtype) if _is_float(a.dtype) else jnp.matmul(a, b)

    if code == OP["WHILE"]:
        # WhileOptions: cond_subgraph_index=0, body_subgraph_index=1.
        # Subgraph evaluation comes through ctx["run_sg"]; the loop
        # carry is the op's full input tuple (TFLite guarantees matched
        # shapes/dtypes between body inputs and outputs).
        run = ctx.get("run_sg")
        if run is None:
            raise BackendError(
                "WHILE op encountered without subgraph context")
        cond_idx = opt_i(0, 0)
        body_idx = opt_i(1, 0)
        carry = tuple(get(i) for i in op.inputs)

        def cond_fn(c):
            out = run(cond_idx, c)
            return jnp.reshape(out[0], ()).astype(jnp.bool_)

        def body_fn(c):
            return tuple(run(body_idx, c))

        return tuple(jax.lax.while_loop(cond_fn, body_fn, carry))

    _UNARY = {
        OP["EXP"]: jnp.exp, OP["LOG"]: jnp.log, OP["SQRT"]: jnp.sqrt,
        OP["RSQRT"]: lambda x: 1.0 / jnp.sqrt(x), OP["NEG"]: jnp.negative,
        OP["FLOOR"]: jnp.floor, OP["CEIL"]: jnp.ceil, OP["SIN"]: jnp.sin,
        OP["COS"]: jnp.cos, OP["ELU"]: jax.nn.elu,
    }
    if code in _UNARY:
        return _UNARY[code](get(op.inputs[0]))

    if code == OP["GELU"]:
        # GeluOptions: approximate (field 0)
        return jax.nn.gelu(get(op.inputs[0]), approximate=bool(opt_b(0)))

    if code == OP["POW"]:
        return jnp.power(get(op.inputs[0]), get(op.inputs[1]))

    if code == OP["SQUARED_DIFFERENCE"]:
        d_ = get(op.inputs[0]) - get(op.inputs[1])
        return d_ * d_

    if code == OP["CAST"]:
        return get(op.inputs[0]).astype(
            tensors[op.outputs[0]].dtype)

    if code in (OP["REDUCE_MAX"], OP["REDUCE_MIN"], OP["REDUCE_PROD"]):
        x = get(op.inputs[0])
        axes = tuple(int(a) for a in static(op.inputs[1]).ravel())
        keep = bool(opt_b(0))
        red = {OP["REDUCE_MAX"]: jnp.max, OP["REDUCE_MIN"]: jnp.min,
               OP["REDUCE_PROD"]: jnp.prod}[code]
        return red(x, axis=axes, keepdims=keep)

    if code == OP["ARG_MIN"]:
        x = get(op.inputs[0])
        axis = int(static(op.inputs[1]).ravel()[0])
        return jnp.argmin(x, axis=axis).astype(
            tensors[op.outputs[0]].dtype)

    if code in (OP["SELECT"], OP["SELECT_V2"]):
        cond = get(op.inputs[0])
        a, b2 = get(op.inputs[1]), get(op.inputs[2])
        # SELECT (v1): a rank-1 condition picks along the FIRST axis of
        # higher-rank operands (TFLite kernel semantics); SELECT_V2 is
        # plain numpy-style broadcasting
        if code == OP["SELECT"] and cond.ndim == 1 and a.ndim > 1:
            cond = cond.reshape((cond.shape[0],) + (1,) * (a.ndim - 1))
        return jnp.where(cond, a, b2)

    if code == OP["TILE"]:
        reps = [int(v) for v in static(op.inputs[1]).ravel()]
        return jnp.tile(get(op.inputs[0]), reps)

    if code == OP["UNPACK"]:
        # UnpackOptions: num (field 0), axis (field 1)
        x = get(op.inputs[0])
        axis = opt_i(1, 0)
        n = opt_i(0, 0) or x.shape[axis]
        parts = jnp.split(x, n, axis=axis)
        return tuple(jnp.squeeze(pp, axis=axis) for pp in parts)

    if code == OP["MIRROR_PAD"]:
        x = get(op.inputs[0])
        pads = static(op.inputs[1]).reshape(-1, 2)
        mode = "reflect" if opt_i(0, 0) == 0 else "symmetric"
        return jnp.pad(x, [(int(a), int(b)) for a, b in pads],
                       mode=mode)

    if code in (OP["DEPTH_TO_SPACE"], OP["SPACE_TO_DEPTH"]):
        x = get(op.inputs[0])
        bs = opt_i(0, 2)
        b, h, w, c = x.shape
        if code == OP["DEPTH_TO_SPACE"]:
            y = x.reshape(b, h, w, bs, bs, c // (bs * bs))
            y = y.transpose(0, 1, 3, 2, 4, 5)
            return y.reshape(b, h * bs, w * bs, c // (bs * bs))
        y = x.reshape(b, h // bs, bs, w // bs, bs, c)
        y = y.transpose(0, 1, 3, 2, 4, 5)
        return y.reshape(b, h // bs, w // bs, c * bs * bs)

    if code == OP["L2_NORMALIZATION"]:
        x = get(op.inputs[0]).astype(jnp.float32)
        denom = jnp.sqrt(jnp.maximum(
            jnp.sum(x * x, axis=-1, keepdims=True), 1e-12))
        return _act(jnp, (x / denom).astype(cdt), opt_b(0))

    if code == OP["TRANSPOSE_CONV"]:
        # inputs: output_shape (static), weights (O,H,W,I), activations.
        # TRANSPOSE_CONV is exactly the input-gradient of the forward
        # conv over the declared output shape — build it as that VJP,
        # which is correct by construction for every stride/padding
        # combination (hand-rolled lax.conv_transpose padding math
        # measured 7e-3 off the interpreter).
        out_shape = [int(v) for v in static(op.inputs[0]).ravel()]
        w = get(op.inputs[1])
        x = get(op.inputs[2])
        # TransposeConvOptions: padding=0, stride_w=1, stride_h=2
        stride = (opt_i(2, 1), opt_i(1, 1))
        pad = _pad_str(opt_b(0))
        w_fwd = jnp.transpose(w, (1, 2, 0, 3))       # → HWIO (I=out ch)

        def fwd(t):
            # HIGHEST: the default conv precision truncates to ~bf16 on
            # some backends (measured 7e-3 vs the interpreter)
            return lax.conv_general_dilated(
                t, w_fwd, window_strides=stride, padding=pad,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)

        _, vjp = jax.vjp(fwd, jnp.zeros(out_shape, x.dtype))
        y = vjp(x.astype(jnp.float32))[0].astype(cdt)
        if len(op.inputs) > 3 and op.inputs[3] >= 0:
            y = y + get(op.inputs[3]).astype(cdt)
        # TransposeConvOptions: fused_activation_function = field 3
        return _act(jnp, y, opt_b(3))

    raise BackendError(
        f"TFLite op {op.name} (builtin code {code}"
        + (f", custom {op.custom_name!r}" if op.custom_name else "")
        + f") in {graph.path!r} is not supported by the XLA lowering; "
        f"supported: {sorted(OP)}")
