"""Caffe2 NetDef ingestion (init_net + predict_net pair) → one XLA fn.

Third model-file ecosystem next to `.tflite` and TF `.pb` — reference
parity with the caffe2 filter subplugin
(`ext/nnstreamer/tensor_filter/tensor_filter_caffe2.cc`: the reference
links the caffe2 runtime and takes `model="init_net.pb,predict_net.pb"`
with `inputname=`/`outputname=` blob binding). Here both NetDefs are
parsed with the dependency-free protobuf wire reader (`protowire.py`):
the init net's fill ops are executed host-side into the parameter dict,
and the predict net lowers node-by-node to one jax-traceable function
(NCHW convolutions on the MXU, inference-mode SpatialBN folded to
scale/shift, n-ary Sum residuals).

Covered ops target the reference's own test pair
(`caffe2_init_net.pb`/`caffe2_predict_net.pb`, a CIFAR-10 ResNet:
Conv/SpatialBN/Relu/Sum/AveragePool/FC/Softmax) plus MaxPool and
ConstantFill; unsupported ops fail loudly. Semantic golden: the
reference's own `data/5` sample classifies as label 5, the expectation
its `checkLabel.py` asserts (tests/test_modelio.py).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.modelio import protowire as pw
from nnstreamer_tpu.modelio.tflite import LoweredModel

log = get_logger("modelio.caffe2")

# NetDef fields
_ND_NAME, _ND_OP = 1, 2
_ND_EXTERNAL_INPUT, _ND_EXTERNAL_OUTPUT = 5, 6
# OperatorDef
_OP_INPUT, _OP_OUTPUT, _OP_NAME, _OP_TYPE, _OP_ARG = 1, 2, 3, 4, 5
# Argument
_A_NAME, _A_F, _A_I, _A_S, _A_FLOATS, _A_INTS = 1, 2, 3, 4, 5, 6


@dataclass
class C2Op:
    type: str
    inputs: List[str]
    outputs: List[str]
    args: Dict[str, Any]


def _decode_arg(buf: bytes):
    d = pw.fields_dict(buf)
    name = pw.first(d, _A_NAME, b"").decode()
    if _A_F in d:
        return name, pw.fixed32_to_float(d[_A_F][0])
    if _A_I in d:
        return name, pw.to_signed64(d[_A_I][0])
    if _A_S in d:
        return name, d[_A_S][0].decode(errors="replace")
    if _A_FLOATS in d:
        vals = d[_A_FLOATS]
        if len(vals) == 1 and isinstance(vals[0], bytes):   # packed
            return name, np.frombuffer(vals[0], "<f4")
        # caffe2.proto is proto2: repeated floats arrive UNPACKED (one
        # fixed32 per element) — reinterpret vectorized, not per-scalar
        return name, np.asarray(vals, np.uint32).view(np.float32)
    if _A_INTS in d:
        vals = d[_A_INTS]
        if len(vals) == 1 and isinstance(vals[0], bytes):
            return name, np.array(
                [pw.to_signed64(v) for v in pw.packed_varints(vals[0])],
                np.int64)
        return name, np.array([pw.to_signed64(v) for v in vals], np.int64)
    return name, None


def parse_netdef(path: str) -> Tuple[List[C2Op], List[str], List[str]]:
    with open(path, "rb") as f:
        buf = f.read()
    try:
        d = pw.fields_dict(buf)
        raw_ops = d.get(_ND_OP, [])
        if not raw_ops:
            raise ValueError("no operators")
        ops = []
        for ob in raw_ops:
            od = pw.fields_dict(ob)
            args = dict(_decode_arg(ab) for ab in od.get(_OP_ARG, []))
            ops.append(C2Op(
                type=pw.first(od, _OP_TYPE, b"").decode(),
                inputs=[v.decode() for v in od.get(_OP_INPUT, [])],
                outputs=[v.decode() for v in od.get(_OP_OUTPUT, [])],
                args=args))
        ext_in = [v.decode() for v in d.get(_ND_EXTERNAL_INPUT, [])]
        ext_out = [v.decode() for v in d.get(_ND_EXTERNAL_OUTPUT, [])]
        return ops, ext_in, ext_out
    except (ValueError, IndexError, struct.error,
            UnicodeDecodeError) as e:
        raise BackendError(
            f"{path!r} is not a caffe2 NetDef: {e}") from None


def _run_init_net(ops: List[C2Op]) -> Dict[str, np.ndarray]:
    """Execute fill ops host-side → blob name → array."""
    blobs: Dict[str, np.ndarray] = {}
    for op in ops:
        shape = tuple(int(v) for v in
                      np.asarray(op.args.get("shape", [])).ravel())
        if op.type == "GivenTensorFill":
            vals = np.asarray(op.args["values"], np.float32)
        elif op.type in ("GivenTensorIntFill", "GivenTensorInt64Fill"):
            vals = np.asarray(op.args["values"], np.int64)
        elif op.type == "ConstantFill":
            vals = np.full(shape or (1,),
                           float(op.args.get("value", 0.0)), np.float32)
        elif op.type in ("XavierFill", "MSRAFill", "UniformFill",
                         "GaussianFill"):
            # frozen inference pairs should not contain random fills;
            # zeros keep loading deterministic if one slips through
            log.warning("init net %s for %r: filling zeros",
                        op.type, op.outputs)
            vals = np.zeros(shape or (1,), np.float32)
        else:
            raise BackendError(
                f"caffe2 init-net op {op.type!r} is not a supported fill")
        blobs[op.outputs[0]] = vals.reshape(shape) if shape else vals
    return blobs


def lower_caffe2(init_path: str, predict_path: str,
                 input_names: Optional[List[str]] = None,
                 output_names: Optional[List[str]] = None,
                 batch: Optional[int] = None,
                 side: Optional[int] = None) -> LoweredModel:
    init_ops, _, _ = parse_netdef(init_path)
    ops, ext_in, ext_out = parse_netdef(predict_path)
    params = _run_init_net(init_ops)

    produced = {o for op in ops for o in op.outputs}
    if input_names is None:
        cand = [i for op in ops for i in op.inputs
                if i not in produced and i not in params]
        input_names = list(dict.fromkeys(cand)) \
            or [i for i in ext_in if i not in params]
        if not input_names and ops:
            # caffe2 init nets commonly plant a DUMMY placeholder blob
            # for the data input (GivenTensorFill of one value); the
            # dataflow root — the first op's first input — is the real
            # input and the runtime value must override the dummy
            input_names = [ops[0].inputs[0]]
    for nm in input_names:
        params.pop(nm, None)
    if output_names is None:
        consumed = {i for op in ops for i in op.inputs}
        # only FIRST outputs count: the lowering writes op.outputs[0]
        # (secondary outputs like Dropout's mask are never produced)
        output_names = [op.outputs[0] for op in ops
                        if op.outputs and op.outputs[0] not in consumed] \
            or [o for o in ext_out if o in produced] \
            or [ops[-1].outputs[0]]

    def fn(p, *inputs):
        import jax
        import jax.numpy as jnp
        from jax import lax

        if len(inputs) != len(input_names):
            raise BackendError(
                f"caffe2 net expects {len(input_names)} inputs "
                f"({input_names}), got {len(inputs)}")
        vals: Dict[str, Any] = {
            nm: jnp.asarray(x) for nm, x in zip(input_names, inputs)}

        def get(name: str):
            if name in vals:
                return vals[name]
            if name in p:
                return jnp.asarray(p[name])
            raise BackendError(f"caffe2 blob {name!r} has no value")

        for op in ops:
            t = op.type
            if op.args.get("order", "NCHW") != "NCHW":
                raise BackendError(
                    f"caffe2 {t}: only order=NCHW supported")
            if t == "Conv":
                x, w = get(op.inputs[0]), get(op.inputs[1])
                unsupported = [a for a in (
                    "dilation", "dilations", "kernels", "strides",
                    "pads", "pad_t", "pad_l", "pad_b", "pad_r",
                    "group") if a in op.args]
                if unsupported:
                    raise BackendError(
                        f"caffe2 Conv: args {unsupported} are not "
                        f"lowered (square kernel/stride/pad only); "
                        f"refusing to run with silently-wrong numerics")
                k = int(op.args.get("kernel", w.shape[-1]))
                if k != w.shape[-1]:
                    raise BackendError(
                        f"caffe2 Conv: kernel arg {k} disagrees with "
                        f"weight shape {tuple(w.shape)}")
                stride = int(op.args.get("stride", 1))
                pad = int(op.args.get("pad", 0))
                y = lax.conv_general_dilated(
                    x, w, window_strides=(stride, stride),
                    padding=[(pad, pad), (pad, pad)],
                    dimension_numbers=("NCHW", "OIHW", "NCHW"),
                    precision=lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
                if len(op.inputs) > 2:
                    y = y + get(op.inputs[2]).reshape(1, -1, 1, 1)
                vals[op.outputs[0]] = y
            elif t == "SpatialBN":
                if not op.args.get("is_test", 0):
                    raise BackendError(
                        "caffe2 SpatialBN: only inference (is_test=1)")
                x = get(op.inputs[0])
                s, b = get(op.inputs[1]), get(op.inputs[2])
                rm, riv = get(op.inputs[3]), get(op.inputs[4])
                eps = float(op.args.get("epsilon", 1e-5))
                inv = s / jnp.sqrt(riv + eps)
                y = (x - rm.reshape(1, -1, 1, 1)) \
                    * inv.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)
                vals[op.outputs[0]] = y
            elif t == "Relu":
                vals[op.outputs[0]] = jnp.maximum(get(op.inputs[0]), 0)
            elif t == "Sum":
                acc = get(op.inputs[0])
                for i in op.inputs[1:]:
                    acc = acc + get(i)
                vals[op.outputs[0]] = acc
            elif t in ("AveragePool", "MaxPool"):
                x = get(op.inputs[0])
                k = int(op.args.get("kernel", 0))
                pool_pad = int(op.args.get("pad", 0))
                if op.args.get("global_pooling", 0) or \
                        (pool_pad == 0 and k == x.shape[-1]
                         == x.shape[-2]):
                    red = jnp.mean if t == "AveragePool" else jnp.max
                    vals[op.outputs[0]] = red(x, axis=(2, 3),
                                              keepdims=True)
                    continue
                stride = int(op.args.get("stride", 1))
                dims = (1, 1, k, k)
                strides = (1, 1, stride, stride)
                pads = [(0, 0), (0, 0), (pool_pad, pool_pad),
                        (pool_pad, pool_pad)]
                if t == "MaxPool":
                    vals[op.outputs[0]] = lax.reduce_window(
                        x, -jnp.inf, lax.max, dims, strides, pads)
                else:
                    s_ = lax.reduce_window(
                        x, 0.0, lax.add, dims, strides, pads)
                    cnt = lax.reduce_window(
                        jnp.ones_like(x), 0.0, lax.add, dims, strides,
                        pads)
                    vals[op.outputs[0]] = s_ / cnt
            elif t == "FC":
                x = get(op.inputs[0])
                w, b = get(op.inputs[1]), get(op.inputs[2])
                x2 = x.reshape(x.shape[0], -1)
                vals[op.outputs[0]] = x2 @ w.T + b
            elif t == "Softmax":
                # caffe2 semantics: flatten to 2D around `axis`
                # (default 1) and normalize over the trailing block
                x = get(op.inputs[0])
                ax = int(op.args.get("axis", 1))
                lead = int(np.prod(x.shape[:ax])) if ax else 1
                y = jax.nn.softmax(x.reshape(lead, -1), axis=-1)
                vals[op.outputs[0]] = y.reshape(x.shape)
            elif t in ("Dropout",):
                vals[op.outputs[0]] = get(op.inputs[0])
            else:
                raise BackendError(
                    f"caffe2 op {t!r} is not supported by the XLA "
                    f"lowering")
        return tuple(get(nm) for nm in output_names)

    # shapes: probe with a NCHW input inferred from the first conv
    first_conv = next((op for op in ops if op.type == "Conv"), None)
    if first_conv is None:
        raise BackendError(
            "caffe2 predict net has no Conv; cannot infer the input "
            "shape (declare it with custom=side=<pixels>)")
    w_name = first_conv.inputs[1]
    if w_name not in params:
        raise BackendError(
            f"caffe2: first Conv weight blob {w_name!r} is not filled "
            f"by the init net (mismatched init/predict pair, or the "
            f"blob was claimed as an input)")
    c_in = params[w_name].shape[1]
    # spatial size is data-dependent: custom=side=<n> declares it,
    # defaulting to 32 (the reference's CIFAR pair)
    import jax

    side = side or 32
    b = batch or 1
    probe = [np.zeros((b, c_in, side, side), np.float32)]
    out_avals = jax.eval_shape(fn, params, *probe)
    return LoweredModel(
        fn=fn, params=params,
        in_shapes=[(b, c_in, side, side)],
        in_dtypes=[np.dtype(np.float32)],
        out_shapes=[tuple(a.shape) for a in out_avals],
        out_dtypes=[np.dtype(a.dtype) for a in out_avals],
        name=os.path.basename(predict_path))
