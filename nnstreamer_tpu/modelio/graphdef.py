"""TF frozen-GraphDef ingestion → one fused XLA computation.

Second mainstream model-file route next to `.tflite` (VERDICT r2 missing
#1). The reference links the TensorFlow C runtime and executes the graph
with TF sessions (`ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc:801`,
input/output binding via `inputname=`/`outputname=` properties). Here the
frozen `.pb` is parsed with the dependency-free protobuf wire reader
(`protowire.py`) and lowered node-by-node to one jax-traceable function,
so the whole graph — including the speech-command audio frontend
(AudioSpectrogram → Mfcc) — fuses into a single TPU program.

Covered op vocabulary: the reference's own frozen models
(`tests/test_models/models/mnist.pb`, `conv_actions_frozen.pb`) plus the
common inference set: Const/Placeholder/Identity, MatMul, Conv2D,
DepthwiseConv2dNative, BiasAdd/Add/AddV2/Sub/Mul, Relu/Relu6/Softmax,
MaxPool/AvgPool, Reshape/Squeeze/ExpandDims/ConcatV2/Pad, Mean, ArgMax,
DecodeWav, AudioSpectrogram, Mfcc. Unsupported ops fail loudly.

DecodeWav runs **host-side** (`host_pre`): it is byte-string parsing, not
tensor math — the RIFF header is decoded on host exactly once per frame
and the PCM samples enter the XLA program as a float tensor. The
`sample_rate` output becomes a load-time constant (the Mfcc mel
filterbank depends on it structurally; reference models carry one rate).

Audio frontend semantics follow the public TF kernels:
- AudioSpectrogram (tensorflow/core/kernels/spectrogram.cc): periodic
  Hann window, fft_length = next-pow-2(window_size), whole windows only,
  output (channels, frames, fft_length/2+1), optional squared magnitude.
- Mfcc (mfcc_mel_filterbank.cc / mfcc_dct.cc): triangular mel filterbank
  (mel(f) = 1127·ln(1+f/700)) over bins 1.., floor 1e-12, natural log,
  DCT-II with weights sqrt(2/N)·cos(πk(n+0.5)/N).
Both are golden-tested against the TF kernels in tests/test_modelio.py.
"""

from __future__ import annotations

import math
import os
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.modelio import protowire as pw
from nnstreamer_tpu.modelio.tflite import LoweredModel

log = get_logger("modelio.graphdef")

# -- proto field numbers (public tensorflow .proto schemas) ----------------
# GraphDef
_GD_NODE = 1
# NodeDef
_ND_NAME, _ND_OP, _ND_INPUT, _ND_DEVICE, _ND_ATTR = 1, 2, 3, 4, 5
# map<string, AttrValue> entry
_MAP_KEY, _MAP_VALUE = 1, 2
# AttrValue (oneof)
_AV_LIST, _AV_S, _AV_I, _AV_F, _AV_B = 1, 2, 3, 4, 5
_AV_TYPE, _AV_SHAPE, _AV_TENSOR = 6, 7, 8
# TensorProto
_TP_DTYPE, _TP_SHAPE, _TP_CONTENT = 1, 2, 4
_TP_FLOAT, _TP_DOUBLE, _TP_INT, _TP_STRING, _TP_INT64 = 5, 6, 7, 8, 10
_TP_BOOL = 11
# TensorShapeProto / Dim
_TS_DIM, _DIM_SIZE = 2, 1

#: TF DataType enum → numpy
_DTYPES = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
    6: np.int8, 7: np.bytes_, 9: np.int64, 10: np.bool_, 14: np.uint16,
    17: np.uint16, 22: np.uint32, 23: np.uint64,
}


@dataclass
class NodeDef:
    name: str
    op: str
    inputs: List[str]
    attrs: Dict[str, Dict[int, List[Any]]]   # attr name → AttrValue fields

    def attr_i(self, key: str, default: int = 0) -> int:
        a = self.attrs.get(key)
        return pw.to_signed64(pw.first(a, _AV_I, default)) if a else default

    def attr_f(self, key: str, default: float = 0.0) -> float:
        a = self.attrs.get(key)
        if not a or _AV_F not in a:
            return default
        return pw.fixed32_to_float(a[_AV_F][0])

    def attr_b(self, key: str, default: bool = False) -> bool:
        a = self.attrs.get(key)
        return bool(pw.first(a, _AV_B, default)) if a else default

    def attr_s(self, key: str, default: str = "") -> str:
        a = self.attrs.get(key)
        v = pw.first(a, _AV_S) if a else None
        return v.decode() if isinstance(v, bytes) else default

    def attr_ints(self, key: str) -> List[int]:
        a = self.attrs.get(key)
        if not a or _AV_LIST not in a:
            return []
        lst = pw.fields_dict(a[_AV_LIST][0])
        out: List[int] = []
        for v in lst.get(_AV_I, []):
            if isinstance(v, bytes):          # packed encoding
                out.extend(pw.to_signed64(x) for x in pw.packed_varints(v))
            else:
                out.append(pw.to_signed64(v))
        return out

    def attr_type(self, key: str, default: int = 0) -> int:
        a = self.attrs.get(key)
        return pw.first(a, _AV_TYPE, default) if a else default

    def attr_tensor(self, key: str) -> Optional[np.ndarray]:
        a = self.attrs.get(key)
        if not a or _AV_TENSOR not in a:
            return None
        return _decode_tensor(pw.fields_dict(a[_AV_TENSOR][0]))

    def attr_shape(self, key: str) -> Optional[Tuple[int, ...]]:
        a = self.attrs.get(key)
        if not a or _AV_SHAPE not in a:
            return None
        sh = pw.fields_dict(a[_AV_SHAPE][0])
        dims = []
        for d in sh.get(_TS_DIM, []):
            dd = pw.fields_dict(d)
            dims.append(pw.to_signed64(pw.first(dd, _DIM_SIZE, -1)))
        return tuple(dims)


def _decode_tensor(tp: Dict[int, List[Any]]) -> np.ndarray:
    """TensorProto → numpy array."""
    dt_enum = pw.first(tp, _TP_DTYPE, 1)
    dtype = _DTYPES.get(dt_enum)
    if dtype is None:
        raise BackendError(f"TensorProto dtype enum {dt_enum} unsupported")
    shape: Tuple[int, ...] = ()
    if _TP_SHAPE in tp:
        sh = pw.fields_dict(tp[_TP_SHAPE][0])
        shape = tuple(
            pw.to_signed64(pw.first(pw.fields_dict(d), _DIM_SIZE, -1))
            for d in sh.get(_TS_DIM, []))
    content = pw.first(tp, _TP_CONTENT)
    if content:
        arr = np.frombuffer(content, dtype=np.dtype(dtype))
        return arr.reshape(shape) if shape else arr
    # typed repeated fields (possibly a single splat value)
    if dt_enum == 1 and _TP_FLOAT in tp:          # packed or repeated f32
        vals = tp[_TP_FLOAT]
        if len(vals) == 1 and isinstance(vals[0], bytes):
            arr = np.frombuffer(vals[0], np.float32)
        else:
            arr = np.array([pw.fixed32_to_float(v) if isinstance(v, int)
                            else np.frombuffer(v, np.float32)[0]
                            for v in vals], np.float32)
    elif dt_enum == 3 and _TP_INT in tp:
        vals = tp[_TP_INT]
        if len(vals) == 1 and isinstance(vals[0], bytes):
            arr = np.array([pw.to_signed64(v)
                            for v in pw.packed_varints(vals[0])], np.int64)
        else:
            arr = np.array([pw.to_signed64(v) for v in vals], np.int64)
        arr = arr.astype(np.int32)
    elif dt_enum == 9 and _TP_INT64 in tp:
        vals = tp[_TP_INT64]
        if len(vals) == 1 and isinstance(vals[0], bytes):
            arr = np.array([pw.to_signed64(v)
                            for v in pw.packed_varints(vals[0])], np.int64)
        else:
            arr = np.array([pw.to_signed64(v) for v in vals], np.int64)
    else:
        raise BackendError(
            f"TensorProto with dtype enum {dt_enum} has no decodable "
            f"payload (fields {sorted(tp)})")
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:
        arr = np.full(shape, arr[0])              # splat-value encoding
    return arr.reshape(shape) if shape else arr


def parse_graphdef(path: str) -> List[NodeDef]:
    with open(path, "rb") as f:
        buf = f.read()
    try:
        gd = pw.fields_dict(buf)
        raw_nodes = gd.get(_GD_NODE, [])
        if not raw_nodes:
            raise ValueError("no NodeDef entries")
        nodes = []
        for nb in raw_nodes:
            nd = pw.fields_dict(nb)
            attrs: Dict[str, Dict[int, List[Any]]] = {}
            for entry in nd.get(_ND_ATTR, []):
                e = pw.fields_dict(entry)
                key = pw.first(e, _MAP_KEY, b"").decode()
                val = pw.first(e, _MAP_VALUE, b"")
                attrs[key] = pw.fields_dict(val)
            nodes.append(NodeDef(
                name=pw.first(nd, _ND_NAME, b"").decode(),
                op=pw.first(nd, _ND_OP, b"").decode(),
                inputs=[v.decode() for v in nd.get(_ND_INPUT, [])],
                attrs=attrs))
        return nodes
    except (ValueError, IndexError, struct.error,
            UnicodeDecodeError) as e:
        raise BackendError(
            f"{path!r} is not a frozen TF GraphDef: {e}") from None


# -- host-side WAV decode (DecodeWav) --------------------------------------

def decode_wav_bytes(data: bytes, desired_samples: int = -1,
                     desired_channels: int = -1
                     ) -> Tuple[np.ndarray, int]:
    """RIFF/WAVE PCM16 → (float32 [samples, channels] in [-1,1], rate).

    Host-side twin of TF's DecodeWav kernel: walks the chunk list, reads
    `fmt ` and `data`, pads/truncates to desired_samples like the TF op.
    """
    if len(data) < 12 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise BackendError("DecodeWav: input is not a RIFF/WAVE stream")
    pos = 12
    rate = None
    channels = None
    bits = None
    raw_data = None
    while pos + 8 <= len(data):
        cid = data[pos:pos + 4]
        (clen,) = struct.unpack_from("<I", data, pos + 4)
        body = pos + 8
        if cid == b"fmt ":
            fmt, channels, rate = struct.unpack_from("<HHI", data, body)
            bits = struct.unpack_from("<H", data, body + 14)[0]
            if fmt != 1 or bits != 16:
                raise BackendError(
                    f"DecodeWav supports PCM16 only (fmt={fmt}, "
                    f"bits={bits})")
        elif cid == b"data":
            raw_data = data[body:body + clen]
        pos = body + clen + (clen & 1)
    # Decode only after the walk: a `data` chunk may precede `fmt `, so
    # channels/rate are validated here, not where the chunk was seen.
    if rate is None or not channels or raw_data is None:
        raise BackendError("DecodeWav: missing fmt/data chunk")
    samples = np.frombuffer(
        raw_data[:len(raw_data) - (len(raw_data) % 2)], "<i2")
    x = (samples.astype(np.float32) / 32768.0).reshape(-1, channels)
    if desired_channels > 0 and x.shape[1] != desired_channels:
        if x.shape[1] > desired_channels:
            x = x[:, :desired_channels]
        else:  # TF kernel: duplicate the last channel up to the target
            pad = np.repeat(x[:, -1:], desired_channels - x.shape[1],
                            axis=1)
            x = np.concatenate([x, pad], axis=1)
    if desired_samples > 0:
        if x.shape[0] >= desired_samples:
            x = x[:desired_samples]
        else:
            x = np.pad(x, ((0, desired_samples - x.shape[0]), (0, 0)))
    return x, int(rate)


# -- audio frontend (jax twins of the TF kernels) --------------------------

def _next_pow2(n: int) -> int:
    v = 1
    while v < n:
        v *= 2
    return v


def audio_spectrogram(jnp, audio, window_size: int, stride: int,
                      magnitude_squared: bool):
    """(samples, channels) → (channels, frames, fft//2+1) — TF
    spectrogram.cc semantics (periodic Hann, next-pow-2 FFT, full
    windows only)."""
    n = audio.shape[0]
    fft_len = _next_pow2(window_size)
    frames = 1 + (n - window_size) // stride if n >= window_size else 0
    idx = (np.arange(frames)[:, None] * stride
           + np.arange(window_size)[None, :])          # (frames, win)
    window = 0.5 - 0.5 * np.cos(
        2.0 * np.pi * np.arange(window_size) / window_size)
    x = audio.T[:, idx]                                # (ch, frames, win)
    x = x * jnp.asarray(window, x.dtype)
    spec = jnp.fft.rfft(x, n=fft_len, axis=-1)
    mag2 = jnp.real(spec) ** 2 + jnp.imag(spec) ** 2
    return mag2 if magnitude_squared else jnp.sqrt(mag2)


def mel_filterbank(n_bins: int, sample_rate: int, channels: int,
                   lower_hz: float, upper_hz: float) -> np.ndarray:
    """(n_bins, channels) weights — exact TF mfcc_mel_filterbank.cc
    scheme: band mapper per FFT bin, weight w to its band and (1−w) to
    the next, bins outside [start_index, end_index] dropped. The matrix
    is applied to sqrt(spectrogram) (the kernel's `spec_val`).
    mel(f) = 1127·ln(1+f/700)."""
    def mel(f):
        return 1127.0 * math.log1p(f / 700.0)

    hz_per_sbin = 0.5 * sample_rate / (n_bins - 1)
    start_index = int(1.5 + lower_hz / hz_per_sbin)
    end_index = int(upper_hz / hz_per_sbin)
    mel_low = mel(lower_hz)
    mel_hi = mel(upper_hz)
    spacing = (mel_hi - mel_low) / (channels + 1)
    # center_frequencies_[i] = mel_low + spacing·(i+1), i = 0..channels
    centers = mel_low + spacing * (np.arange(channels + 1) + 1.0)

    w = np.zeros((n_bins, channels), np.float64)
    for i in range(start_index, min(end_index + 1, n_bins)):
        melf = mel(i * hz_per_sbin)
        if melf < mel_low or melf > mel_hi:
            continue
        band = int(np.searchsorted(centers, melf, side="left")) - 1
        if band >= 0:
            weight = (centers[band + 1] - melf) / \
                (centers[band + 1] - centers[band])
        else:
            weight = (centers[0] - melf) / (centers[0] - mel_low)
        if band >= 0:
            w[i, band] += weight
        if band + 1 < channels:
            w[i, band + 1] += 1.0 - weight
    return w.astype(np.float32)


def dct_matrix(n_in: int, n_out: int) -> np.ndarray:
    """(n_in, n_out) DCT-II weights — TF mfcc_dct.cc scaling."""
    fnorm = math.sqrt(2.0 / n_in)
    arg = math.pi / n_in
    k = np.arange(n_out)[None, :]
    n = np.arange(n_in)[:, None]
    return (fnorm * np.cos(k * arg * (n + 0.5))).astype(np.float32)


def mfcc(jnp, spectrogram, sample_rate: int, *, upper_hz: float,
         lower_hz: float, fb_channels: int, dct_count: int):
    """(channels, frames, bins) → (channels, frames, dct_count)."""
    n_bins = spectrogram.shape[-1]
    fb = mel_filterbank(n_bins, sample_rate, fb_channels,
                        lower_hz, upper_hz)
    dct = dct_matrix(fb_channels, dct_count)
    # TF's filterbank consumes magnitude (sqrt of the squared spec)
    energy = jnp.sqrt(spectrogram) @ jnp.asarray(fb, spectrogram.dtype)
    logfb = jnp.log(jnp.maximum(energy, 1e-12))
    return logfb @ jnp.asarray(dct, logfb.dtype)


# -- lowering ---------------------------------------------------------------

def _ref_name(ref: str) -> Tuple[str, int]:
    """'node:2' → ('node', 2); control deps '^node' handled by caller."""
    if ":" in ref:
        name, _, idx = ref.rpartition(":")
        return name, int(idx)
    return ref, 0


def lower_graphdef(nodes: Sequence[NodeDef],
                   input_names: Optional[List[str]] = None,
                   output_names: Optional[List[str]] = None,
                   batch: Optional[int] = None,
                   sample_rate: int = 16000) -> LoweredModel:
    """Lower parsed NodeDefs to a jax fn (+ host_pre for DecodeWav)."""
    import jax
    import jax.numpy as jnp

    by_name = {n.name: n for n in nodes}
    consumed = {pn for n in nodes for pn in
                (_ref_name(i)[0] for i in n.inputs if not i.startswith("^"))}

    placeholders = [n for n in nodes if n.op == "Placeholder"]
    if input_names is None:
        input_names = [n.name for n in placeholders]
    if output_names is None:
        output_names = [n.name for n in nodes
                        if n.name not in consumed and n.op not in
                        ("Const", "Placeholder")] or [nodes[-1].name]

    # constants are params (device-resident once, like the tflite route)
    params: Dict[str, Any] = {}
    for n in nodes:
        if n.op == "Const":
            t = n.attr_tensor("value")
            if t is None:
                raise BackendError(f"Const node {n.name!r} has no tensor")
            params[n.name] = t

    # DecodeWav host stage: the graph input becomes the decoded samples
    wav_nodes = [n for n in nodes if n.op == "DecodeWav"]
    host_pre: Optional[Callable] = None
    wav_entry: Optional[str] = None
    if wav_nodes:
        if len(wav_nodes) > 1:
            raise BackendError("multiple DecodeWav nodes unsupported")
        wn = wav_nodes[0]
        src = _ref_name(wn.inputs[0])[0]
        if input_names != [src]:
            raise BackendError(
                f"DecodeWav input {src!r} must be the graph input "
                f"(inputs: {input_names})")
        wav_entry = wn.name
        want_s = wn.attr_i("desired_samples", -1)
        want_c = wn.attr_i("desired_channels", -1)
        if want_s <= 0:
            raise BackendError(
                f"DecodeWav node {wn.name!r} has no desired_samples "
                f"attr; the XLA lowering needs a static sample count "
                f"(re-export the graph with desired_samples set)")
        if want_c <= 0:
            want_c = 1
        rate_holder = {"rate": sample_rate}

        def host_pre(tensors):
            raw = np.asarray(tensors[0])
            audio, rate = decode_wav_bytes(raw.tobytes(), want_s, want_c)
            if rate != rate_holder["rate"]:
                raise BackendError(
                    f"wav sample rate {rate} != model rate "
                    f"{rate_holder['rate']} (set custom=sample_rate=)")
            return (audio,) + tuple(tensors[1:])

    def placeholder_shape(n: NodeDef) -> Tuple[int, ...]:
        sh = n.attr_shape("shape") or ()
        sh = tuple(batch if (d == -1 and i == 0 and batch) else d
                   for i, d in enumerate(sh))
        return tuple(1 if d == -1 else d for d in sh)

    def fn(p, *inputs):
        if len(inputs) != len(input_names):
            raise BackendError(
                f"graph expects {len(input_names)} inputs "
                f"({input_names}), got {len(inputs)}")
        vals: Dict[Tuple[str, int], Any] = {}
        if wav_entry is not None:
            # host_pre replaced the wav bytes with decoded samples
            vals[(wav_entry, 0)] = jnp.asarray(inputs[0], jnp.float32)
            vals[(wav_entry, 1)] = jnp.int32(sample_rate)
        else:
            for nm, x in zip(input_names, inputs):
                vals[(nm, 0)] = jnp.asarray(x)

        def get(ref: str):
            nm, idx = _ref_name(ref)
            if (nm, idx) in vals:
                return vals[(nm, idx)]
            if nm in params:
                return jnp.asarray(p[nm])
            node = by_name.get(nm)
            if node is None:
                raise BackendError(f"undefined graph node {nm!r}")
            _eval(node)
            return vals[(nm, idx)]

        def _eval(n: NodeDef):
            out = _eval_node(n, get, p, jnp)
            outs = out if isinstance(out, tuple) else (out,)
            for i, o in enumerate(outs):
                vals[(n.name, i)] = o

        results = []
        for nm in output_names:
            results.append(get(nm if ":" in nm else nm + ":0"))
        return tuple(results)

    def _eval_node(n: NodeDef, get, p, jnp):
        op = n.op
        ins = [i for i in n.inputs if not i.startswith("^")]
        if op in ("Identity", "StopGradient", "PreventGradient", "Snapshot"):
            return get(ins[0])
        if op == "Placeholder":
            raise BackendError(
                f"Placeholder {n.name!r} is not bound as a graph input "
                f"(inputs: {input_names})")
        if op == "MatMul":
            a, b = get(ins[0]), get(ins[1])
            if n.attr_b("transpose_a"):
                a = a.T
            if n.attr_b("transpose_b"):
                b = b.T
            return jax.lax.dot_general(
                a, b, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32).astype(a.dtype)
        if op in ("Add", "AddV2", "BiasAdd"):
            if op == "BiasAdd" and \
                    n.attr_s("data_format", "NHWC") != "NHWC":
                raise BackendError(
                    f"BiasAdd ({n.name!r}): only NHWC supported")
            return get(ins[0]) + get(ins[1])
        if op == "Sub":
            return get(ins[0]) - get(ins[1])
        if op == "Mul":
            return get(ins[0]) * get(ins[1])
        if op == "RealDiv":
            return get(ins[0]) / get(ins[1])
        if op == "Relu":
            return jnp.maximum(get(ins[0]), 0)
        if op == "Relu6":
            return jnp.clip(get(ins[0]), 0, 6)
        if op == "Softmax":
            return jax.nn.softmax(get(ins[0]), axis=-1)
        if op == "Reshape":
            shape = np.asarray(_static(ins[1], p)).ravel().tolist()
            return get(ins[0]).reshape([int(d) for d in shape])
        if op == "Squeeze":
            dims = n.attr_ints("squeeze_dims")
            return jnp.squeeze(get(ins[0]),
                               axis=tuple(dims) if dims else None)
        if op == "ExpandDims":
            axis = int(np.asarray(_static(ins[1], p)).ravel()[0])
            return jnp.expand_dims(get(ins[0]), axis)
        if op == "ConcatV2":
            axis = int(np.asarray(_static(ins[-1], p)).ravel()[0])
            return jnp.concatenate([get(i) for i in ins[:-1]], axis=axis)
        if op == "Pad":
            pads = np.asarray(_static(ins[1], p)).reshape(-1, 2)
            return jnp.pad(get(ins[0]),
                           [(int(a), int(b)) for a, b in pads])
        if op == "Mean":
            axes = tuple(int(a) for a in
                         np.asarray(_static(ins[1], p)).ravel())
            return jnp.mean(get(ins[0]), axis=axes,
                            keepdims=n.attr_b("keep_dims"))
        if op == "ArgMax":
            axis = int(np.asarray(_static(ins[1], p)).ravel()[0])
            return jnp.argmax(get(ins[0]), axis=axis).astype(jnp.int64)
        def need_nhwc():
            fmt = n.attr_s("data_format", "NHWC")
            if fmt != "NHWC":
                raise BackendError(
                    f"{op} ({n.name!r}): only NHWC supported, got {fmt}")

        if op == "Conv2D":
            x, w = get(ins[0]), get(ins[1])
            need_nhwc()
            st = n.attr_ints("strides") or [1, 1, 1, 1]
            dil = n.attr_ints("dilations") or [1, 1, 1, 1]
            return jax.lax.conv_general_dilated(
                x, w, window_strides=tuple(st[1:3]),
                padding=n.attr_s("padding", "VALID"),
                rhs_dilation=tuple(dil[1:3]),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32).astype(x.dtype)
        if op == "DepthwiseConv2dNative":
            x, w = get(ins[0]), get(ins[1])
            need_nhwc()
            st = n.attr_ints("strides") or [1, 1, 1, 1]
            dil = n.attr_ints("dilations") or [1, 1, 1, 1]
            c = x.shape[-1]
            w = w.reshape(w.shape[0], w.shape[1], 1, -1)
            return jax.lax.conv_general_dilated(
                x, w, window_strides=tuple(st[1:3]),
                padding=n.attr_s("padding", "VALID"),
                rhs_dilation=tuple(dil[1:3]),
                feature_group_count=c,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32).astype(x.dtype)
        if op in ("MaxPool", "AvgPool"):
            x = get(ins[0])
            need_nhwc()
            ks = n.attr_ints("ksize") or [1, 1, 1, 1]
            st = n.attr_ints("strides") or [1, 1, 1, 1]
            pad = n.attr_s("padding", "VALID")
            if op == "MaxPool":
                return jax.lax.reduce_window(
                    x, -jnp.inf, jax.lax.max, tuple(ks), tuple(st), pad)
            s = jax.lax.reduce_window(
                x, 0.0, jax.lax.add, tuple(ks), tuple(st), pad)
            ones = jnp.ones(x.shape, x.dtype)
            cnt = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, tuple(ks), tuple(st), pad)
            return s / cnt
        if op == "AudioSpectrogram":
            return audio_spectrogram(
                jnp, get(ins[0]), n.attr_i("window_size"),
                n.attr_i("stride"), n.attr_b("magnitude_squared"))
        if op == "Mfcc":
            # the mel filterbank is structural: the rate must be static.
            # Prefer the graph's own rate constant; DecodeWav-fed graphs
            # fall back to the loader's sample_rate (host_pre verifies
            # the wav header against it).
            rate = sample_rate
            try:
                rate = int(np.asarray(_static(ins[1], p)).ravel()[0])
            except BackendError:
                pass
            return mfcc(
                jnp, get(ins[0]), rate,
                upper_hz=n.attr_f("upper_frequency_limit", 4000.0),
                lower_hz=n.attr_f("lower_frequency_limit", 20.0),
                fb_channels=n.attr_i("filterbank_channel_count", 40),
                dct_count=n.attr_i("dct_coefficient_count", 13))
        if op == "DecodeWav":
            raise BackendError(
                "DecodeWav must be the graph entry (host-side decode)")
        if op == "Cast":
            return get(ins[0]).astype(_DTYPES[n.attr_type("DstT", 1)])
        raise BackendError(
            f"GraphDef op {op!r} (node {n.name!r}) is not supported by "
            f"the XLA lowering")

    def _static(ref: str, p) -> np.ndarray:
        nm, _ = _ref_name(ref)
        if nm in params:
            return params[nm]
        node = by_name.get(nm)
        if node is not None and node.op == "Identity":
            return _static(node.inputs[0], p)
        raise BackendError(
            f"node {ref!r} must be a compile-time constant")

    in_shapes: List[Tuple[int, ...]] = []
    in_dtypes: List[np.dtype] = []
    if wav_entry is not None:
        wn = wav_nodes[0]
        want_s = wn.attr_i("desired_samples", -1)
        want_c = max(wn.attr_i("desired_channels", -1), 1)
        in_shapes.append((max(want_s, 1), want_c))
        in_dtypes.append(np.dtype(np.float32))
    else:
        for nm in input_names:
            n = by_name.get(nm)
            if n is None:
                raise BackendError(f"input node {nm!r} not in graph")
            in_shapes.append(placeholder_shape(n))
            in_dtypes.append(np.dtype(
                _DTYPES.get(n.attr_type("dtype", 1), np.float32)))

    # outputs: shape/dtype via jax's shape-only evaluation
    import jax

    probe = [np.zeros(s, d) for s, d in zip(in_shapes, in_dtypes)]
    out_avals = jax.eval_shape(fn, params, *probe)
    out_shapes = [tuple(a.shape) for a in out_avals]
    out_dtypes = [np.dtype(a.dtype) for a in out_avals]

    m = LoweredModel(
        fn=fn, params=params,
        in_shapes=in_shapes, in_dtypes=in_dtypes,
        out_shapes=out_shapes, out_dtypes=out_dtypes,
        name="")
    m.host_pre = host_pre
    m.wav_input = wav_entry is not None
    return m
