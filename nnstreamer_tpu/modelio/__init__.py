"""Model-file ingestion (the reference's "load model= files" capability,
`tensor_filter_common.c:1208` extension auto-detect +
`tensor_filter_tensorflow_lite.cc` and friends).

Formats:
- `.tflite` — TFLite flatbuffer, parsed with a self-contained reader and
  lowered to one fused XLA computation (quantized uint8/int8 models
  dequantize → bf16; see tflite.py).
- `.npz` — this framework's own serialized params format for zoo /
  python-defined models (params_io.py).
- `.pt` — TorchScript archives (legacy model.json AND modern data.pkl
  generations), parsed from scratch and AST-lowered to one XLA
  computation (torchscript.py) — no torch needed at load time.
- `.uff` — NVIDIA/TensorRT UFF MetaGraph, protowire-decoded and lowered
  to one XLA program (uff.py).
- `.caffemodel` — Caffe NetParameter snapshots (graph + blobs in one
  file), protowire-decoded (caffe.py).
- `.dlc` — SNPE Deep Learning Container (zip of NETD/NETP
  flatbuffers), read without the SNPE SDK (dlc.py).
- `.rtm` — DeepViewRT model (RTMx flatbuffer), read without the
  vendor runtime (rtm.py).

`load_model_file(path, **opts)` dispatches on extension and returns a
`backends.xla.ModelBundle`.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio.params_io import load_params, save_params
from nnstreamer_tpu.modelio.tflite import (
    lower_tflite, parse_tflite, register_tflite_custom_op)
import nnstreamer_tpu.modelio.tflite_custom  # noqa: F401 (registers ops)

#: extensions this package can ingest → default backend
MODEL_EXTENSIONS = {"tflite": "xla", "npz": "xla", "pb": "xla",
                    "pt": "xla", "uff": "xla", "caffemodel": "xla",
                    "dlc": "xla", "rtm": "xla"}


def load_model_file(path: str, batch: Optional[int] = None,
                    compute_dtype: Optional[str] = None,
                    quantize_output: bool = True,
                    input_names=None, output_names=None,
                    sample_rate: int = 16000, side: Optional[int] = None):
    """Load a model file into a ModelBundle (extension-dispatched)."""
    from nnstreamer_tpu.backends.xla import ModelBundle
    from nnstreamer_tpu.tensor.dtypes import DType
    from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

    def mk(shapes, dtypes):
        return TensorsSpec(tensors=tuple(
            TensorInfo(shape=tuple(s), dtype=DType.from_np(d))
            for s, d in zip(shapes, dtypes)))

    if "," in path:
        # "init_net.pb,predict_net.pb" — the reference's caffe2 filter
        # model-pair syntax (tensor_filter_caffe2.cc)
        parts = [p.strip() for p in path.split(",") if p.strip()]
        if len(parts) != 2:
            raise BackendError(
                f"a comma model pair must be exactly "
                f"'init_net.pb,predict_net.pb', got {path!r}")
        for p in parts:
            if not os.path.exists(p):
                raise BackendError(f"model file {p!r} does not exist")
        if compute_dtype is not None:
            raise BackendError(
                "custom=dtype= is not consumed by caffe2 init,predict "
                "pairs (they run in the NetDef's declared dtypes); "
                "supported for .tflite and .pt")
        from nnstreamer_tpu.modelio.caffe2 import lower_caffe2

        lowered = lower_caffe2(parts[0], parts[1],
                               input_names=input_names,
                               output_names=output_names, batch=batch,
                               side=side)
        return ModelBundle(
            fn=lowered.fn, params=lowered.params,
            in_spec=mk(lowered.in_shapes, lowered.in_dtypes),
            out_spec=mk(lowered.out_shapes, lowered.out_dtypes),
            name=lowered.name)

    if not os.path.exists(path):
        raise BackendError(
            f"model file {path!r} does not exist; supported formats: "
            f"{sorted(MODEL_EXTENSIONS)}")
    ext = path.rsplit(".", 1)[-1].lower() if "." in path else ""

    if ext not in ("pb", "uff") and (input_names or output_names):
        # fail loudly rather than silently ignoring a binding request
        raise BackendError(
            f"inputname/outputname bind graph nodes and apply to "
            f".pb/.uff models only (got a .{ext} file)")
    if side is not None:
        raise BackendError(
            f"custom=side= declares a caffe2 NetDef input resolution "
            f"and applies to init,predict pairs only (got {path!r})")
    if compute_dtype is not None and ext not in ("tflite", "pt"):
        # only the tflite/.pt lowerings consume a compute dtype; the
        # rest run in the graph's own dtypes (.npz archs take dtype via
        # the arch query string instead). An allowlist so a future
        # format can't silently swallow dtype= the way round 4's
        # .uff/.caffemodel/.pb did — the loader's fail-loud convention
        # (like inputname/outputname and side above).
        raise BackendError(
            f"custom=dtype= is not consumed by .{ext} models (they run "
            f"in the graph's declared dtypes; .npz archs take "
            f"?dtype=... in the arch string); supported for .tflite "
            f"and .pt")

    if ext == "tflite":
        # per-format compute default: tflite runs bf16 (MXU-native,
        # quantized models dequantize into it); .pt runs fp32 for
        # fidelity with torch-exported weights — either is an explicit
        # custom=dtype= away
        compute_dtype = compute_dtype or "bfloat16"
        graph = parse_tflite(path)
        if compute_dtype in ("int8", "native", "auto"):
            from nnstreamer_tpu.modelio.tflite_quant import (
                lower_tflite_quant, quantized_graph_supported)
            if quantized_graph_supported(graph):
                try:
                    lowered = lower_tflite_quant(graph, batch=batch)
                except BackendError:
                    # support pre-check is necessarily approximate
                    # (e.g. per-channel weight zero points surface only
                    # during lowering); auto falls back to float
                    if compute_dtype != "auto":
                        raise
                    lowered = lower_tflite(graph, batch=batch,
                                           quantize_output=quantize_output)
            elif compute_dtype == "auto":
                lowered = lower_tflite(graph, batch=batch,
                                       quantize_output=quantize_output)
            else:
                raise BackendError(
                    f"{path!r} is not a fully-quantized graph; int8-native "
                    f"execution needs per-tensor uint8/int8 quantization "
                    f"throughout (use dtype=bfloat16)")
        else:
            lowered = lower_tflite(graph, batch=batch,
                                   compute_dtype=compute_dtype,
                                   quantize_output=quantize_output)
        return ModelBundle(
            fn=lowered.fn, params=lowered.params,
            in_spec=mk(lowered.in_shapes, lowered.in_dtypes),
            out_spec=mk(lowered.out_shapes, lowered.out_dtypes),
            name=lowered.name)

    if ext == "pb":
        from nnstreamer_tpu.modelio.graphdef import (
            lower_graphdef, parse_graphdef)

        lowered = lower_graphdef(
            parse_graphdef(path), input_names=input_names,
            output_names=output_names, batch=batch,
            sample_rate=sample_rate)
        wav = getattr(lowered, "wav_input", False)
        return ModelBundle(
            fn=lowered.fn, params=lowered.params,
            # wav-entry graphs take raw file bytes whose length is
            # pipeline-declared (reference: input=1:16022 inputtype=int16)
            in_spec=None if wav else mk(lowered.in_shapes,
                                        lowered.in_dtypes),
            out_spec=mk(lowered.out_shapes, lowered.out_dtypes),
            name=os.path.basename(path),
            host_pre=getattr(lowered, "host_pre", None))

    if ext == "pt":
        from nnstreamer_tpu.modelio.torchscript import lower_torchscript

        lowered = lower_torchscript(
            path, compute_dtype=compute_dtype or "float32")
        # TorchScript archives carry no input shape metadata (like the
        # reference, dims are pipeline-declared: tensor_filter_pytorch
        # gets them from caps); specs resolve via set_input_info
        return ModelBundle(fn=lowered.fn, params=lowered.params,
                           in_spec=None, out_spec=None,
                           name=os.path.basename(path))

    if ext == "uff":
        from nnstreamer_tpu.modelio.uff import lower_uff, parse_uff

        lowered = lower_uff(parse_uff(path), input_names=input_names,
                            output_names=output_names)
        # UFF carries no input shape (reference: pipeline-declared
        # dims); fn is NHWC shape-polymorphic, specs negotiate from caps
        return ModelBundle(fn=lowered.fn, params=lowered.params,
                           in_spec=None, out_spec=None,
                           name=os.path.basename(path))

    if ext == "dlc":
        from nnstreamer_tpu.modelio.dlc import lower_dlc, parse_dlc

        lowered = lower_dlc(parse_dlc(path), batch=batch)
        return ModelBundle(
            fn=lowered.fn, params=lowered.params,
            in_spec=mk(lowered.in_shapes, lowered.in_dtypes),
            out_spec=mk(lowered.out_shapes, lowered.out_dtypes),
            name=os.path.basename(path))

    if ext == "rtm":
        from nnstreamer_tpu.modelio.rtm import lower_rtm, parse_rtm

        lowered = lower_rtm(parse_rtm(path), batch=batch)
        return ModelBundle(
            fn=lowered.fn, params=lowered.params,
            in_spec=mk(lowered.in_shapes, lowered.in_dtypes),
            out_spec=mk(lowered.out_shapes, lowered.out_dtypes),
            name=os.path.basename(path))

    if ext == "caffemodel":
        from nnstreamer_tpu.modelio.caffe import (
            lower_caffe, parse_caffemodel)

        lowered = lower_caffe(parse_caffemodel(path), batch=batch)
        return ModelBundle(
            fn=lowered.fn, params=lowered.params,
            in_spec=mk(lowered.in_shapes, lowered.in_dtypes),
            out_spec=mk(lowered.out_shapes, lowered.out_dtypes),
            name=os.path.basename(path))

    if ext == "npz":
        arch, params = load_params(path)
        from nnstreamer_tpu.backends.xla import XLABackend
        bundle = XLABackend()._resolve(arch)
        bundle.params = params
        bundle.name = f"{os.path.basename(path)}({arch})"
        return bundle

    raise BackendError(
        f"unsupported model file extension {ext!r} for {path!r}; "
        f"supported: {sorted(MODEL_EXTENSIONS)}")


def parse_loader_opts(custom: str) -> Dict[str, Any]:
    """Parse the filter's `custom=` option string into loader options
    (reference custom-prop analog): "batch=8,dtype=float32,
    quantize_output=false"."""
    opts: Dict[str, Any] = {}
    for part in (custom or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        k = k.strip()
        v = v.strip()
        if k == "batch":
            try:
                opts["batch"] = int(v)
            except ValueError:
                raise BackendError(
                    f"custom option batch={v!r} is not an integer") from None
        elif k in ("dtype", "compute_dtype"):
            opts["compute_dtype"] = v
        elif k == "quantize_output":
            opts["quantize_output"] = v.lower() in ("1", "true", "yes")
        elif k == "dynamic_spatial":
            # consumed by XLABackend (flexible-shape spatial bucketing),
            # not by the file loaders
            opts["dynamic_spatial"] = v.lower() in ("1", "true", "yes")
        elif k in ("inputname", "input_names"):
            opts["input_names"] = [s for s in v.split(";") if s]
        elif k in ("outputname", "output_names"):
            opts["output_names"] = [s for s in v.split(";") if s]
        elif k == "side":
            # caffe2 NetDef input spatial size (pixels per side)
            try:
                opts["side"] = int(v)
            except ValueError:
                raise BackendError(
                    f"custom option side={v!r} is not an integer") \
                    from None
        elif k == "sample_rate":
            try:
                opts["sample_rate"] = int(v)
            except ValueError:
                raise BackendError(
                    f"custom option sample_rate={v!r} is not an "
                    f"integer") from None
    return opts


__all__ = ["load_model_file", "load_params", "save_params",
           "parse_tflite", "lower_tflite", "parse_loader_opts",
           "register_tflite_custom_op", "MODEL_EXTENSIONS"]
