"""Caffe ``.caffemodel`` ingestion — NetParameter wire reader → JAX.

Reference parity: the reference runs Caffe models through the armnn
filter's CaffeParser (``ext/nnstreamer/tensor_filter/
tensor_filter_armnn.cc``; golden: ``tests/nnstreamer_filter_armnn/
unittest_filter_armnn.cc:580`` runs ``lenet_iter_9000.caffemodel`` on
``9.raw`` and expects argmax 9).  Here the NetParameter protobuf is
decoded with the repo's dependency-free ``protowire`` reader (same
approach as the GraphDef/caffe2 importers) and lowered to ONE fused XLA
computation: the ``.caffemodel`` snapshot carries both the layer graph
and the learned blobs, so no sidecar ``.prototxt`` is needed.

Layer set: the inference closure of classic Caffe classifiers —
Input, Convolution, Pooling (MAX/AVE, Caffe's CEIL output rule),
InnerProduct, ReLU/TanH/Sigmoid, Softmax, LRN, Dropout (inference
no-op), Concat, Eltwise, Flatten, Split.  Unknown layers raise with
the layer type (never silently wrong).

Data layout is Caffe-native NCHW; conv blobs are OIHW, IP blobs
(out, in) — all MXU-friendly shapes under XLA.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import protowire as pw

# NetParameter
_NP_NAME, _NP_LAYER_V2 = 1, 100
# LayerParameter
_L_NAME, _L_TYPE, _L_BOTTOM, _L_TOP, _L_PHASE, _L_BLOBS = 1, 2, 3, 4, 10, 7
_L_CONV, _L_IP, _L_POOL, _L_INPUT, _L_LRN = 106, 117, 121, 143, 118
_L_DROPOUT, _L_CONCAT, _L_ELTWISE = 108, 104, 110
# BlobProto
_B_NUM, _B_CH, _B_H, _B_W, _B_DATA, _B_SHAPE = 1, 2, 3, 4, 5, 7
# ConvolutionParameter
_C_NUM_OUT, _C_BIAS, _C_PAD, _C_KERNEL, _C_GROUP, _C_STRIDE = 1, 2, 3, 4, 5, 6
_C_PAD_H, _C_PAD_W, _C_KERNEL_H, _C_KERNEL_W = 9, 10, 11, 12
_C_STRIDE_H, _C_STRIDE_W, _C_DILATION = 13, 14, 18
# PoolingParameter
_P_POOL, _P_KERNEL, _P_STRIDE, _P_PAD = 1, 2, 3, 4
_P_KERNEL_H, _P_KERNEL_W, _P_STRIDE_H, _P_STRIDE_W = 5, 6, 7, 8
_P_PAD_H, _P_PAD_W, _P_GLOBAL = 9, 10, 12
# InnerProductParameter
_IP_NUM_OUT, _IP_BIAS, _IP_AXIS, _IP_TRANSPOSE = 1, 2, 5, 6
# LRNParameter
_LRN_SIZE, _LRN_ALPHA, _LRN_BETA, _LRN_K = 1, 2, 3, 5


@dataclass
class CaffeLayer:
    name: str
    type: str
    bottoms: List[str]
    tops: List[str]
    blobs: List[np.ndarray]
    params: Dict[int, Any]


@dataclass
class CaffeNet:
    name: str
    layers: List[CaffeLayer]


def _decode_blob(buf: bytes) -> np.ndarray:
    d = pw.fields_dict(buf)
    vals = d.get(_B_DATA, [])
    if len(vals) == 1 and isinstance(vals[0], bytes):    # packed floats
        data = np.frombuffer(vals[0], "<f4")
    else:   # proto2 unpacked: one fixed32 per element
        data = np.asarray(vals, np.uint32).view(np.float32)
    shape_msg = pw.first(d, _B_SHAPE)
    if shape_msg is not None:
        dims = _shape_dims(shape_msg)
    else:   # legacy num/channels/height/width
        dims = [int(pw.first(d, f, 1) or 1)
                for f in (_B_NUM, _B_CH, _B_H, _B_W)]
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    return data.reshape([int(x) for x in dims])


def _shape_dims(shape_msg: bytes) -> List[int]:
    vals = pw.fields_dict(shape_msg).get(1, [])
    if len(vals) == 1 and isinstance(vals[0], bytes):
        return [int(x) for x in pw.packed_varints(vals[0])]
    return [int(x) for x in vals]


def parse_caffemodel(path: str) -> CaffeNet:
    with open(path, "rb") as f:
        raw = f.read()
    with pw.wire_context(f"caffemodel {path!r}", BackendError):
        return _parse_caffemodel(raw, path)


def _parse_caffemodel(raw: bytes, path: str) -> CaffeNet:
    d = pw.fields_dict(raw)
    if _NP_LAYER_V2 not in d:
        raise BackendError(
            f"{path!r}: no LayerParameter entries — V0/V1 (pre-2014) "
            f"caffemodel snapshots are not supported; re-export with a "
            f"modern Caffe")
    layers: List[CaffeLayer] = []
    for lb in d[_NP_LAYER_V2]:
        ld = pw.fields_dict(lb)
        layers.append(CaffeLayer(
            name=pw.first(ld, _L_NAME, b"").decode(),
            type=pw.first(ld, _L_TYPE, b"").decode(),
            bottoms=[b.decode() for b in ld.get(_L_BOTTOM, [])],
            tops=[t.decode() for t in ld.get(_L_TOP, [])],
            blobs=[_decode_blob(b) for b in ld.get(_L_BLOBS, [])],
            params={f: ld[f] for f in (_L_CONV, _L_IP, _L_POOL,
                                       _L_INPUT, _L_LRN, _L_DROPOUT,
                                       _L_CONCAT, _L_ELTWISE)
                    if f in ld}))
    return CaffeNet(name=pw.first(d, _NP_NAME, b"").decode()
                    or os.path.basename(path), layers=layers)


def _rep_int(d, field, default) -> int:
    v = pw.first(d, field)
    return int(v) if v is not None else default


def _hw(d, f_single, f_h, f_w, default) -> Tuple[int, int]:
    h = pw.first(d, f_h)
    w = pw.first(d, f_w)
    if h is not None or w is not None:
        return int(h or default), int(w or default)
    s = _rep_int(d, f_single, default)
    return s, s


def _pool2d(jnp_mod, x, kind: str, k, s, p):
    """Caffe pooling: output size uses CEIL, then the clip rule — the
    last window must start inside the image + left pad
    (pooling_layer.cpp: decrement when (out-1)*stride >= size+pad).
    Padding-high is derived from the exact output count, with the
    identity value so the overhang never wins."""
    import jax.numpy as jnp
    from jax import lax

    pads = []
    for i in range(2):
        size = x.shape[2 + i]
        out = -(-(size + 2 * p[i] - k[i]) // s[i]) + 1   # ceil
        if p[i] and (out - 1) * s[i] >= size + p[i]:
            out -= 1
        hi = max((out - 1) * s[i] + k[i] - size - p[i], 0)
        pads.append((p[i], hi))
    if kind == "max":
        lo = (jnp.finfo(x.dtype).min
              if jnp.issubdtype(x.dtype, jnp.floating)
              else jnp.iinfo(x.dtype).min)
        return lax.reduce_window(
            x, lo, lax.max, (1, 1) + tuple(k), (1, 1) + tuple(s),
            ((0, 0), (0, 0)) + tuple(pads))
    acc = lax.reduce_window(
        x, np.array(0, x.dtype), lax.add, (1, 1) + tuple(k),
        (1, 1) + tuple(s), ((0, 0), (0, 0)) + tuple(pads))
    # caffe AVE divides by the full kernel area (padding included)
    return acc / float(np.prod(k))


def lower_caffe(net: CaffeNet, batch: Optional[int] = None,
                in_shape: Optional[Tuple[int, ...]] = None):
    """CaffeNet → LoweredModel-style callable fn(params, x) -> outputs.

    The single XLA computation covers the whole net (TEST phase): train
    -only layers (Data/loss/accuracy) are skipped, in-place activations
    resolve through the blob dict exactly like Caffe's top/bottom
    aliasing."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.modelio.tflite import LoweredModel

    params: Dict[str, List[np.ndarray]] = {}
    input_name = None
    input_shape = in_shape
    deploy: List[CaffeLayer] = []
    for layer in net.layers:
        if layer.type in ("Data", "HDF5Data", "ImageData", "Accuracy",
                          "SoftmaxWithLoss", "EuclideanLoss", "Silence"):
            continue
        if layer.type == "Input":
            input_name = layer.tops[0]
            ip = layer.params.get(_L_INPUT)
            if ip and input_shape is None:
                shp = pw.first(pw.fields_dict(ip[0]), 1)
                if shp is not None:
                    input_shape = tuple(_shape_dims(shp))
            continue
        if layer.blobs:
            params[layer.name] = [np.asarray(b, np.float32)
                                  for b in layer.blobs]
        deploy.append(layer)
    if input_name is None:
        if not deploy:
            raise BackendError("caffemodel has no computable layers")
        input_name = deploy[0].bottoms[0]
    if input_shape is None:
        raise BackendError(
            "caffemodel declares no Input layer shape (train-phase "
            "snapshot?); re-export merged with the deploy prototxt so "
            "the Input layer carries input_param { shape }, or call "
            "lower_caffe(net, in_shape=...) directly")
    if batch:
        input_shape = (batch,) + tuple(input_shape[1:])

    def fn(p, x):
        from jax import lax

        blobs: Dict[str, Any] = {input_name: x.astype(jnp.float32)}

        def get(name):
            if name not in blobs:
                raise BackendError(
                    f"caffe: blob {name!r} undefined (net is not "
                    f"topologically ordered?)")
            return blobs[name]

        for layer in deploy:
            t = layer.type
            w = p.get(layer.name, [])
            if t == "Convolution":
                cd = pw.fields_dict(layer.params[_L_CONV][0])
                kh, kw = _hw(cd, _C_KERNEL, _C_KERNEL_H, _C_KERNEL_W, 1)
                sh, sw = _hw(cd, _C_STRIDE, _C_STRIDE_H, _C_STRIDE_W, 1)
                ph, pmw = _hw(cd, _C_PAD, _C_PAD_H, _C_PAD_W, 0)
                group = _rep_int(cd, _C_GROUP, 1)
                dil = _rep_int(cd, _C_DILATION, 1)
                out = lax.conv_general_dilated(
                    get(layer.bottoms[0]), jnp.asarray(w[0]),
                    window_strides=(sh, sw),
                    padding=((ph, ph), (pmw, pmw)),
                    rhs_dilation=(dil, dil),
                    dimension_numbers=("NCHW", "OIHW", "NCHW"),
                    feature_group_count=group)
                if len(w) > 1:
                    out = out + jnp.asarray(w[1]).reshape(1, -1, 1, 1)
            elif t == "Pooling":
                pd = pw.fields_dict(layer.params[_L_POOL][0])
                x_in = get(layer.bottoms[0])
                if pw.first(pd, _P_GLOBAL):
                    k = (x_in.shape[2], x_in.shape[3])
                    s, pad = (1, 1), (0, 0)
                else:
                    k = _hw(pd, _P_KERNEL, _P_KERNEL_H, _P_KERNEL_W, 1)
                    s = _hw(pd, _P_STRIDE, _P_STRIDE_H, _P_STRIDE_W, 1)
                    pad = _hw(pd, _P_PAD, _P_PAD_H, _P_PAD_W, 0)
                pool_enum = _rep_int(pd, _P_POOL, 0)
                if pool_enum not in (0, 1):
                    raise BackendError(
                        f"caffe Pooling method enum {pool_enum} "
                        f"({layer.name}) has no jax lowering (MAX/AVE "
                        f"only; STOCHASTIC is train-time sampling)")
                kind = "max" if pool_enum == 0 else "ave"
                out = _pool2d(jnp, x_in, kind, k, s, pad)
            elif t == "InnerProduct":
                x_in = get(layer.bottoms[0])
                ipd = pw.fields_dict(layer.params[_L_IP][0]) \
                    if _L_IP in layer.params else {}
                axis = _rep_int(ipd, _IP_AXIS, 1)
                transpose = bool(pw.first(ipd, _IP_TRANSPOSE, 0))
                lead = int(np.prod(x_in.shape[:axis])) if axis else 1
                flat = x_in.reshape(lead, -1)
                wm = jnp.asarray(w[0])
                out = flat @ (wm if transpose else wm.T)
                if len(w) > 1:
                    out = out + jnp.asarray(w[1]).reshape(1, -1)
            elif t == "ReLU":
                out = jax.nn.relu(get(layer.bottoms[0]))
            elif t == "TanH":
                out = jnp.tanh(get(layer.bottoms[0]))
            elif t == "Sigmoid":
                out = jax.nn.sigmoid(get(layer.bottoms[0]))
            elif t == "Softmax":
                out = jax.nn.softmax(get(layer.bottoms[0]), axis=1)
            elif t == "Dropout":
                out = get(layer.bottoms[0])     # inference no-op
            elif t == "Flatten":
                x_in = get(layer.bottoms[0])
                out = x_in.reshape(x_in.shape[0], -1)
            elif t == "Concat":
                out = jnp.concatenate([get(b) for b in layer.bottoms],
                                      axis=1)
            elif t == "Eltwise":
                xs = [get(b) for b in layer.bottoms]
                op = 1     # default SUM
                coeffs = None
                ep = layer.params.get(_L_ELTWISE)
                if ep:
                    ed = pw.fields_dict(ep[0])
                    op = _rep_int(ed, 1, 1)
                    if 2 in ed:   # coeff (repeated float, SUM only)
                        coeffs = [pw.fixed32_to_float(v)
                                  for v in ed[2]]
                if coeffs is not None:
                    if op != 1:
                        raise BackendError(
                            f"caffe Eltwise ({layer.name}): coeff with "
                            f"non-SUM operation is invalid")
                    if len(coeffs) != len(xs):
                        raise BackendError(
                            f"caffe Eltwise ({layer.name}): "
                            f"{len(coeffs)} coeffs for {len(xs)} "
                            f"bottoms")
                    out = coeffs[0] * xs[0]
                    for c, other in zip(coeffs[1:], xs[1:]):
                        out = out + c * other
                else:
                    out = xs[0]
                    for other in xs[1:]:
                        out = (out * other if op == 0 else
                               out + other if op == 1 else
                               jnp.maximum(out, other))
            elif t == "LRN":
                ld = pw.fields_dict(layer.params[_L_LRN][0])
                if _rep_int(ld, 4, 0) != 0:    # norm_region
                    raise BackendError(
                        f"caffe LRN ({layer.name}): WITHIN_CHANNEL "
                        f"norm_region has no jax lowering "
                        f"(ACROSS_CHANNELS only)")
                size = _rep_int(ld, _LRN_SIZE, 5)
                alpha = pw.fixed32_to_float(
                    pw.first(ld, _LRN_ALPHA, 0)) or 1.0
                beta = pw.fixed32_to_float(
                    pw.first(ld, _LRN_BETA, 0)) or 0.75
                kk = pw.first(ld, _LRN_K)
                kk = pw.fixed32_to_float(kk) if kk is not None else 1.0
                x_in = get(layer.bottoms[0])
                sq = jnp.square(x_in)
                half = size // 2
                padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0),
                                      (0, 0)))
                window = sum(
                    padded[:, i:i + x_in.shape[1]] for i in range(size))
                out = x_in / jnp.power(kk + alpha / size * window, beta)
            elif t == "Split":
                out = get(layer.bottoms[0])
                for top in layer.tops:
                    blobs[top] = out
                continue
            else:
                raise BackendError(
                    f"caffe layer type {t!r} ({layer.name}) has no jax "
                    f"lowering")
            blobs[layer.tops[0]] = out
        # outputs: tops never consumed as a bottom downstream
        consumed = {b for lyr in deploy for b in lyr.bottoms}
        outs = [blobs[lyr.tops[0]] for lyr in deploy
                if lyr.tops and lyr.tops[0] not in consumed
                and lyr.tops[0] in blobs]
        return tuple(outs) if outs else (out,)

    probe = jax.eval_shape(
        fn, params, jax.ShapeDtypeStruct(tuple(input_shape), np.float32))
    return LoweredModel(
        fn=fn, params=params,
        in_shapes=[tuple(int(s) for s in input_shape)],
        in_dtypes=[np.dtype(np.float32)],
        out_shapes=[tuple(a.shape) for a in probe],
        out_dtypes=[np.dtype(a.dtype) for a in probe],
        name=net.name or "caffe")
