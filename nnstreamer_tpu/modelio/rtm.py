"""DeepViewRT `.rtm` ingestion.

The reference runs RTM models through the Au-Zone DeepViewRT runtime
(`ext/nnstreamer/tensor_filter/tensor_filter_deepview_rt.cc:595` loads
the container via nn_model APIs); this module reads the container
itself — no vendor runtime — and lowers the graph to one XLA
computation, like every other `modelio` front-end.

Container layout (reversed from the reference's own checked-in
`mobilenet_v1_0.25_224.rtm`, "DeepViewRT 2.4.38 for Python"):

- A FlatBuffer with file identifier ``RTMx`` (root table at the front,
  data growing toward EOF).
- Root: fid1 = creator string, fid3 = name, fid8 = [Layer],
  fid15/fid16 = inline scalars (version words).
- Layer: fid0 = name, fid2 = type (u16: 1 = Input, 2 = Const,
  0x3c = Conv2D, 0x3d = Pool, 0x65 = Reshape, 0x2c = Softmax),
  fid3 = [i32 input layer index], fid4 = [Attr table],
  fid5 = [i32 output shape, NHWC], fid9 = dtype tag (u8, 11 = f32).
- Attr: fid0 = name, fid1 = [i32] values (strides/dilations/ksize/
  head/tail/shape/axes; for the Const layer's single ``data`` attr
  fid1 is the tensor shape), fid2 = [f32] tensor data, fid3 = [i32]
  override values (``groups`` stores its real value here — fid1 is 1
  even for depthwise layers).
- Weight layouts are TF-native: HWIO for regular convolutions,
  HWCM (kh, kw, C, mult) for depthwise (groups == C).

Activation and pooling kind are stored as strings inside a per-layer
serialized record blob whose addressing is not consistently decodable;
they are instead inferred from the converter's terminal-op layer
naming (`.../Relu6` → relu6, `.../Relu` → relu, otherwise linear;
`AvgPool`/`MaxPool` for pooling) — the TF exporter names each fused
layer after its last op, and the in-env golden pins the semantics:
orange.png → "orange", the exact expectation of
`tests/nnstreamer_filter_deepview_rt/runTest.sh:72-75`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from struct import error as struct_error
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio.flatbuf import Reader

#: Layer.type values (fid2, u16)
_T_INPUT = 0x01
_T_CONST = 0x02
_T_SOFTMAX = 0x2C
_T_CONV = 0x3C
_T_POOL = 0x3D
_T_RESHAPE = 0x65

_TYPE_NAMES = {_T_INPUT: "Input", _T_CONST: "Const", _T_CONV: "Conv2D",
               _T_POOL: "Pool", _T_RESHAPE: "Reshape",
               _T_SOFTMAX: "Softmax"}


@dataclass
class RTMLayer:
    index: int
    name: str
    type: int
    inputs: List[int]
    shape: Tuple[int, ...]
    attrs: Dict[str, List[int]] = field(default_factory=dict)
    tensor: Optional[np.ndarray] = None

    @property
    def type_name(self) -> str:
        return _TYPE_NAMES.get(self.type, f"type_{self.type:#x}")


@dataclass
class RTMGraph:
    creator: str
    layers: List[RTMLayer]


def parse_rtm(path: str) -> RTMGraph:
    """Parse a .rtm flatbuffer into a graph description (host side)."""
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < 8 or buf[4:8] != b"RTMx":
        raise BackendError(
            f"{path!r} is not a DeepViewRT model (missing RTMx "
            f"identifier)")
    try:
        return _parse(buf)
    except (IndexError, ValueError, UnicodeDecodeError,
            struct_error, MemoryError) as e:
        raise BackendError(f"rtm {path!r}: malformed flatbuffer: {e}")


def _parse(buf: bytes) -> RTMGraph:
    r = Reader(buf)
    root = r.root()
    layers: List[RTMLayer] = []
    for i, t in enumerate(r.field_vec_tables(root, 8)):
        typ = r.field_scalar(t, 2, "<H", 0)
        ins = r.field_vec_scalars(t, 3, np.int32)
        shape = r.field_vec_scalars(t, 5, np.int32)
        attrs: Dict[str, List[int]] = {}
        tensor = None
        for at in r.field_vec_tables(t, 4):
            aname = r.field_string(at, 0) or ""
            i1 = r.field_vec_scalars(at, 1, np.int32)
            f2 = r.field_vec_scalars(at, 2, np.float32)
            i3 = r.field_vec_scalars(at, 3, np.int32)
            if aname == "data" and f2 is not None:
                # no shape vector = a legal flat tensor
                ts = [int(x) for x in i1] if i1 is not None \
                    else [int(f2.size)]
                if f2.size != int(np.prod(ts)):
                    raise BackendError(
                        f"rtm: const layer {i} data has {f2.size} "
                        f"elements for shape {ts}")
                tensor = np.asarray(f2).reshape(ts)
            elif i3 is not None and i3.size:
                # the real value slot when present (e.g. groups)
                attrs[aname] = [int(x) for x in i3]
            elif i1 is not None:
                attrs[aname] = [int(x) for x in i1]
        layers.append(RTMLayer(
            index=i, name=r.field_string(t, 0) or f"layer{i}",
            type=typ,
            inputs=[int(x) for x in ins] if ins is not None else [],
            shape=tuple(int(x) for x in shape)
            if shape is not None else (),
            attrs=attrs, tensor=tensor))
    return RTMGraph(creator=r.field_string(root, 1) or "", layers=layers)


def _activation(name: str):
    """Terminal-op naming → activation (see module docstring)."""
    import jax.numpy as jnp

    tail = name.rsplit("/", 1)[-1].lower()
    if "relu6" in tail:
        return lambda x: jnp.clip(x, 0.0, 6.0)
    if "relu" in tail:
        return lambda x: jnp.maximum(x, 0.0)
    return lambda x: x


def _pad2d(attrs: Dict[str, List[int]]):
    head = attrs.get("head", [0, 0, 0, 0])
    tail = attrs.get("tail", [0, 0, 0, 0])
    return ((head[1], tail[1]), (head[2], tail[2]))


def lower_rtm(graph: RTMGraph, batch: Optional[int] = None):
    """RTMGraph → LoweredModel: one XLA computation, NHWC throughout."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from nnstreamer_tpu.modelio.tflite import LoweredModel

    by_index = {lay.index: lay for lay in graph.layers}
    params: Dict[str, np.ndarray] = {}
    input_layers: List[RTMLayer] = []
    compute: List[RTMLayer] = []
    for lay in graph.layers:
        if lay.type == _T_INPUT:
            input_layers.append(lay)
        elif lay.type == _T_CONST:
            if lay.tensor is None:
                raise BackendError(
                    f"rtm: Const layer {lay.name!r} has no data attr")
            params[str(lay.index)] = lay.tensor
        else:
            compute.append(lay)
    if not input_layers:
        raise BackendError("rtm: graph declares no Input layer")
    if not compute:
        raise BackendError("rtm: graph has no computable layers")

    in_shapes = []
    for lay in input_layers:
        shape = tuple(lay.shape) or (1,)
        if batch:
            if len(shape) < 2:
                raise BackendError(
                    f"rtm: batch override needs a rank>=2 input; "
                    f"{lay.name!r} has shape {shape}")
            shape = (batch,) + shape[1:]
        in_shapes.append(shape)

    consumed = {i for lay in compute for i in lay.inputs}
    out_layers = [lay for lay in compute if lay.index not in consumed]
    if not out_layers:
        out_layers = [compute[-1]]

    def fn(p, *xs):
        blobs: Dict[int, Any] = {}
        for lay, x in zip(input_layers, xs):
            blobs[lay.index] = x.astype(jnp.float32)

        def get(i):
            if i in blobs:
                return blobs[i]
            lay = by_index.get(i)
            if lay is not None and lay.type == _T_CONST:
                blobs[i] = jnp.asarray(p[str(i)], jnp.float32)
                return blobs[i]
            raise BackendError(
                f"rtm: layer input {i} undefined (graph not "
                f"topologically ordered?)")

        for lay in compute:
            if lay.type == _T_CONV:
                if len(lay.inputs) not in (2, 3):
                    raise BackendError(
                        f"rtm: Conv2D {lay.name!r} expects "
                        f"(input, weights[, bias]) — got "
                        f"{len(lay.inputs)} inputs")
                x = get(lay.inputs[0])
                w = get(lay.inputs[1])
                groups = lay.attrs.get("groups", [1])[0]
                strides = lay.attrs.get("strides", [1, 1, 1, 1])
                dil = lay.attrs.get("dilations", [1, 1, 1, 1])
                if groups > 1:
                    # HWCM depthwise layout → HWIO with I = C/groups
                    kh, kw, c, m = w.shape
                    if c != groups:
                        raise BackendError(
                            f"rtm: depthwise {lay.name!r} kernel "
                            f"channels {c} != groups {groups}")
                    w = w.reshape(kh, kw, 1, c * m)
                y = lax.conv_general_dilated(
                    x, w, window_strides=tuple(strides[1:3]),
                    padding=_pad2d(lay.attrs),
                    rhs_dilation=tuple(dil[1:3]),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=groups)
                if len(lay.inputs) == 3:
                    y = y + get(lay.inputs[2]).reshape(1, 1, 1, -1)
                blobs[lay.index] = _activation(lay.name)(y)
            elif lay.type == _T_POOL:
                x = get(lay.inputs[0])
                ksize = lay.attrs.get("ksize", [1, 1, 1, 1])
                strides = lay.attrs.get("strides", [1, 1, 1, 1])
                is_avg = "max" not in lay.name.rsplit("/", 1)[-1].lower()
                kh, kw = ksize[1], ksize[2]
                if (kh, kw) == x.shape[1:3]:
                    # global pool — one fused reduce, no window op
                    red = jnp.mean if is_avg else jnp.max
                    blobs[lay.index] = red(x, axis=(1, 2),
                                           keepdims=True)
                else:
                    pad = ((0, 0),) + _pad2d(lay.attrs) + ((0, 0),)
                    win = (1, kh, kw, 1)
                    st = (1,) + tuple(strides[1:3]) + (1,)
                    if is_avg:
                        s = lax.reduce_window(x, 0.0, lax.add, win,
                                              st, pad)
                        # TF SAME avg-pool excludes padding from the
                        # divisor: count the valid cells per window
                        ones = jnp.ones(x.shape[1:3], x.dtype)
                        cnt = lax.reduce_window(
                            ones[None, :, :, None], 0.0, lax.add,
                            win, st, pad)
                        blobs[lay.index] = s / cnt
                    else:
                        blobs[lay.index] = lax.reduce_window(
                            x, -jnp.inf, lax.max, win, st, pad)
            elif lay.type == _T_RESHAPE:
                shape = lay.attrs.get("shape")
                if not shape:
                    raise BackendError(
                        f"rtm: Reshape {lay.name!r} has no shape attr")
                x = get(lay.inputs[0])
                tgt = list(shape)
                if tgt and tgt[0] == 1 and x.shape[0] != 1:
                    # the file's shape attr is batch-1; follow the
                    # traced batch (batch= override)
                    tgt[0] = x.shape[0]
                if int(np.prod(tgt)) != int(np.prod(x.shape)):
                    raise BackendError(
                        f"rtm: Reshape {lay.name!r} target {tgt} does "
                        f"not match input shape {tuple(x.shape)}")
                blobs[lay.index] = x.reshape(tgt)
            elif lay.type == _T_SOFTMAX:
                axes = lay.attrs.get("axes", [-1])
                blobs[lay.index] = jax.nn.softmax(
                    get(lay.inputs[0]), axis=axes[0])
            else:
                raise BackendError(
                    f"rtm: layer type {lay.type_name} ({lay.name!r}) "
                    f"has no lowering (supported: Input, Const, "
                    f"Conv2D, Pool, Reshape, Softmax)")
        return tuple(blobs[lay.index] for lay in out_layers)

    probe = jax.eval_shape(fn, params, *[
        jax.ShapeDtypeStruct(s, np.float32) for s in in_shapes])
    return LoweredModel(
        fn=fn, params=params,
        in_shapes=in_shapes,
        in_dtypes=[np.dtype(np.float32)] * len(in_shapes),
        out_shapes=[tuple(int(d) for d in a.shape) for a in probe],
        out_dtypes=[np.dtype(a.dtype) for a in probe],
        name="rtm")
