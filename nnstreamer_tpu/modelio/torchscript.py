"""TorchScript ``.pt`` ingestion — from-scratch, no torch at load time.

Reference parity: ``ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc``
(:775 LoC) loads TorchScript archives through libtorch and runs them on
CPU/GPU.  Here the archive is parsed directly and *lowered to one JAX
computation* instead: TorchScript's serialized code is a restricted
Python dialect, so the method bodies are parsed with :mod:`ast` and
interpreted symbolically — tensor ops become jnp/lax ops traced into the
XLA program, host scalars (shapes, flags, branch conditions) evaluate
eagerly at trace time.  The result is a single fused XLA executable per
input shape, not an op-by-op eager walk — the tpu-first answer to
libtorch's kernel-per-node execution.

Two container generations are handled, both without torch:

* **legacy** (producerVersion 1.0, ``model.json`` + ``code/*.py`` +
  ``tensors/N``) — torch ≥ 1.3 itself refuses to load these ("Legacy
  model format is not supported on mobile"), but the reference ships its
  pytorch goldens in exactly this format (pytorch_lenet5.pt), so this
  loader runs models that the *installed* torch cannot.
* **modern** (``data.pkl`` + ``constants.pkl`` + ``code/**.py`` +
  ``data/N``) — module tree unpickled with a custom
  ``pickle.Unpickler`` (``find_class``/``persistent_load`` stubs; no
  torch classes are imported).

Supported op set: the inference closure of common exported models —
conv1d/2d (+transposed, groups), linear/addmm/matmul/bmm, pooling
(max/avg/adaptive), batch/layer norm, activations, softmax, shape ops
(reshape/view/permute/transpose/cat/…), elementwise math, reductions,
top-k, embedding, interpolation.  Unsupported ops fail loud with the op
name (never silently wrong).
"""
from __future__ import annotations

import ast
import json
import os
import pickle
import zipfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError

__all__ = ["load_torchscript", "lower_torchscript", "TSProgram"]


# ---------------------------------------------------------------------------
# containers
# ---------------------------------------------------------------------------

_LEGACY_DTYPES = {
    "FLOAT": np.float32, "DOUBLE": np.float64, "HALF": np.float16,
    "INT64": np.int64, "INT32": np.int32, "INT16": np.int16,
    "INT8": np.int8, "UINT8": np.uint8, "BOOL": np.bool_,
}

_STORAGE_DTYPES = {
    "FloatStorage": np.float32, "DoubleStorage": np.float64,
    "HalfStorage": np.float16, "LongStorage": np.int64,
    "IntStorage": np.int32, "ShortStorage": np.int16,
    "CharStorage": np.int8, "ByteStorage": np.uint8,
    "BoolStorage": np.bool_,
}

# torch.dtype enum codes (aten/src/ATen/core/ScalarType) as they appear
# in serialized `torch.to(x, <int>)` / `softmax(..., dtype)` calls
_TORCH_DTYPE_CODES = {
    0: np.uint8, 1: np.int8, 2: np.int16, 3: np.int32, 4: np.int64,
    5: np.float16, 6: np.float32, 7: np.float64, 11: np.bool_,
}


class _ParamSlot:
    """Marker for a learnable tensor living in the params dict."""

    __slots__ = ("path",)

    def __init__(self, path: str):
        self.path = path


class _TSModule:
    """A deserialized module node: qualname + attribute bag."""

    __slots__ = ("qualname", "attrs")

    def __init__(self, qualname: str, attrs: Optional[dict] = None):
        self.qualname = qualname
        self.attrs = attrs if attrs is not None else {}


@dataclass
class _ClassInfo:
    qualname: str
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    consts: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TSProgram:
    root: _TSModule
    classes: Dict[str, _ClassInfo]
    functions: Dict[str, ast.FunctionDef]
    params: Dict[str, np.ndarray]
    constants: List[Any]
    name: str


def _strided_copy(flat: np.ndarray, offset: int, size, stride) -> np.ndarray:
    """Materialize a (possibly strided/offset) tensor view of a flat
    storage as a contiguous array."""
    size = tuple(int(s) for s in size)
    stride = tuple(int(s) for s in stride)
    if not size:
        return np.ascontiguousarray(flat[offset])
    it = flat.itemsize
    view = np.lib.stride_tricks.as_strided(
        flat[offset:], shape=size, strides=tuple(s * it for s in stride))
    return np.ascontiguousarray(view)


# -- modern format: custom unpickler ----------------------------------------

class _Storage:
    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array


def _rebuild_tensor_v2(storage, storage_offset, size, stride,
                       requires_grad=False, backward_hooks=None,
                       metadata=None):
    return _strided_copy(storage.array, storage_offset, size, stride)


def _rebuild_tensor(storage, storage_offset, size, stride):
    return _strided_copy(storage.array, storage_offset, size, stride)


class _StorageClass:
    __slots__ = ("dtype",)

    def __init__(self, dtype):
        self.dtype = dtype


_DYN_CLASSES: Dict[str, type] = {}


def _dyn_class(qualname: str) -> type:
    cls = _DYN_CLASSES.get(qualname)
    if cls is None:
        cls = type(qualname.rsplit(".", 1)[-1], (), {"_ts_qual": qualname})
        _DYN_CLASSES[qualname] = cls
    return cls


class _TSUnpickler(pickle.Unpickler):
    """Unpickles data.pkl / constants.pkl with torch globals stubbed out
    and storages resolved against the archive — no torch import."""

    def __init__(self, fobj, read_record: Callable[[str], bytes]):
        super().__init__(fobj)
        self._read_record = read_record

    def find_class(self, module, name):
        if module.startswith("__torch__"):
            return _dyn_class(f"{module}.{name}")
        if module == "torch._utils":
            if name == "_rebuild_tensor_v2":
                return _rebuild_tensor_v2
            if name == "_rebuild_tensor":
                return _rebuild_tensor
        if module == "torch" and name in _STORAGE_DTYPES:
            return _StorageClass(_STORAGE_DTYPES[name])
        if module == "torch" and name == "device":
            return lambda s: s
        if module == "torch.jit._pickle":
            if name in ("build_intlist", "build_doublelist",
                        "build_boollist", "build_tensorlist"):
                return lambda data: list(data)
            if name == "restore_type_tag":
                return lambda value, _type: value
        if module == "collections" and name == "OrderedDict":
            return dict
        raise BackendError(
            f"TorchScript archive references unsupported global "
            f"{module}.{name}")

    def persistent_load(self, pid):
        if not (isinstance(pid, tuple) and pid and pid[0] == "storage"):
            raise BackendError(f"unknown persistent id {pid!r}")
        _, storage_cls, key, _location, _numel = pid
        raw = self._read_record(str(key))
        return _Storage(np.frombuffer(raw, storage_cls.dtype).copy())


# ---------------------------------------------------------------------------
# code registry
# ---------------------------------------------------------------------------

def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _index_code(src: str, namespace: str,
                classes: Dict[str, _ClassInfo],
                functions: Dict[str, ast.FunctionDef]) -> None:
    tree = ast.parse(src)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            qual = f"{namespace}.{node.name}" if namespace else node.name
            ci = classes.setdefault(qual, _ClassInfo(qualname=qual))
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    ci.methods[item.name] = item
                elif isinstance(item, ast.AnnAssign) and item.value is not None \
                        and isinstance(item.target, ast.Name):
                    # `padding : Final[Tuple[int, int]] = (1, 1)` — Final
                    # attrs live only in code, not in the pickled state
                    ci.consts[item.target.id] = _literal(item.value)
                elif isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name):
                    ci.consts[item.targets[0].id] = _literal(item.value)
        elif isinstance(node, ast.FunctionDef):
            qual = f"{namespace}.{node.name}" if namespace else node.name
            functions[qual] = node


# ---------------------------------------------------------------------------
# archive loading
# ---------------------------------------------------------------------------

def load_torchscript(path: str) -> TSProgram:
    """Parse a TorchScript zip archive into a :class:`TSProgram`."""
    try:
        zf = zipfile.ZipFile(path)
    except (zipfile.BadZipFile, OSError) as e:
        raise BackendError(f"{path!r} is not a TorchScript archive: {e}") \
            from None
    names = zf.namelist()
    if not names:
        raise BackendError(f"{path!r}: empty archive")
    root = names[0].split("/", 1)[0]

    def read(rel: str) -> bytes:
        return zf.read(f"{root}/{rel}")

    def has(rel: str) -> bool:
        return f"{root}/{rel}" in names

    classes: Dict[str, _ClassInfo] = {}
    functions: Dict[str, ast.FunctionDef] = {}

    if has("model.json"):
        return _load_legacy(root, read, names, zf, classes, functions)

    # -- modern format -----------------------------------------------------
    for n in names:
        if n.startswith(f"{root}/code/") and n.endswith(".py"):
            ns = n[len(f"{root}/code/"):-3].replace("/", ".")
            try:
                _index_code(zf.read(n).decode("utf-8"), ns, classes,
                            functions)
            except SyntaxError as e:
                raise BackendError(
                    f"{path!r}: cannot parse serialized code {n}: {e}") \
                    from None

    import io

    constants: List[Any] = []
    if has("constants.pkl"):
        up = _TSUnpickler(io.BytesIO(read("constants.pkl")),
                          lambda key: read(f"constants/{key}"))
        constants = list(up.load())

    up = _TSUnpickler(io.BytesIO(read("data.pkl")),
                      lambda key: read(f"data/{key}"))
    obj = up.load()

    params: Dict[str, np.ndarray] = {}

    seen_arrays: Dict[int, _ParamSlot] = {}

    def convert(node, prefix: str) -> Any:
        if isinstance(node, np.ndarray):
            # the pickler memoizes: e.g. nn.LSTM's weight_ih_l0 and
            # _flat_weights[0] unpickle to the SAME array — one slot,
            # not two copies in the params dict
            slot = seen_arrays.get(id(node))
            if slot is None:
                params[prefix] = node
                slot = seen_arrays[id(node)] = _ParamSlot(prefix)
            return slot
        qual = getattr(type(node), "_ts_qual", None)
        if qual is not None:
            mod = _TSModule(qual)
            for k, v in vars(node).items():
                mod.attrs[k] = convert(v, f"{prefix}.{k}" if prefix else k)
            return mod
        if isinstance(node, (list, tuple)):
            return type(node)(
                convert(v, f"{prefix}.{i}") for i, v in enumerate(node))
        return node

    root_mod = convert(obj, "")
    if not isinstance(root_mod, _TSModule):
        raise BackendError(
            f"{path!r}: data.pkl root is not a script module")
    return TSProgram(root=root_mod, classes=classes, functions=functions,
                     params=params, constants=constants,
                     name=os.path.basename(path))


def _load_legacy(root, read, names, zf, classes, functions) -> TSProgram:
    """producerVersion-1.0 archives: model.json module tree +
    tensors/N raw storages + per-module code arenas."""
    meta = json.loads(read("model.json"))
    tensors_meta = meta.get("tensors", [])
    params: Dict[str, np.ndarray] = {}

    def load_tensor(idx: int) -> np.ndarray:
        t = tensors_meta[idx]
        dt = _LEGACY_DTYPES.get(t.get("dataType"))
        if dt is None:
            raise BackendError(
                f"legacy TorchScript tensor dataType "
                f"{t.get('dataType')!r} unsupported")
        flat = np.frombuffer(read(t["data"]["key"]), dt).copy()
        return _strided_copy(flat, int(t.get("offset", 0)),
                             [int(d) for d in t.get("dims", [])],
                             [int(s) for s in t.get("strides", [])])

    for n in names:
        if n.startswith(f"{root}/code/") and n.endswith(".py"):
            arena = n[len(root) + 1:]          # "code/xxx.py"
            src = zf.read(n).decode("utf-8")
            ci = _ClassInfo(qualname=arena)
            tree = ast.parse(src)
            for node in tree.body:
                if isinstance(node, ast.FunctionDef):
                    ci.methods[node.name] = node
            classes[arena] = ci

    def build(node: dict, prefix: str) -> _TSModule:
        arena = node.get("torchscriptArena", {}).get("key", "")
        mod = _TSModule(arena or f"<legacy:{node.get('name', '?')}>")
        for p in node.get("parameters", []):
            pname = p["name"]
            path = f"{prefix}.{pname}" if prefix else pname
            params[path] = load_tensor(int(p["tensorId"]))
            mod.attrs[pname] = _ParamSlot(path)
        for sub in node.get("submodules", []):
            sname = sub["name"]
            mod.attrs[sname] = build(
                sub, f"{prefix}.{sname}" if prefix else sname)
        mod.attrs.setdefault("training", False)
        return mod

    root_mod = build(meta["mainModule"], "")
    return TSProgram(root=root_mod, classes=classes, functions=functions,
                     params=params, constants=[],
                     name=meta["mainModule"].get("name", root))


# ---------------------------------------------------------------------------
# interpreter
# ---------------------------------------------------------------------------

class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _AnyType:
    """Stand-in for typing names in serialized annotations/`annotate`
    calls — subscriptable, attribute-chainable, never executed."""

    def __getitem__(self, _):
        return self

    def __getattr__(self, _):
        return self

    def __call__(self, *a, **k):
        raise BackendError("TorchScript type expression is not callable")


_ANYTYPE = _AnyType()


class _NSNode:
    """Lazy resolver for dotted `__torch__...` references."""

    __slots__ = ("interp", "prefix")

    def __init__(self, interp: "_Interp", prefix: str):
        self.interp = interp
        self.prefix = prefix


class _TorchNS:
    __slots__ = ("interp",)

    def __init__(self, interp):
        self.interp = interp


class _OpsNS:
    __slots__ = ("interp", "space")

    def __init__(self, interp, space: str):
        self.interp = interp
        self.space = space


class _ConstantsNS:
    __slots__ = ("interp",)

    def __init__(self, interp):
        self.interp = interp


def _is_tensor(v) -> bool:
    import jax

    return isinstance(v, (np.ndarray, jax.Array)) or hasattr(v, "aval")


class _Interp:
    """Trace-time evaluator for serialized TorchScript method bodies."""

    def __init__(self, prog: TSProgram, params: Dict[str, Any],
                 float_dtype):
        self.prog = prog
        self.params = params
        self.fdt = float_dtype
        self.ops = _make_torch_ops(self)
        self.prims = _make_prim_ops(self)
        import jax.numpy as jnp

        self.jnp = jnp
        self.globals: Dict[str, Any] = {
            "torch": _TorchNS(self),
            "ops": _OpsNS(self, "ops"),
            "CONSTANTS": _ConstantsNS(self),
            "annotate": lambda _t, v: v,
            "unchecked_cast": lambda _t, v: v,
            "uninitialized": lambda _t: None,
            "int": int, "float": float, "bool": bool, "str": str,
            "len": len, "range": range, "min": min, "max": max,
            "abs": abs, "print": lambda *a, **k: None,
            # traced Sequential containers address numeric submodule
            # names via getattr(self, "0"); honor an explicit default
            "getattr": lambda obj, name, *d: self._try_getattr(
                obj, name, d),
            "Optional": _ANYTYPE, "List": _ANYTYPE, "Tuple": _ANYTYPE,
            "Dict": _ANYTYPE, "Final": _ANYTYPE, "Tensor": _ANYTYPE,
            "NoneType": _ANYTYPE, "Any": _ANYTYPE, "number": _ANYTYPE,
            "Module": _ANYTYPE,
            "__torch__": _NSNode(self, "__torch__"),
        }

    # -- entry --------------------------------------------------------------
    def call_method(self, mod: _TSModule, name: str, args: tuple):
        ci = self.prog.classes.get(mod.qualname)
        if ci is None or name not in ci.methods:
            raise BackendError(
                f"TorchScript method {mod.qualname}.{name} has no "
                f"serialized code")
        return self.call_function(ci.methods[name], (mod,) + tuple(args))

    def call_function(self, fd: ast.FunctionDef, args: tuple):
        env: Dict[str, Any] = {}
        names = [a.arg for a in fd.args.args]
        defaults = fd.args.defaults
        required = len(names) - len(defaults)
        if len(args) > len(names) or len(args) < required:
            raise BackendError(
                f"TorchScript call {fd.name}: got {len(args)} args, "
                f"signature has {len(names)}")
        for i, n in enumerate(names):
            if i < len(args):
                env[n] = args[i]
            else:
                env[n] = self.eval(defaults[i - required], env)
        try:
            for st in fd.body:
                self.exec(st, env)
        except _Return as r:
            return r.value
        return None

    # -- statements ---------------------------------------------------------
    def exec(self, node: ast.stmt, env: Dict[str, Any]) -> None:
        k = type(node).__name__
        if k == "Assign":
            val = self.eval(node.value, env)
            for tgt in node.targets:
                self._bind(tgt, val, env)
        elif k == "AnnAssign":
            if node.value is not None:
                self._bind(node.target, self.eval(node.value, env), env)
        elif k == "AugAssign":
            cur = self.eval(
                ast.copy_location(
                    ast.Name(id=node.target.id, ctx=ast.Load()), node)
                if isinstance(node.target, ast.Name) else node.target, env)
            val = self._binop(type(node.op).__name__, cur,
                              self.eval(node.value, env))
            self._bind(node.target, val, env)
        elif k == "Return":
            raise _Return(self.eval(node.value, env)
                          if node.value is not None else None)
        elif k == "If":
            cond = self._host_bool(self.eval(node.test, env))
            for st in (node.body if cond else node.orelse):
                self.exec(st, env)
        elif k == "For":
            it = self.eval(node.iter, env)
            if _is_tensor(it):
                raise BackendError(
                    "TorchScript data-dependent loop (iterating a "
                    "tensor) is not supported under jit")
            for v in it:
                self._bind(node.target, v, env)
                try:
                    for st in node.body:
                        self.exec(st, env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif k == "While":
            guard = 0
            while self._host_bool(self.eval(node.test, env)):
                guard += 1
                if guard > 100000:
                    raise BackendError(
                        "TorchScript while-loop exceeded 100000 "
                        "trace-time iterations")
                try:
                    for st in node.body:
                        self.exec(st, env)
                except _Break:
                    break
                except _Continue:
                    continue
        elif k == "Expr":
            self.eval(node.value, env)
        elif k == "Pass":
            pass
        elif k == "Break":
            raise _Break()
        elif k == "Continue":
            raise _Continue()
        elif k == "Raise":
            raise BackendError(
                "TorchScript model raised an exception at trace time")
        else:
            raise BackendError(
                f"TorchScript statement {k} is not supported")

    def _bind(self, tgt: ast.expr, val, env) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            vals = list(val)
            if len(vals) != len(tgt.elts):
                raise BackendError(
                    f"TorchScript tuple unpack arity mismatch "
                    f"({len(tgt.elts)} targets, {len(vals)} values)")
            for t, v in zip(tgt.elts, vals):
                self._bind(t, v, env)
        else:
            raise BackendError(
                f"TorchScript assignment target "
                f"{type(tgt).__name__} is not supported")

    def _host_bool(self, v) -> bool:
        if _is_tensor(v) and getattr(v, "shape", None) not in ((), None):
            raise BackendError(
                "TorchScript data-dependent control flow (branching on "
                "a tensor) is not supported under jit")
        try:
            return bool(v)
        except Exception:
            raise BackendError(
                "TorchScript data-dependent control flow (branching on "
                "a traced value) is not supported under jit") from None

    # -- expressions --------------------------------------------------------
    def eval(self, node: ast.expr, env: Dict[str, Any]):
        k = type(node).__name__
        m = getattr(self, f"_eval_{k}", None)
        if m is None:
            raise BackendError(
                f"TorchScript expression {k} is not supported")
        return m(node, env)

    def _eval_Constant(self, node, env):
        return node.value

    def _eval_Name(self, node, env):
        if node.id in env:
            return env[node.id]
        if node.id in self.globals:
            return self.globals[node.id]
        raise BackendError(
            f"TorchScript name {node.id!r} is not defined")

    def _eval_Tuple(self, node, env):
        return tuple(self.eval(e, env) for e in node.elts)

    def _eval_List(self, node, env):
        return [self.eval(e, env) for e in node.elts]

    def _eval_Dict(self, node, env):
        return {self.eval(kn, env): self.eval(vn, env)
                for kn, vn in zip(node.keys, node.values)}

    def _eval_Attribute(self, node, env):
        obj = self.eval(node.value, env)
        return self._getattr(obj, node.attr)

    def _resolve_slots(self, v):
        """Param slots hide anywhere attributes nest (an nn.LSTM's
        _flat_weights is a LIST of parameters)."""
        if isinstance(v, _ParamSlot):
            return self.params[v.path]
        if isinstance(v, (list, tuple)):
            return type(v)(self._resolve_slots(e) for e in v)
        return v

    def _try_getattr(self, obj, name: str, default: tuple):
        try:
            return self._getattr(obj, name)
        except BackendError:
            if default:
                return default[0]
            raise

    def _getattr(self, obj, name: str):
        if isinstance(obj, _TSModule):
            if name in obj.attrs:
                return self._resolve_slots(obj.attrs[name])
            ci = self.prog.classes.get(obj.qualname)
            if ci and name in ci.consts:
                return ci.consts[name]
            if ci and name in ci.methods:
                return _BoundMethod(self, obj, name)
            raise BackendError(
                f"TorchScript module {obj.qualname} has no attribute "
                f"{name!r}")
        if isinstance(obj, _ConstantsNS):
            if name.startswith("c") and name[1:].isdigit():
                return self.prog.constants[int(name[1:])]
            raise BackendError(f"unknown CONSTANTS.{name}")
        if isinstance(obj, _TorchNS):
            op = self.ops.get(name)
            if op is None:
                raise BackendError(
                    f"TorchScript op torch.{name} is not supported by "
                    f"the jax lowering (file an op-table entry)")
            return op
        if isinstance(obj, _OpsNS):
            if obj.space == "ops":
                return _OpsNS(self, name)
            if obj.space == "prim":
                op = self.prims.get(name)
                if op is None:
                    raise BackendError(
                        f"TorchScript op ops.prim.{name} is not "
                        f"supported")
                return op
            raise BackendError(
                f"TorchScript op namespace ops.{obj.space}.{name} is "
                f"not supported (quantized/custom ops have no jax "
                f"lowering)")
        if isinstance(obj, _NSNode):
            prefix = f"{obj.prefix}.{name}"
            if prefix in self.prog.functions:
                fd = self.prog.functions[prefix]
                return lambda *a: self.call_function(fd, a)
            return _NSNode(self, prefix)
        if isinstance(obj, _AnyType):
            return _ANYTYPE
        raise BackendError(
            f"TorchScript attribute {name!r} on "
            f"{type(obj).__name__} is not supported")

    def _eval_Call(self, node, env):
        fn = self.eval(node.func, env)
        args = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                args.extend(self.eval(a.value, env))
            else:
                args.append(self.eval(a, env))
        kwargs = {kw.arg: self.eval(kw.value, env)
                  for kw in node.keywords if kw.arg is not None}
        if isinstance(fn, _BoundMethod):
            return fn(*args, **kwargs)
        if callable(fn):
            return fn(*args, **kwargs)
        raise BackendError(
            f"TorchScript call target {type(fn).__name__} is not "
            f"callable")

    def _eval_Subscript(self, node, env):
        obj = self.eval(node.value, env)
        if isinstance(obj, _AnyType):
            return _ANYTYPE
        sl = node.slice
        if isinstance(sl, ast.Slice):
            lo = self.eval(sl.lower, env) if sl.lower else None
            hi = self.eval(sl.upper, env) if sl.upper else None
            st = self.eval(sl.step, env) if sl.step else None
            return obj[slice(lo, hi, st)]
        return obj[self.eval(sl, env)]

    def _eval_UnaryOp(self, node, env):
        v = self.eval(node.operand, env)
        k = type(node.op).__name__
        if k == "USub":
            return -v
        if k == "UAdd":
            return +v
        if k == "Not":
            return not self._host_bool(v)
        if k == "Invert":
            return ~v
        raise BackendError(f"TorchScript unary op {k} unsupported")

    def _binop(self, k: str, a, b):
        import operator as op

        table = {"Add": op.add, "Sub": op.sub, "Mult": op.mul,
                 "Div": op.truediv, "FloorDiv": op.floordiv,
                 "Mod": op.mod, "Pow": op.pow, "MatMult": op.matmul,
                 "BitAnd": op.and_, "BitOr": op.or_, "BitXor": op.xor,
                 "LShift": op.lshift, "RShift": op.rshift}
        if k not in table:
            raise BackendError(f"TorchScript binary op {k} unsupported")
        return table[k](a, b)

    def _eval_BinOp(self, node, env):
        return self._binop(type(node.op).__name__,
                           self.eval(node.left, env),
                           self.eval(node.right, env))

    def _eval_Compare(self, node, env):
        import operator as op

        table = {"Eq": op.eq, "NotEq": op.ne, "Lt": op.lt, "LtE": op.le,
                 "Gt": op.gt, "GtE": op.ge,
                 "Is": lambda a, b: a is b,
                 "IsNot": lambda a, b: a is not b,
                 "In": lambda a, b: a in b,
                 "NotIn": lambda a, b: a not in b}
        left = self.eval(node.left, env)
        result = True
        for cmp_op, right_n in zip(node.ops, node.comparators):
            right = self.eval(right_n, env)
            k = type(cmp_op).__name__
            if k not in table:
                raise BackendError(
                    f"TorchScript comparison {k} unsupported")
            r = table[k](left, right)
            if _is_tensor(r):
                return r         # tensor comparison: no chaining
            if not r:
                return False
            left = right
        return result

    def _eval_BoolOp(self, node, env):
        is_and = isinstance(node.op, ast.And)
        val = None
        for v in node.values:
            val = self.eval(v, env)
            b = self._host_bool(val)
            if is_and and not b:
                return val
            if not is_and and b:
                return val
        return val

    def _eval_IfExp(self, node, env):
        return self.eval(node.body, env) \
            if self._host_bool(self.eval(node.test, env)) \
            else self.eval(node.orelse, env)

    def _eval_ListComp(self, node, env):
        if len(node.generators) != 1:
            raise BackendError(
                "TorchScript nested comprehensions unsupported")
        gen = node.generators[0]
        it = self.eval(gen.iter, env)
        out = []
        sub = dict(env)
        for v in it:
            self._bind(gen.target, v, sub)
            if all(self._host_bool(self.eval(c, sub)) for c in gen.ifs):
                out.append(self.eval(node.elt, sub))
        return out


class _BoundMethod:
    __slots__ = ("interp", "mod", "name")

    def __init__(self, interp, mod, name):
        self.interp = interp
        self.mod = mod
        self.name = name

    def __call__(self, *args, **kwargs):
        if kwargs:
            raise BackendError(
                f"TorchScript method {self.name} called with keyword "
                f"args (unsupported)")
        return self.interp.call_method(self.mod, self.name, args)


# ---------------------------------------------------------------------------
# op tables
# ---------------------------------------------------------------------------

def _norm_pair(v, nd: int) -> Tuple[int, ...]:
    if isinstance(v, (int, np.integer)):
        return (int(v),) * nd
    v = tuple(int(x) for x in v)
    return v * nd if len(v) == 1 else v


def _make_torch_ops(I: "_Interp") -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp
    from jax import lax

    fdt = I.fdt

    def asarr(x):
        return x if _is_tensor(x) else jnp.asarray(x)

    def both_host(a, b):
        return not _is_tensor(a) and not _is_tensor(b)

    # -- elementwise / scalar ------------------------------------------
    def t_add(x, other, alpha=1):
        if both_host(x, other):
            return x + alpha * other
        return asarr(x) + (alpha * asarr(other) if alpha != 1
                           else asarr(other))

    def t_sub(x, other, alpha=1):
        if both_host(x, other):
            return x - alpha * other
        return asarr(x) - (alpha * asarr(other) if alpha != 1
                           else asarr(other))

    def t_rsub(x, other, alpha=1):
        return t_sub(other, x, alpha)

    def t_mul(x, other):
        return x * other if both_host(x, other) else asarr(x) * asarr(other)

    def t_div(x, other, rounding_mode=None):
        if rounding_mode == "floor":
            return jnp.floor_divide(asarr(x), asarr(other))
        if rounding_mode == "trunc":
            return jnp.trunc(asarr(x) / asarr(other)).astype(
                jnp.result_type(x))
        if both_host(x, other):
            return x / other
        a = asarr(x)
        if not jnp.issubdtype(a.dtype, jnp.floating):
            a = a.astype(fdt)
        return a / asarr(other)

    def _cast(np_dt):
        def f(x, non_blocking=False):
            return asarr(x).astype(np_dt)
        return f

    def t_to(x, *args, **kwargs):
        # serialized overloads: to(x, dtype_code, non_blocking, copy
        # [, memory_format]) or to(x, other_tensor, ...)
        for a in args:
            if _is_tensor(a):
                return asarr(x).astype(asarr(a).dtype)
            if isinstance(a, (int, np.integer)) \
                    and not isinstance(a, bool):
                code = int(a)
                if code in _TORCH_DTYPE_CODES:
                    return asarr(x).astype(_TORCH_DTYPE_CODES[code])
                if code == 15:           # torch.bfloat16
                    return asarr(x).astype(jnp.bfloat16)
                raise BackendError(
                    f"torch.to: dtype code {code} has no jax lowering")
        return asarr(x)

    def _cmp(jf, pf):
        def f(a, b):
            return pf(a, b) if both_host(a, b) else jf(asarr(a), asarr(b))
        return f

    import operator as pyop

    # -- convolutions --------------------------------------------------
    def conv_nd(x, w, bias, stride, padding, dilation, groups,
                transposed=False, output_padding=None):
        nd = w.ndim - 2
        stride = _norm_pair(stride, nd)
        padding = _norm_pair(padding, nd)
        dilation = _norm_pair(dilation, nd)
        spec = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
                3: ("NCDHW", "OIDHW", "NCDHW")}.get(nd)
        if spec is None:
            raise BackendError(f"conv{nd}d unsupported")
        dn = lax.conv_dimension_numbers(x.shape, w.shape, spec)
        if transposed:
            if groups != 1:
                raise BackendError(
                    "grouped transposed convolution has no jax lowering "
                    "here")
            op = _norm_pair(output_padding or 0, nd)
            # torch convT weight is (Cin, Cout, *K): swap to OI, flip taps
            w2 = jnp.swapaxes(w, 0, 1)[
                (slice(None), slice(None))
                + (slice(None, None, -1),) * nd]
            k = w.shape[2:]
            pad = [((k[i] - 1) * dilation[i] - padding[i],
                    (k[i] - 1) * dilation[i] - padding[i] + op[i])
                   for i in range(nd)]
            out = lax.conv_general_dilated(
                x, w2, window_strides=(1,) * nd, padding=pad,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn)
        else:
            out = lax.conv_general_dilated(
                x, w, window_strides=stride,
                padding=[(p, p) for p in padding], rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=int(groups))
        if bias is not None:
            out = out + jnp.reshape(asarr(bias),
                                    (1, -1) + (1,) * nd)
        return out

    def t_convolution(x, w, bias, stride, padding, dilation, transposed,
                      output_padding, groups, *flags):
        return conv_nd(asarr(x), asarr(w), bias, stride, padding,
                       dilation, int(groups), bool(transposed),
                       output_padding)

    def t_conv2d(x, w, bias=None, stride=1, padding=0, dilation=1,
                 groups=1):
        return conv_nd(asarr(x), asarr(w), bias, stride, padding,
                       dilation, int(groups))

    def t_conv1d(x, w, bias=None, stride=1, padding=0, dilation=1,
                 groups=1):
        return conv_nd(asarr(x), asarr(w), bias, stride, padding,
                       dilation, int(groups))

    def t_conv_transpose2d(x, w, bias=None, stride=1, padding=0,
                           output_padding=0, groups=1, dilation=1):
        return conv_nd(asarr(x), asarr(w), bias, stride, padding,
                       dilation, int(groups), transposed=True,
                       output_padding=output_padding)

    # -- pooling -------------------------------------------------------
    def _pool_dims(x, kernel, stride, padding, ceil_mode, init):
        nd = x.ndim - 2
        k = _norm_pair(kernel, nd)
        s = _norm_pair(stride, nd) if stride not in (None, [], ())  \
            else k
        p = _norm_pair(padding, nd)
        pads = []
        for i in range(nd):
            size = x.shape[2 + i] + 2 * p[i]
            hi = p[i]
            if ceil_mode:
                rem = (size - k[i]) % s[i]
                if rem:
                    extra = s[i] - rem
                    # torch: the last window must start inside the
                    # input or left padding
                    if (size + extra - k[i]) // s[i] * s[i] \
                            < x.shape[2 + i] + p[i]:
                        hi += extra
            pads.append((p[i], hi))
        return k, s, pads

    def t_max_pool2d(x, kernel, stride=None, padding=0, dilation=1,
                     ceil_mode=False):
        x = asarr(x)
        d = _norm_pair(dilation, 2)
        if d != (1, 1):
            raise BackendError("dilated max_pool2d unsupported")
        k, s, pads = _pool_dims(x, kernel, stride, padding,
                                bool(ceil_mode), "max")
        lo = (jnp.finfo(x.dtype).min
              if jnp.issubdtype(x.dtype, jnp.floating)
              else jnp.iinfo(x.dtype).min)
        return lax.reduce_window(
            x, lo, lax.max, (1, 1) + k, (1, 1) + s,
            ((0, 0), (0, 0)) + tuple(pads))

    def t_max_pool2d_with_indices(x, kernel, stride=None, padding=0,
                                  dilation=1, ceil_mode=False):
        raise BackendError(
            "torch.max_pool2d_with_indices: the indices output has no "
            "jax lowering (use max_pool2d if the model does not need "
            "unpooling indices)")

    def t_avg_pool2d(x, kernel, stride=None, padding=0, ceil_mode=False,
                     count_include_pad=True, divisor_override=None):
        x = asarr(x)
        k, s, pads = _pool_dims(x, kernel, stride, padding,
                                bool(ceil_mode), "add")
        xf = x.astype(fdt) if not jnp.issubdtype(x.dtype, jnp.floating) \
            else x
        acc = lax.reduce_window(
            xf, np.array(0, xf.dtype), lax.add, (1, 1) + k, (1, 1) + s,
            ((0, 0), (0, 0)) + tuple(pads))
        if divisor_override:
            return acc / divisor_override
        if count_include_pad and not ceil_mode:
            return acc / float(np.prod(k))
        # torch divisor: count_include_pad counts *declared* padding
        # but never the ceil_mode overhang; otherwise only real
        # elements count
        pd = _norm_pair(padding, 2)
        ones = jnp.ones(x.shape[2:], xf.dtype)[None, None]
        if count_include_pad:
            ones = jnp.pad(ones, ((0, 0), (0, 0), (pd[0], pd[0]),
                                  (pd[1], pd[1])), constant_values=1)
            cpads = tuple((0, pads[i][1] - pd[i]) for i in range(2))
        else:
            cpads = tuple(pads)
        cnt = lax.reduce_window(
            ones, np.array(0, xf.dtype), lax.add, (1, 1) + k,
            (1, 1) + s, ((0, 0), (0, 0)) + cpads)
        return acc / cnt

    def t_adaptive_avg_pool2d(x, out_size):
        x = asarr(x)
        oh, ow = _norm_pair(out_size, 2)
        h, w = x.shape[-2], x.shape[-1]
        if (oh, ow) == (1, 1):
            return jnp.mean(x, axis=(-2, -1), keepdims=True)
        if h % oh == 0 and w % ow == 0:
            return t_avg_pool2d(x, (h // oh, w // ow),
                                (h // oh, w // ow))
        raise BackendError(
            f"adaptive_avg_pool2d {h}x{w}->{oh}x{ow} (non-divisible) "
            f"unsupported")

    # -- linear algebra ------------------------------------------------
    def t_linear(x, w, bias=None):
        out = jnp.matmul(asarr(x), jnp.swapaxes(asarr(w), -1, -2))
        return out if bias is None else out + asarr(bias)

    def t_addmm(bias, m1, m2, beta=1, alpha=1):
        out = jnp.matmul(asarr(m1), asarr(m2))
        if alpha != 1:
            out = out * alpha
        if bias is not None:
            out = out + (asarr(bias) if beta == 1
                         else beta * asarr(bias))
        return out

    # -- normalization -------------------------------------------------
    def t_batch_norm(x, weight, bias, running_mean, running_var,
                     training, momentum, eps, cudnn_enabled=True):
        if training:
            raise BackendError(
                "batch_norm in training mode unsupported (inference "
                "lowering)")
        x = asarr(x)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        inv = lax.rsqrt(asarr(running_var).astype(x.dtype)
                        + np.asarray(eps, np.float32).astype(x.dtype))
        out = (x - jnp.reshape(asarr(running_mean).astype(x.dtype),
                               shape)) * jnp.reshape(inv, shape)
        if weight is not None:
            out = out * jnp.reshape(asarr(weight).astype(x.dtype), shape)
        if bias is not None:
            out = out + jnp.reshape(asarr(bias).astype(x.dtype), shape)
        return out

    def t_layer_norm(x, normalized_shape, weight=None, bias=None,
                     eps=1e-5, cudnn_enable=True):
        x = asarr(x)
        nd = len(tuple(normalized_shape))
        axes = tuple(range(x.ndim - nd, x.ndim))
        mu = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=axes, keepdims=True)
        out = (x - mu) * lax.rsqrt(var + eps)
        if weight is not None:
            out = out * asarr(weight)
        if bias is not None:
            out = out + asarr(bias)
        return out

    # -- shape ---------------------------------------------------------
    def t_size(x, dim=None):
        shape = [int(s) for s in asarr(x).shape]
        return shape if dim is None else shape[dim]

    def t_reshape(x, shape):
        return jnp.reshape(asarr(x), [int(s) for s in shape])

    def t_flatten(x, start_dim=0, end_dim=-1):
        x = asarr(x)
        nd = x.ndim
        s = start_dim % nd
        e = end_dim % nd
        new = x.shape[:s] + (-1,) + x.shape[e + 1:]
        return jnp.reshape(x, new)

    def t_transpose(x, d0, d1):
        return jnp.swapaxes(asarr(x), int(d0), int(d1))

    def t_permute(x, dims):
        return jnp.transpose(asarr(x), [int(d) for d in dims])

    def t_cat(tensors, dim=0):
        return jnp.concatenate([asarr(t) for t in tensors], axis=int(dim))

    def t_stack(tensors, dim=0):
        return jnp.stack([asarr(t) for t in tensors], axis=int(dim))

    def t_chunk(x, chunks, dim=0):
        # torch.chunk: ceil-sized chunks with a short last chunk —
        # NOT numpy array_split's balanced sizes (7/3 → [3,3,1], not
        # [3,2,2])
        x = asarr(x)
        d = int(dim) % x.ndim
        n = x.shape[d]
        step = -(-n // int(chunks))
        idx = list(range(step, n, step))
        return jnp.split(x, idx, axis=d)

    def t_split(x, size, dim=0):
        x = asarr(x)
        if isinstance(size, (list, tuple)):
            idx = np.cumsum([int(s) for s in size])[:-1].tolist()
        else:
            idx = list(range(int(size), x.shape[int(dim)], int(size)))
        return jnp.split(x, idx, axis=int(dim))

    def t_select(x, dim, index):
        return jnp.take(asarr(x), int(index), axis=int(dim))

    def t_slice(x, dim=0, start=None, end=None, step=1):
        x = asarr(x)
        sl = [slice(None)] * x.ndim
        big = 2 ** 62
        if end is not None and end >= big:
            end = None
        sl[int(dim)] = slice(None if start is None else int(start),
                             None if end is None else int(end),
                             int(step) if step else 1)
        return x[tuple(sl)]

    def t_narrow(x, dim, start, length):
        return t_slice(x, dim, start, int(start) + int(length))

    def t_unflatten(x, dim, sizes):
        x = asarr(x)
        d = int(dim) % x.ndim
        new = (x.shape[:d] + tuple(int(s) for s in sizes)
               + x.shape[d + 1:])
        return jnp.reshape(x, new)

    def t_unsqueeze(x, dim):
        return jnp.expand_dims(asarr(x), int(dim))

    def t_squeeze(x, dim=None):
        x = asarr(x)
        if dim is None:
            return jnp.squeeze(x)
        d = int(dim)
        return jnp.squeeze(x, axis=d) if x.shape[d] == 1 else x

    def t_expand(x, sizes, implicit=False):
        x = asarr(x)
        sizes = [int(s) for s in sizes]
        # align ranks (new leading dims), -1 keeps the existing size
        lead = len(sizes) - x.ndim
        tgt = [x.shape[i - lead] if s == -1 else s
               for i, s in enumerate(sizes)]
        return jnp.broadcast_to(x, tgt)

    def t_repeat(x, sizes):
        return jnp.tile(asarr(x), [int(s) for s in sizes])

    def t_pad(x, pad, mode="constant", value=0.0):
        x = asarr(x)
        pad = [int(p) for p in pad]
        if mode != "constant":
            raise BackendError(f"pad mode {mode!r} unsupported")
        # torch pad list is (last dim first): [l, r, t, b, ...]
        cfg = [(0, 0)] * x.ndim
        for i in range(len(pad) // 2):
            cfg[x.ndim - 1 - i] = (pad[2 * i], pad[2 * i + 1])
        return jnp.pad(x, cfg, constant_values=value or 0.0)

    # -- reductions / indexing -----------------------------------------
    def _axes(dim):
        if dim is None:
            return None
        if isinstance(dim, (list, tuple)):
            return tuple(int(d) for d in dim)
        return int(dim)

    def t_mean(x, dim=None, keepdim=False, dtype=None):
        x = asarr(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(fdt)
        out = jnp.mean(x, axis=_axes(dim), keepdims=bool(keepdim))
        if dtype is not None and int(dtype) in _TORCH_DTYPE_CODES:
            out = out.astype(_TORCH_DTYPE_CODES[int(dtype)])
        return out

    def t_sum(x, dim=None, keepdim=False, dtype=None):
        out = jnp.sum(asarr(x), axis=_axes(dim), keepdims=bool(keepdim))
        if dtype is not None and int(dtype) in _TORCH_DTYPE_CODES:
            out = out.astype(_TORCH_DTYPE_CODES[int(dtype)])
        return out

    def t_max(x, other_or_dim=None, keepdim=False):
        x = asarr(x)
        if other_or_dim is None:
            return jnp.max(x)
        if _is_tensor(other_or_dim):
            return jnp.maximum(x, other_or_dim)
        d = int(other_or_dim)
        return (jnp.max(x, axis=d, keepdims=bool(keepdim)),
                jnp.argmax(x, axis=d, keepdims=bool(keepdim))
                .astype(jnp.int32))

    def t_min(x, other_or_dim=None, keepdim=False):
        x = asarr(x)
        if other_or_dim is None:
            return jnp.min(x)
        if _is_tensor(other_or_dim):
            return jnp.minimum(x, other_or_dim)
        d = int(other_or_dim)
        return (jnp.min(x, axis=d, keepdims=bool(keepdim)),
                jnp.argmin(x, axis=d, keepdims=bool(keepdim))
                .astype(jnp.int32))

    def t_topk(x, k, dim=-1, largest=True, sorted=True):
        x = asarr(x)
        d = int(dim) % x.ndim
        xm = jnp.moveaxis(x, d, -1)
        if not largest:
            v, i = lax.top_k(-xm, int(k))
            v = -v
        else:
            v, i = lax.top_k(xm, int(k))
        return (jnp.moveaxis(v, -1, d),
                jnp.moveaxis(i, -1, d).astype(jnp.int32))

    def t_argmax(x, dim=None, keepdim=False):
        x = asarr(x)
        if dim is None:
            return jnp.argmax(x).astype(jnp.int32)
        return jnp.argmax(x, axis=int(dim),
                          keepdims=bool(keepdim)).astype(jnp.int32)

    def t_embedding(weight, indices, padding_idx=-1,
                    scale_grad_by_freq=False, sparse=False):
        return jnp.take(asarr(weight), asarr(indices).astype(jnp.int32),
                        axis=0)

    def t_index_select(x, dim, index):
        return jnp.take(asarr(x), asarr(index).astype(jnp.int32),
                        axis=int(dim))

    def t_gather(x, dim, index, sparse_grad=False):
        return jnp.take_along_axis(
            asarr(x), asarr(index).astype(jnp.int32), axis=int(dim))

    # -- activations ---------------------------------------------------
    def t_softmax(x, dim, dtype=None):
        out = jax.nn.softmax(asarr(x), axis=int(dim))
        if dtype is not None and int(dtype) in _TORCH_DTYPE_CODES:
            out = out.astype(_TORCH_DTYPE_CODES[int(dtype)])
        return out

    def t_log_softmax(x, dim, dtype=None):
        out = jax.nn.log_softmax(asarr(x), axis=int(dim))
        if dtype is not None and int(dtype) in _TORCH_DTYPE_CODES:
            out = out.astype(_TORCH_DTYPE_CODES[int(dtype)])
        return out

    def t_upsample_nearest2d(x, output_size=None, *scales):
        x = asarr(x)
        if output_size:
            oh, ow = int(output_size[0]), int(output_size[1])
        else:
            # serialized trailing args are (scales_h, scales_w) — or,
            # in newer serializations, one [scales_h, scales_w] list
            sc = []
            for s in scales:
                if isinstance(s, (list, tuple)):
                    sc.extend(v for v in s if v is not None)
                elif s is not None:
                    sc.append(s)
            if len(sc) >= 2:
                fh, fw = float(sc[0]), float(sc[1])
            elif len(sc) == 1:
                fh = fw = float(sc[0])
            else:
                raise BackendError(
                    "upsample_nearest2d without output_size or scale "
                    "factors")
            oh, ow = int(x.shape[-2] * fh), int(x.shape[-1] * fw)
        return jax.image.resize(x, x.shape[:-2] + (oh, ow), "nearest")

    def t_upsample_bilinear2d(x, output_size, align_corners=False,
                              *scales):
        if align_corners:
            raise BackendError(
                "upsample_bilinear2d align_corners=True unsupported")
        x = asarr(x)
        oh, ow = int(output_size[0]), int(output_size[1])
        return jax.image.resize(x, x.shape[:-2] + (oh, ow), "linear")

    def t_clamp(x, min=None, max=None):
        return jnp.clip(asarr(x), min, max)

    def t_sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
               scale=None, enable_gqa=False):
        """torch.scaled_dot_product_attention — the modern exported
        attention op. torch layout is (..., H, S, D); softmax in f32."""
        if dropout_p:
            raise BackendError(
                "scaled_dot_product_attention with dropout_p>0 "
                "unsupported (inference lowering)")
        q, k, v = asarr(q), asarr(k), asarr(v)
        if enable_gqa and k.shape[-3] != q.shape[-3]:
            rep = q.shape[-3] // k.shape[-3]
            k = jnp.repeat(k, rep, axis=-3)
            v = jnp.repeat(v, rep, axis=-3)
        s = q.shape[-1] ** -0.5 if scale is None else float(scale)
        logits = jnp.einsum(
            "...qd,...kd->...qk", q.astype(jnp.float32),
            k.astype(jnp.float32)) * s
        if is_causal:
            # torch defines is_causal as ones(L, S).tril(diagonal=0) —
            # top-left aligned even when Lq != Lk (KV-cached decode
            # exports hit that shape)
            sq, sk = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((sq, sk), bool), k=0)
            logits = jnp.where(mask, logits, -jnp.inf)
        if attn_mask is not None:
            m = asarr(attn_mask)
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, -jnp.inf)
            else:
                logits = logits + m.astype(jnp.float32)
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("...qk,...kd->...qd", w,
                          v.astype(jnp.float32)).astype(q.dtype)

    # -- recurrent layers ----------------------------------------------
    def _rnn_common(x, hx_list, params_list, has_biases, num_layers,
                    dropout, train, bidirectional, batch_first):
        if train:
            raise BackendError(
                "lstm/gru in training mode unsupported (inference "
                "lowering)")
        x = asarr(x)
        if batch_first:
            x = jnp.swapaxes(x, 0, 1)            # (T, B, I)
        nd = 2 if bidirectional else 1
        per = 4 if has_biases else 2
        return x, [asarr(h) for h in hx_list], \
            [asarr(p) for p in params_list], int(num_layers), nd, per

    def _run_rnn(x, h_states, params, num_layers, nd, per, has_biases,
                 batch_first, step_fn, n_state):
        # torch flat-weights layout: per layer, per direction:
        # [w_ih, w_hh (, b_ih, b_hh)]; gate blocks stacked on dim 0
        outs = x
        finals = [[] for _ in range(n_state)]
        for layer in range(num_layers):
            layer_ys = []
            for d in range(nd):
                idx = (layer * nd + d) * per
                w_ih, w_hh = params[idx], params[idx + 1]
                b_ih = params[idx + 2] if has_biases else None
                b_hh = params[idx + 3] if has_biases else None
                seq = outs if d == 0 else outs[::-1]
                init = tuple(s[layer * nd + d] for s in h_states)
                carry, ys = jax.lax.scan(
                    lambda c, xt: step_fn(c, xt, w_ih, w_hh, b_ih,
                                          b_hh), init, seq)
                if d == 1:
                    ys = ys[::-1]
                layer_ys.append(ys)
                for s, v in zip(finals, carry):
                    s.append(v)
            outs = (jnp.concatenate(layer_ys, axis=-1) if nd == 2
                    else layer_ys[0])
        if batch_first:
            outs = jnp.swapaxes(outs, 0, 1)
        return (outs,) + tuple(jnp.stack(s) for s in finals)

    def t_torch_lstm(x, hx, params_list, has_biases, num_layers,
                     dropout, train, bidirectional, batch_first):
        x, hs, ps, num_layers, nd, per = _rnn_common(
            x, hx, params_list, has_biases, num_layers, dropout, train,
            bidirectional, batch_first)

        def step(carry, xt, w_ih, w_hh, b_ih, b_hh):
            h, c = carry
            gates = xt @ w_ih.T + h @ w_hh.T
            if b_ih is not None:
                gates = gates + b_ih + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                       jax.nn.sigmoid(o))
            c = f * c + i * jnp.tanh(g)
            h = o * jnp.tanh(c)
            return (h, c), h

        return _run_rnn(x, hs, ps, num_layers, nd, per, has_biases,
                        batch_first, step, 2)

    def t_torch_gru(x, hx, params_list, has_biases, num_layers,
                    dropout, train, bidirectional, batch_first):
        # torch.gru passes h0 as a Tensor (not a list like lstm)
        x, hs, ps, num_layers, nd, per = _rnn_common(
            x, [hx], params_list, has_biases, num_layers, dropout,
            train, bidirectional, batch_first)

        def step(carry, xt, w_ih, w_hh, b_ih, b_hh):
            (h,) = carry
            gi = xt @ w_ih.T
            gh = h @ w_hh.T
            if b_ih is not None:
                gi = gi + b_ih
                gh = gh + b_hh
            ir, iz, infld = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            nval = jnp.tanh(infld + r * hn)
            h = (1 - z) * nval + z * h
            return (h,), h

        return _run_rnn(x, hs, ps, num_layers, nd, per, has_biases,
                        batch_first, step, 1)

    def t_dropout(x, p=0.5, train=False):
        if train:
            raise BackendError("dropout train=True unsupported "
                               "(inference lowering)")
        return asarr(x)

    def _torch_assert(cond, msg=None):
        # host-evaluable asserts enforce; traced (data-dependent)
        # conditions cannot be checked at trace time — skip, matching
        # torch's behavior under tracing
        if _is_tensor(cond):
            return None
        if not cond:
            raise BackendError(f"TorchScript assertion failed: {msg}")
        return None

    def t_native_mha(q, k, v, embed_dim, num_heads, qkv_w, qkv_b,
                     proj_w, proj_b, mask=None, need_weights=True,
                     average_attn_weights=True, mask_type=None):
        """torch._native_multi_head_attention — the fused fast path
        nn.MultiheadAttention takes on CPU-like devices. Packed-QKV
        self-attention: (B, S, E) in, (B, S, E) out."""
        x = asarr(q)
        B, S, E = x.shape
        H = int(num_heads)
        hd = E // H
        qkv = x @ asarr(qkv_w).T + asarr(qkv_b)
        qq, kk, vv = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, H, hd).transpose(0, 2, 1, 3)

        am = None
        if mask is not None:
            m = asarr(mask)
            if m.ndim == 2 and int(mask_type or 0) == 1:
                # key-padding mask (B, S): True = ignore that key
                am = jnp.where(m[:, None, None, :].astype(bool),
                               -jnp.inf, 0.0).astype(jnp.float32)
            else:
                am = m
        a = t_sdpa(heads(qq), heads(kk), heads(vv), attn_mask=am)
        out = a.transpose(0, 2, 1, 3).reshape(B, S, E)
        out = out @ asarr(proj_w).T + asarr(proj_b)
        if not need_weights:
            return out, None
        raise BackendError(
            "_native_multi_head_attention with need_weights=True "
            "unsupported (use need_weights=False)")

    def t_encoder_layer_fwd(src, embed_dim, num_heads, qkv_w, qkv_b,
                            proj_w, proj_b, use_gelu, norm_first, eps,
                            ln1_w, ln1_b, ln2_w, ln2_b, ffn_w1, ffn_b1,
                            ffn_w2, ffn_b2, mask=None, mask_type=None):
        """torch._transformer_encoder_layer_fwd — the fused
        TransformerEncoderLayer fast path: MHA + residual + LayerNorm +
        FFN + residual + LayerNorm, pre- or post-norm."""
        x = asarr(src)

        def ln(t, w, b):
            return t_layer_norm(t, (t.shape[-1],), w, b, eps)

        def attn(t):
            out, _ = t_native_mha(t, t, t, embed_dim, num_heads,
                                  qkv_w, qkv_b, proj_w, proj_b,
                                  mask=mask, need_weights=False,
                                  mask_type=mask_type)
            return out

        def ffn(t):
            h = t @ asarr(ffn_w1).T + asarr(ffn_b1)
            h = jax.nn.gelu(h, approximate=False) if use_gelu \
                else jax.nn.relu(h)
            return h @ asarr(ffn_w2).T + asarr(ffn_b2)

        if norm_first:
            x = x + attn(ln(x, ln1_w, ln1_b))
            return x + ffn(ln(x, ln2_w, ln2_b))
        x = ln(x + attn(x), ln1_w, ln1_b)
        return ln(x + ffn(x), ln2_w, ln2_b)

    def unary(jf):
        return lambda x, *a, **k: jf(asarr(x))

    ops: Dict[str, Callable] = {
        # arithmetic
        "add": t_add, "add_": t_add, "sub": t_sub, "sub_": t_sub,
        "rsub": t_rsub, "mul": t_mul, "mul_": t_mul, "div": t_div,
        "div_": t_div, "floor_divide": lambda a, b: a // b,
        "floordiv": lambda a, b: a // b,
        "remainder": lambda a, b: a % b,
        "pow": lambda a, b: a ** b,
        "matmul": lambda a, b: jnp.matmul(asarr(a), asarr(b)),
        "mm": lambda a, b: jnp.matmul(asarr(a), asarr(b)),
        "bmm": lambda a, b: jnp.matmul(asarr(a), asarr(b)),
        "einsum": lambda eq, tensors: jnp.einsum(
            eq, *[asarr(t) for t in tensors]),
        "neg": unary(jnp.negative), "abs": unary(jnp.abs),
        "exp": unary(jnp.exp), "log": unary(jnp.log),
        "sqrt": unary(jnp.sqrt),
        "rsqrt": lambda x: 1.0 / jnp.sqrt(asarr(x)),
        "floor": unary(jnp.floor), "ceil": unary(jnp.ceil),
        "round": unary(jnp.round), "erf": unary(lax.erf),
        "sin": unary(jnp.sin), "cos": unary(jnp.cos),
        "clamp": t_clamp, "clamp_": t_clamp,
        "clamp_min": lambda x, v: jnp.maximum(asarr(x), v),
        "clamp_max": lambda x, v: jnp.minimum(asarr(x), v),
        "maximum": lambda a, b: jnp.maximum(asarr(a), asarr(b)),
        "minimum": lambda a, b: jnp.minimum(asarr(a), asarr(b)),
        # comparisons (host ints or tensors)
        "eq": _cmp(jnp.equal, pyop.eq), "ne": _cmp(jnp.not_equal, pyop.ne),
        "lt": _cmp(jnp.less, pyop.lt), "le": _cmp(jnp.less_equal, pyop.le),
        "gt": _cmp(jnp.greater, pyop.gt),
        "ge": _cmp(jnp.greater_equal, pyop.ge),
        "__is__": lambda a, b: a is b,
        "__isnot__": lambda a, b: a is not b,
        "__not__": lambda a: not a,
        "__contains__": lambda c, item: item in c,
        "__getitem__": lambda c, i: c[i],
        "__and__": lambda a, b: a and b if both_host(a, b)
        else jnp.logical_and(asarr(a), asarr(b)),
        "__or__": lambda a, b: a or b if both_host(a, b)
        else jnp.logical_or(asarr(a), asarr(b)),
        # casts
        "_cast_Float": _cast(fdt), "_cast_Double": _cast(np.float64),
        "_cast_Half": _cast(np.float16), "_cast_Byte": _cast(np.uint8),
        "_cast_Char": _cast(np.int8), "_cast_Short": _cast(np.int16),
        "_cast_Int": _cast(np.int32), "_cast_Long": _cast(np.int64),
        "_cast_Bool": _cast(np.bool_), "to": t_to,
        "detach": lambda x: asarr(x), "clone": lambda x: asarr(x),
        "contiguous": lambda x, *a, **k: asarr(x),
        # creation
        "zeros": lambda size, **k: jnp.zeros([int(s) for s in size], fdt),
        "ones": lambda size, **k: jnp.ones([int(s) for s in size], fdt),
        "zeros_like": lambda x, **k: jnp.zeros_like(asarr(x)),
        "ones_like": lambda x, **k: jnp.ones_like(asarr(x)),
        "full": lambda size, v, **k: jnp.full(
            [int(s) for s in size], v, fdt),
        "full_like": lambda x, v, **k: jnp.full_like(asarr(x), v),
        "arange": lambda *a, **k: jnp.arange(
            *[x for x in a if x is not None][:3]),
        "tensor": lambda v, **k: jnp.asarray(v),
        "scalar_tensor": lambda v, **k: jnp.asarray(v, fdt),
        # nn
        "_convolution": t_convolution, "conv2d": t_conv2d,
        "conv1d": t_conv1d, "conv_transpose2d": t_conv_transpose2d,
        "linear": t_linear, "addmm": t_addmm,
        "max_pool2d": t_max_pool2d,
        "max_pool2d_with_indices": t_max_pool2d_with_indices,
        "avg_pool2d": t_avg_pool2d,
        "adaptive_avg_pool2d": t_adaptive_avg_pool2d,
        "batch_norm": t_batch_norm, "layer_norm": t_layer_norm,
        "embedding": t_embedding,
        "upsample_nearest2d": t_upsample_nearest2d,
        "upsample_bilinear2d": t_upsample_bilinear2d,
        "dropout": t_dropout, "dropout_": t_dropout,
        "feature_dropout": t_dropout,
        "lstm": t_torch_lstm, "gru": t_torch_gru,
        "scaled_dot_product_attention": t_sdpa,
        "_native_multi_head_attention": t_native_mha,
        "_transformer_encoder_layer_fwd": t_encoder_layer_fwd,
        # activations
        "relu": lambda x: jax.nn.relu(asarr(x)),
        "relu_": lambda x: jax.nn.relu(asarr(x)),
        "relu6": lambda x: jnp.clip(asarr(x), 0, 6),
        "threshold": lambda x, t, v: jnp.where(asarr(x) > t, asarr(x), v),
        "threshold_": lambda x, t, v: jnp.where(asarr(x) > t, asarr(x), v),
        "leaky_relu": lambda x, s=0.01: jax.nn.leaky_relu(asarr(x), s),
        "leaky_relu_": lambda x, s=0.01: jax.nn.leaky_relu(asarr(x), s),
        "elu": lambda x, a=1.0, *r: jax.nn.elu(asarr(x), a),
        "gelu": lambda x, approximate="none": jax.nn.gelu(
            asarr(x), approximate=(approximate == "tanh")),
        "silu": lambda x: jax.nn.silu(asarr(x)),
        "sigmoid": lambda x: jax.nn.sigmoid(asarr(x)),
        "tanh": unary(jnp.tanh),
        "hardtanh": lambda x, lo=-1.0, hi=1.0: jnp.clip(asarr(x), lo, hi),
        "hardtanh_": lambda x, lo=-1.0, hi=1.0: jnp.clip(asarr(x), lo, hi),
        "hardswish": lambda x: asarr(x) * jnp.clip(
            asarr(x) + 3, 0, 6) / 6,
        "hardsigmoid": lambda x: jnp.clip(asarr(x) / 6 + 0.5, 0, 1),
        "softmax": t_softmax, "log_softmax": t_log_softmax,
        # shape
        "size": t_size, "dim": lambda x: asarr(x).ndim,
        "numel": lambda x: int(np.prod(asarr(x).shape)),
        "reshape": t_reshape, "view": t_reshape, "flatten": t_flatten,
        "transpose": t_transpose, "transpose_": t_transpose,
        "t": lambda x: jnp.swapaxes(asarr(x), -1, -2),
        "permute": t_permute, "cat": t_cat, "stack": t_stack,
        "chunk": t_chunk, "split": t_split,
        "unbind": lambda x, dim=0: [
            jnp.take(asarr(x), i, axis=int(dim))
            for i in range(asarr(x).shape[int(dim)])],
        "select": t_select, "slice": t_slice, "narrow": t_narrow,
        "unsqueeze": t_unsqueeze, "unsqueeze_": t_unsqueeze,
        "unflatten": t_unflatten,
        "squeeze": t_squeeze, "squeeze_": t_squeeze,
        "expand": t_expand,
        "expand_as": lambda x, o: jnp.broadcast_to(
            asarr(x), asarr(o).shape),
        "repeat": t_repeat, "pad": t_pad,
        "constant_pad_nd": lambda x, pad, v=0.0: t_pad(
            x, pad, "constant", v),
        # reductions / indexing
        "mean": t_mean, "sum": t_sum, "max": t_max, "min": t_min,
        "topk": t_topk, "argmax": t_argmax,
        "argmin": lambda x, dim=None, keepdim=False: jnp.argmin(
            asarr(x), axis=None if dim is None else int(dim),
            keepdims=bool(keepdim)).astype(jnp.int32),
        "index_select": t_index_select, "gather": t_gather,
        "where": lambda c, a, b: jnp.where(asarr(c), asarr(a), asarr(b)),
        # List[bool] overloads stay host-side (fast-path eligibility
        # checks in nn.MultiheadAttention build bool lists)
        "all": lambda x, dim=None, keepdim=False: (
            all(x) if isinstance(x, (list, tuple))
            and not any(_is_tensor(e) for e in x)
            else jnp.all(asarr(x), axis=None if dim is None
                         else int(dim), keepdims=bool(keepdim))),
        "any": lambda x, dim=None, keepdim=False: (
            any(x) if isinstance(x, (list, tuple))
            and not any(_is_tensor(e) for e in x)
            else jnp.any(asarr(x), axis=None if dim is None
                         else int(dim), keepdims=bool(keepdim))),
        "isnan": lambda x: jnp.isnan(asarr(x)),
        "isinf": lambda x: jnp.isinf(asarr(x)),
        "logical_not": lambda x: jnp.logical_not(asarr(x)),
        # misc
        "warn": lambda *a, **k: None,
        "is_autocast_enabled": lambda *a: False,
        "is_grad_enabled": lambda: False,
        "_assert": _torch_assert,
        "format": lambda fmt, *a: str(fmt).format(*a),
        "len": lambda x: len(x) if not _is_tensor(x)
        else int(asarr(x).shape[0]),
        "device": lambda x: "cpu",
        "list": lambda x: list(x),
        "append": lambda lst, v: (lst.append(v), lst)[1],
    }
    return ops


def _make_prim_ops(I: "_Interp") -> Dict[str, Callable]:
    def raise_exc(msg="", *a):
        raise BackendError(
            f"TorchScript model raised at trace time: {msg}")

    def prim_dtype(x):
        dt = np.dtype(getattr(x, "dtype", type(x)))
        for code, np_dt in _TORCH_DTYPE_CODES.items():
            if dt == np_dt:
                return code
        raise BackendError(
            f"TorchScript prim::dtype: no torch dtype code for {dt}")

    return {
        "NumToTensor": lambda v: v,
        "ImplicitTensorToNum": lambda v: v,
        "unchecked_unwrap_optional": lambda v: v,
        "unchecked_cast": lambda _t, v: v,
        "RaiseException": raise_exc,
        "min": min, "max": max,
        "TupleConstruct": lambda *a: tuple(a),
        "ListConstruct": lambda *a: list(a),
        "dtype": prim_dtype,
        "device": lambda x: "cpu",
        # nested tensors never occur on this path (inputs are dense)
        "is_nested": lambda x: False,
        "requires_grad": lambda x: False,
        "layout": lambda x: 0,      # torch.strided
        "type": lambda x: "cpu",    # device-type string in branch checks
    }


# ---------------------------------------------------------------------------
# lowering entry point
# ---------------------------------------------------------------------------

@dataclass
class LoweredTS:
    fn: Callable
    params: Dict[str, Any]
    name: str


def _flatten_out(out) -> tuple:
    if isinstance(out, (tuple, list)):
        flat: List[Any] = []
        for o in out:
            flat.extend(_flatten_out(o))
        return tuple(flat)
    return (out,)


def lower_torchscript(path: str,
                      compute_dtype: str = "float32") -> LoweredTS:
    """Load a ``.pt`` archive and lower it to ``fn(params, *inputs)``.

    ``compute_dtype`` sets the float compute type; the default is
    float32 for numeric fidelity with torch-exported weights (the
    reference's pytorch filter also runs fp32 —
    tensor_filter_pytorch.cc).  Pass ``bfloat16`` (``custom=dtype=
    bfloat16``) to run the MXU-native type at ~2x the matmul rate.
    """
    import jax.numpy as jnp

    prog = load_torchscript(path)
    if "forward" not in prog.classes.get(prog.root.qualname,
                                         _ClassInfo("")).methods:
        raise BackendError(
            f"{path!r}: no serialized forward() found for root module "
            f"{prog.root.qualname}")
    if compute_dtype in ("bfloat16", "bf16"):
        fdt = jnp.bfloat16
    elif compute_dtype in ("float32", "fp32", "float"):
        fdt = jnp.float32
    else:
        raise BackendError(
            f"torchscript compute dtype {compute_dtype!r} unsupported "
            f"(float32 or bfloat16)")

    params = {
        k: (v.astype(np.dtype(fdt) if fdt != jnp.bfloat16 else
            jnp.bfloat16) if np.issubdtype(v.dtype, np.floating) else v)
        for k, v in prog.params.items()
    }
    consts = [
        (a.astype(fdt) if isinstance(a, np.ndarray)
         and np.issubdtype(a.dtype, np.floating) else a)
        for a in prog.constants
    ]

    # the interpreter reads weights from the `p` passed into fn, never
    # from TSProgram.params — keep the run program weight-free so the
    # closure does not pin the uncast originals in host memory
    run_prog = TSProgram(root=prog.root, classes=prog.classes,
                         functions=prog.functions, params={},
                         constants=consts, name=prog.name)

    def fn(p, *inputs):
        interp = _Interp(run_prog, p, fdt)
        out = interp.call_method(run_prog.root, "forward",
                                 tuple(inputs))
        return _flatten_out(out)

    return LoweredTS(fn=fn, params=params, name=run_prog.name)
