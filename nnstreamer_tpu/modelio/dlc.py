"""SNPE `.dlc` (Deep Learning Container) ingestion.

The reference runs DLC models through the Qualcomm SNPE SDK
(`ext/nnstreamer/tensor_filter/tensor_filter_snpe.cc:839` builds a
zdl::SNPE network from the container); this module reads the container
itself — no SDK — and lowers the graph to one XLA computation, the same
strategy as every other `modelio` front-end.

Container layout (reversed from the reference's own checked-in
`add2_float.dlc` / `add2_uint8.dlc`, snpe-tflite-to-dlc 1.53):

- A ZIP archive: `model` (graph), `model.params` (weights),
  `dlc.metadata` (converter provenance, informational).
- `model` and `model.params` are FlatBuffers, each behind an 8-byte
  container prefix (`d5 0a 03 00` + 4 reserved bytes), with file
  identifiers ``NETD`` / ``NETP``.
- NETD root: fid1 = [Layer], fid4 = [Attribute] (network attributes —
  `BufferInfos` maps every blob name to `dims`/`data_size`/
  `axis_order`).
- Layer: fid0 = id (i32), fid1 = name, fid2 = type (string), fid3 =
  [input name], fid4 = [output name], fid5 = [Attribute].
- Attribute: fid0 = name, fid1 = type tag (u8), value slot = fid
  (tag+1) for scalar/list tags — tag 3 → i32 at fid4, tag 7 → u32
  list at fid8, tag 8 → i32 list at fid9, tag 11 → [Attribute] at
  fid12 — and tag 40 (tensor) → table at fid2 of
  {fid0: dtype tag (u8, 9 = float32), fid2: element vector}.
- NETP root: fid0 = [{fid0: layer name, fid1: [Attribute]}] where the
  `value` attribute (tag 40) carries the layer's weights.

Layer coverage is exactly what can be golden-verified in this
environment: `Input`, `Const`, and `ElementwiseBinaryOp` (the add2
models; reference goldens `unittest_filter_snpe.cc:167-258` — y = x+2
exact, float32 and uint8 I/O). Anything else fails loudly with the
layer type in the message.
"""

from __future__ import annotations

import io
import zipfile
from struct import error as struct_error
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio.flatbuf import Reader

_PREFIX_MAGIC = b"\xd5\x0a\x03\x00"

#: Attribute.type tag → value field id (scalar / list tags)
_TAG_I32 = 3          # value inline at fid4
_TAG_U32_LIST = 7     # vector at fid8
_TAG_I32_LIST = 8     # vector at fid9
_TAG_LIST = 11        # [Attribute] at fid12
_TAG_TENSOR = 40      # nested table at fid2

#: tensor dtype tags (NETP value tables)
_TENSOR_DTYPES = {9: np.float32}


@dataclass
class DLCLayer:
    id: int
    name: str
    type: str
    inputs: List[str]
    outputs: List[str]
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DLCGraph:
    layers: List[DLCLayer]
    buffer_dims: Dict[str, Tuple[int, ...]]
    params: Dict[str, np.ndarray]
    metadata: str = ""


def _fb(raw: bytes, ident: bytes, what: str) -> Reader:
    if len(raw) < 16 or raw[:4] != _PREFIX_MAGIC:
        raise BackendError(
            f"dlc {what}: missing container prefix "
            f"(got {raw[:4]!r}, want {_PREFIX_MAGIC!r})")
    fb = raw[8:]
    if fb[4:8] != ident:
        raise BackendError(
            f"dlc {what}: flatbuffer identifier {fb[4:8]!r} != {ident!r}")
    return Reader(fb)


def _read_attr(r: Reader, at: int) -> Tuple[str, Any]:
    name = r.field_string(at, 0) or ""
    tag = r.field_scalar(at, 1, "<B", 0)
    if tag == _TAG_I32:
        return name, r.field_scalar(at, 4, "<i", 0)
    if tag == _TAG_U32_LIST:
        v = r.field_vec_scalars(at, 8, np.uint32)
        return name, ([] if v is None else [int(x) for x in v])
    if tag == _TAG_I32_LIST:
        v = r.field_vec_scalars(at, 9, np.int32)
        return name, ([] if v is None else [int(x) for x in v])
    if tag == _TAG_LIST:
        return name, dict(_read_attr(r, sub)
                          for sub in r.field_vec_tables(at, 12))
    if tag == _TAG_TENSOR:
        t = r.field_table(at, 2)
        if t is None:
            raise BackendError(f"dlc: tensor attribute {name!r} has no "
                               f"value table")
        dt_tag = r.field_scalar(t, 0, "<B", 0)
        dt = _TENSOR_DTYPES.get(dt_tag)
        if dt is None:
            raise BackendError(
                f"dlc: tensor attribute {name!r} has unsupported dtype "
                f"tag {dt_tag} (known: {sorted(_TENSOR_DTYPES)})")
        data = r.field_vec_scalars(t, 2, dt)
        return name, (np.zeros((0,), dt) if data is None
                      else np.asarray(data))
    raise BackendError(
        f"dlc: attribute {name!r} has unsupported type tag {tag}")


def parse_dlc(path: str) -> DLCGraph:
    """Parse a .dlc container into a graph description (host side)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
        z = zipfile.ZipFile(io.BytesIO(blob))
        names = set(z.namelist())
        if "model" not in names:
            raise BackendError(
                f"{path!r}: DLC zip has no 'model' member (members: "
                f"{sorted(names)})")
        model = z.read("model")
        params = z.read("model.params") if "model.params" in names \
            else b""
        meta = z.read("dlc.metadata").decode("utf-8", "replace") \
            if "dlc.metadata" in names else ""
    except (OSError, zipfile.BadZipFile, zipfile.LargeZipFile,
            NotImplementedError, EOFError, ValueError) as e:
        # zip-member reads surface CRC/decompress corruption as several
        # exception types; all mean the same thing here
        raise BackendError(f"{path!r} is not a DLC container (zip): {e}")
    try:
        return _parse_members(model, params, meta, path)
    except (IndexError, ValueError, UnicodeDecodeError,
            struct_error) as e:
        raise BackendError(f"dlc {path!r}: malformed flatbuffer: {e}")


def _parse_members(model: bytes, params: bytes, meta: str,
                   path: str) -> DLCGraph:
    r = _fb(model, b"NETD", path)
    root = r.root()
    layers = []
    for t in r.field_vec_tables(root, 1):
        layers.append(DLCLayer(
            id=r.field_scalar(t, 0, "<i", 0),
            name=r.field_string(t, 1) or "",
            type=r.field_string(t, 2) or "",
            inputs=r.field_vec_strings(t, 3),
            outputs=r.field_vec_strings(t, 4),
            attrs=dict(_read_attr(r, at)
                       for at in r.field_vec_tables(t, 5))))
    net_attrs = dict(_read_attr(r, at)
                     for at in r.field_vec_tables(root, 4))
    buffer_dims = {}
    for bname, info in (net_attrs.get("BufferInfos") or {}).items():
        if isinstance(info, dict) and isinstance(info.get("dims"), list):
            buffer_dims[bname] = tuple(info["dims"])

    weights: Dict[str, np.ndarray] = {}
    if params:
        rp = _fb(params, b"NETP", path)
        for rec in rp.field_vec_tables(rp.root(), 0):
            lname = rp.field_string(rec, 0) or ""
            attrs = dict(_read_attr(rp, at)
                         for at in rp.field_vec_tables(rec, 1))
            if isinstance(attrs.get("value"), np.ndarray):
                weights[lname] = attrs["value"]
    return DLCGraph(layers=layers, buffer_dims=buffer_dims,
                    params=weights, metadata=meta)


#: ElementwiseBinaryOp `op` attribute values (0 is the only one the
#: in-env goldens exercise; the rest would be guesswork)
_ELTWISE_OPS = {0: "add"}

#: Input layer `input_type` values: 0 = default (float I/O),
#: 1 = image (uint8 I/O — the reference runs add2_uint8 with
#: custom_properties "InputType:uint8,OutputType:uint8")
_INPUT_TYPE_IMAGE = 1


def lower_dlc(graph: DLCGraph, batch: Optional[int] = None):
    """DLCGraph → LoweredModel: one XLA computation over the layer list.

    Const layers resolve first from NETP weights, then from any inline
    tensor attribute. uint8-I/O models (input_type = image) cast on
    entry and round/clip back to uint8 on exit, matching the SNPE
    user-buffer semantics the reference test drives
    (unittest_filter_snpe.cc:216-258: uint8 in, uint8 out, x+2 exact).
    """
    import jax.numpy as jnp

    from nnstreamer_tpu.modelio.tflite import LoweredModel

    inputs: List[Tuple[str, Tuple[int, ...]]] = []
    consts: Dict[str, np.ndarray] = {}
    compute: List[DLCLayer] = []
    u8_io = False
    def _out_dims(layer: DLCLayer) -> Optional[Tuple[int, ...]]:
        dims = graph.buffer_dims.get(layer.outputs[0])
        if dims:
            return dims
        od = layer.attrs.get("OutputDims")
        if isinstance(od, dict) and isinstance(od.get("0"), list):
            return tuple(od["0"])       # nested per-output attr list
        if isinstance(od, list) and od:
            return tuple(od)            # flat i32-list form
        return None

    for layer in graph.layers:
        if not layer.outputs:
            raise BackendError(
                f"dlc: layer {layer.name!r} ({layer.type}) declares no "
                f"outputs")
        if layer.type == "Input":
            dims = _out_dims(layer)
            if dims is None:
                raise BackendError(
                    f"dlc: Input layer {layer.name!r} has no recorded "
                    f"dims (neither BufferInfos nor OutputDims)")
            inputs.append((layer.outputs[0], tuple(dims)))
            if layer.attrs.get("input_type") == _INPUT_TYPE_IMAGE:
                u8_io = True
        elif layer.type == "Const":
            w = graph.params.get(layer.name)
            if w is None:
                w = layer.attrs.get("value")
            if not isinstance(w, np.ndarray):
                raise BackendError(
                    f"dlc: Const layer {layer.name!r} has no weights in "
                    f"model.params")
            dims = graph.buffer_dims.get(layer.outputs[0])
            consts[layer.outputs[0]] = (w.reshape(dims)
                                        if dims and w.size == int(
                                            np.prod(dims)) else w)
        else:
            compute.append(layer)
    if not inputs:
        raise BackendError("dlc: graph declares no Input layer")
    if not compute:
        raise BackendError("dlc: graph has no computable layers")

    in_shapes = []
    for name, dims in inputs:
        shape = tuple(int(d) for d in dims)
        if batch:
            if len(shape) < 2:
                raise BackendError(
                    f"dlc: batch override needs a rank>=2 input; "
                    f"{name!r} has dims {shape}")
            shape = (batch,) + shape[1:]
        in_shapes.append(shape)
    io_np = np.uint8 if u8_io else np.float32

    # resolve output blob names: every compute output nobody consumes
    consumed = {i for lay in compute for i in lay.inputs}
    out_names = [o for lay in compute for o in lay.outputs
                 if o not in consumed]
    if not out_names:
        out_names = list(compute[-1].outputs)

    params = {name: np.asarray(w) for name, w in consts.items()}

    def fn(p, *xs):
        blobs: Dict[str, Any] = {}
        for (name, _), x in zip(inputs, xs):
            blobs[name] = x.astype(jnp.float32)
        for cname in consts:
            blobs[cname] = jnp.asarray(p[cname], jnp.float32)

        def get(name):
            if name not in blobs:
                raise BackendError(
                    f"dlc: blob {name!r} undefined (graph not "
                    f"topologically ordered?)")
            return blobs[name]

        for lay in compute:
            if lay.type == "ElementwiseBinaryOp":
                op = _ELTWISE_OPS.get(lay.attrs.get("op", 0))
                if op is None:
                    raise BackendError(
                        f"dlc: ElementwiseBinaryOp {lay.name!r} has "
                        f"unsupported op {lay.attrs.get('op')} "
                        f"(supported: {_ELTWISE_OPS})")
                acc = get(lay.inputs[0])
                for other in lay.inputs[1:]:
                    acc = acc + get(other)
                blobs[lay.outputs[0]] = acc
            else:
                raise BackendError(
                    f"dlc: layer type {lay.type!r} ({lay.name!r}) has "
                    f"no lowering (supported: Input, Const, "
                    f"ElementwiseBinaryOp)")
        outs = []
        for name in out_names:
            y = get(name)
            if u8_io:
                y = jnp.clip(jnp.round(y), 0, 255).astype(jnp.uint8)
            outs.append(y)
        return tuple(outs)

    import jax

    probe = jax.eval_shape(fn, params, *[
        jax.ShapeDtypeStruct(s, io_np) for s in in_shapes])
    return LoweredModel(
        fn=fn, params=params,
        in_shapes=in_shapes,
        in_dtypes=[np.dtype(io_np)] * len(in_shapes),
        out_shapes=[tuple(int(d) for d in a.shape) for a in probe],
        out_dtypes=[np.dtype(a.dtype) for a in probe],
        name="dlc")
