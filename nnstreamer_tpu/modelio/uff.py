"""NVIDIA UFF ``.uff`` ingestion — MetaGraph wire reader → JAX.

Reference parity: the reference's TensorRT filter consumes UFF models
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorrt.cc``; golden:
``tests/nnstreamer_filter_tensorrt/runTest.sh:68`` runs ``lenet5.uff``
on MNIST digits with ``1 - x/255`` normalization, inputname=in /
outputname=out, and argmax-checks the digit).  UFF is a protobuf
MetaGraph {version, descriptors, graphs, referenced_data}; it is
decoded here with the repo's dependency-free ``protowire`` reader and
lowered to ONE fused XLA computation — where TensorRT builds a
per-node engine, the whole UFF graph becomes a single MXU-scheduled
XLA program.

Wire layout (reverse-engineered from the checked-in model; field
numbers verified against ``lenet5.uff``):
  MetaGraph: 1=version 2=descriptor_version 3=descriptors 4=graphs
             5=referenced_data(KeyValuePair)
  Graph:     1=id 2=nodes
  Node:      1=id 2=inputs 3=operation 4=fields(KeyValuePair)
  KeyValuePair: 1=key 2=Data
  Data:      1=string 8=int-list(msg{1=packed varints}) 9=blob
             100=reference-string into referenced_data 101=dtype code
dtype codes: 131104=float32, 65568=int32.

Op set: Input, Const, Conv (orders N+C / +CK = NHWC data, HWIO
weights — verified against the reference's own MNIST goldens), Pool
(max/avg), FullyConnected (NC x CK), Binary (add/sub/mul/div/max/min),
Unary, Activation (relu/tanh/sigmoid), Reshape, Flatten, Softmax,
Concat, MarkOutput.  Unknown ops raise with the op name.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio import protowire as pw

_DTYPES = {131104: np.float32, 65568: np.int32,
           131088: np.float16, 65600: np.int64, 32784: np.int8}


@dataclass
class UffNode:
    id: str
    op: str
    inputs: List[str]
    fields: Dict[str, Any] = field(default_factory=dict)


@dataclass
class UffGraph:
    name: str
    nodes: Dict[str, UffNode]
    order: List[str]
    outputs: List[str]
    blobs: Dict[str, bytes]


def _decode_data(buf: bytes):
    d = pw.fields_dict(buf)
    if 1 in d:
        return d[1][0].decode()
    if 8 in d:                      # int list
        sub = pw.fields_dict(d[8][0]) if d[8][0] else {}
        vals = sub.get(1, [])
        if len(vals) == 1 and isinstance(vals[0], bytes):
            return [pw.to_signed64(v)
                    for v in pw.packed_varints(vals[0])]
        return [pw.to_signed64(int(v)) for v in vals]
    if 100 in d:                    # reference into referenced_data
        key = d[100][0].decode()
        return ("__ref__", key)
    if 101 in d:                    # dtype code
        return ("__dtype__", int(d[101][0]))
    if 9 in d:
        return bytes(d[9][0])
    if 2 in d:
        return pw.to_signed64(int(d[2][0]))
    if 3 in d:
        import struct

        return struct.unpack("<d", int(d[3][0]).to_bytes(8, "little"))[0]
    if 4 in d:
        return bool(d[4][0])
    return None


def parse_uff(path: str) -> UffGraph:
    with open(path, "rb") as f:
        raw = f.read()
    with pw.wire_context(f"uff {path!r}", BackendError):
        return _parse_uff(raw, path)


def _parse_uff(raw: bytes, path: str) -> UffGraph:
    d = pw.fields_dict(raw)
    if 4 not in d:
        raise BackendError(f"{path!r}: no graphs in UFF MetaGraph")
    blobs: Dict[str, bytes] = {}
    for rb in d.get(5, []):
        rd = pw.fields_dict(rb)
        key = pw.first(rd, 1, b"").decode()
        val = pw.fields_dict(pw.first(rd, 2, b""))
        if 9 in val:
            blobs[key] = bytes(val[9][0])
    g = pw.fields_dict(d[4][0])
    nodes: Dict[str, UffNode] = {}
    order: List[str] = []
    outputs: List[str] = []
    for nb in g.get(2, []):
        nd = pw.fields_dict(nb)
        node = UffNode(
            id=pw.first(nd, 1, b"").decode(),
            op=pw.first(nd, 3, b"").decode(),
            inputs=[x.decode() for x in nd.get(2, [])])
        for fb in nd.get(4, []):
            fd = pw.fields_dict(fb)
            key = pw.first(fd, 1, b"").decode()
            node.fields[key] = _decode_data(pw.first(fd, 2, b""))
        nodes[node.id] = node
        order.append(node.id)
        if node.op == "MarkOutput":
            outputs.extend(node.inputs)
    return UffGraph(name=pw.first(g, 1, b"").decode(), nodes=nodes,
                    order=order, outputs=outputs, blobs=blobs)


def _const_array(node: UffNode, blobs: Dict[str, bytes]) -> np.ndarray:
    dt = np.float32
    for v in node.fields.values():
        if isinstance(v, tuple) and v[0] == "__dtype__":
            if v[1] not in _DTYPES:
                raise BackendError(
                    f"uff: const {node.id} dtype code {v[1]} unknown")
            dt = _DTYPES[v[1]]
    vals = node.fields.get("values")
    if isinstance(vals, tuple) and vals[0] == "__ref__":
        raw = blobs.get(vals[1])
        if raw is None:
            raise BackendError(
                f"uff: const {node.id} references missing data "
                f"{vals[1]!r}")
    elif isinstance(vals, bytes):
        raw = vals
    else:
        raise BackendError(f"uff: const {node.id} has no values")
    arr = np.frombuffer(raw, dt)
    shape = node.fields.get("shape")
    if isinstance(shape, list) and shape:
        arr = arr.reshape([int(s) for s in shape])
    return arr.copy()


@dataclass
class UffLowered:
    fn: Any
    params: Dict[str, np.ndarray]
    name: str


def lower_uff(graph: UffGraph, input_names=None, output_names=None):
    """UffGraph → fn(params, x) -> outputs, one fused XLA program.

    UFF Input nodes carry no shape (the reference declares dims in the
    pipeline: ``input=28:28:1 inputname=in``); the returned fn is
    shape-polymorphic over NHWC inputs and the filter negotiates the
    concrete shape from pipeline caps via eval_shape — same contract
    as the TorchScript loader.  ``inputname``/``outputname`` (the
    reference's node-binding properties) validate the input binding and
    select/reorder output nodes (default: the MarkOutput set)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    nodes, blobs = graph.nodes, graph.blobs
    inputs = [n for n in graph.order if nodes[n].op == "Input"]
    if len(inputs) != 1:
        raise BackendError(
            f"uff: expected exactly one Input node, got {inputs}")
    if input_names and list(input_names) != inputs:
        raise BackendError(
            f"uff: inputname={list(input_names)} does not match the "
            f"graph's Input node {inputs}")
    if output_names:
        missing = [o for o in output_names if o not in nodes]
        if missing:
            raise BackendError(
                f"uff: outputname nodes {missing} not in the graph")
        graph = UffGraph(name=graph.name, nodes=graph.nodes,
                         order=graph.order,
                         outputs=list(output_names), blobs=graph.blobs)
    params: Dict[str, np.ndarray] = {
        n: _const_array(nodes[n], blobs)
        for n in graph.order if nodes[n].op == "Const"}

    def fn(p, x):
        # nodes serialize output-first: evaluate on demand (memoized)
        # from the marked outputs back to the Input
        vals: Dict[str, Any] = {inputs[0]: x.astype(jnp.float32)}

        def ev(name):
            if name in vals:
                return vals[name]
            if name in p:
                return jnp.asarray(p[name])
            if name not in nodes:
                raise BackendError(f"uff: unknown node {name!r}")
            out = _eval_node(nodes[name])
            vals[name] = out
            return out

        def _eval_node(nd: UffNode):
            n, op = nd.id, nd.op
            if op == "Conv":
                xin, w = ev(nd.inputs[0]), ev(nd.inputs[1])
                strides = nd.fields.get("strides") or [1, 1]
                pads = nd.fields.get("padding") or [0, 0]
                out = lax.conv_general_dilated(
                    xin, w, window_strides=[int(s) for s in strides],
                    padding=[(int(pads[0]),) * 2, (int(pads[1]),) * 2],
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
            elif op == "Pool":
                xin = ev(nd.inputs[0])
                k = [int(v) for v in nd.fields.get("kernel") or [2, 2]]
                s = [int(v) for v in nd.fields.get("strides") or k]
                pp = [int(v) for v in nd.fields.get("padding")
                      or [0, 0]]
                pads = ((0, 0), (pp[0], pp[0]), (pp[1], pp[1]), (0, 0))
                func = nd.fields.get("func", "max")
                if func == "max":
                    lo = jnp.finfo(xin.dtype).min
                    out = lax.reduce_window(
                        xin, lo, lax.max, (1, k[0], k[1], 1),
                        (1, s[0], s[1], 1), pads)
                else:
                    out = lax.reduce_window(
                        xin, np.float32(0), lax.add,
                        (1, k[0], k[1], 1), (1, s[0], s[1], 1),
                        pads) / float(k[0] * k[1])
            elif op == "FullyConnected":
                xin, w = ev(nd.inputs[0]), ev(nd.inputs[1])
                out = xin @ w                    # NC x CK
            elif op == "Binary":
                a, b = ev(nd.inputs[0]), ev(nd.inputs[1])
                # NHWC channels-last: rank-1 bias broadcasts naturally
                f = nd.fields.get("func")
                table = {"add": jnp.add, "sub": jnp.subtract,
                         "mul": jnp.multiply, "div": jnp.divide,
                         "max": jnp.maximum, "min": jnp.minimum}
                if f not in table:
                    raise BackendError(f"uff Binary func {f!r}")
                out = table[f](a, b)
            elif op == "Unary":
                f = nd.fields.get("func")
                table = {"neg": jnp.negative, "exp": jnp.exp,
                         "log": jnp.log, "abs": jnp.abs,
                         "sqrt": jnp.sqrt}
                if f not in table:
                    raise BackendError(f"uff Unary func {f!r}")
                out = table[f](ev(nd.inputs[0]))
            elif op == "Activation":
                f = nd.fields.get("func")
                table = {"relu": jax.nn.relu, "tanh": jnp.tanh,
                         "sigmoid": jax.nn.sigmoid,
                         "elu": jax.nn.elu}
                if f not in table:
                    raise BackendError(f"uff Activation func {f!r}")
                out = table[f](ev(nd.inputs[0]))
            elif op == "Reshape":
                xin = ev(nd.inputs[0])
                # the target shape is graph STRUCTURE (static), not a
                # traced tensor: read it from the parse-time constant
                if nd.inputs[1] not in params:
                    raise BackendError(
                        f"uff Reshape {n}: non-constant shape input")
                shape = [int(v) for v in
                         np.asarray(params[nd.inputs[1]]).reshape(-1)]
                out = xin.reshape(shape)
            elif op == "Flatten":
                xin = ev(nd.inputs[0])
                out = xin.reshape(xin.shape[0], -1)
            elif op == "Softmax":
                out = jax.nn.softmax(ev(nd.inputs[0]), axis=-1)
            elif op == "Concat":
                axis = nd.fields.get("axis")
                axis = 1 if axis is None else int(
                    axis[0] if isinstance(axis, list) else axis)
                out = jnp.concatenate([ev(i) for i in nd.inputs], axis)
            else:
                raise BackendError(
                    f"uff op {op!r} ({n}) has no jax lowering")
            return out

        return tuple(ev(o) for o in graph.outputs)

    return UffLowered(fn=fn, params=params, name=graph.name or "uff")
