"""Minimal protobuf wire-format reader (dependency-free).

The GraphDef loader (`graphdef.py`) needs to read TensorFlow's frozen-
graph protos without importing TensorFlow (the reference links the whole
TF runtime for this, `tensor_filter_tensorflow.cc`; here the file format
is just parsed and lowered to XLA). Protobuf's wire format is five
primitive field encodings — this module decodes them generically and the
caller interprets field numbers against the public .proto schemas.

Wire types: 0=varint, 1=fixed64, 2=length-delimited, 5=fixed32.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

Value = Union[int, bytes]


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """→ (value, new_pos). Unsigned; callers reinterpret as needed.

    Raises ValueError on truncation/corruption — the module's single
    error type (never IndexError/struct.error)."""
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise ValueError("truncated varint (corrupt protobuf)")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt protobuf)")


def to_signed64(v: int) -> int:
    """Reinterpret an unsigned varint as two's-complement int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Value]]:
    """Yield (field_number, wire_type, value) for one message's bytes.

    Length-delimited values come back as bytes; varints as unsigned int;
    fixed32/64 as their raw little-endian unsigned int.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = read_varint(buf, pos)
            yield field, wt, v
        elif wt == 1:
            if pos + 8 > n:
                raise ValueError(f"truncated fixed64 at field {field}")
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
            yield field, wt, v
        elif wt == 2:
            ln, pos = read_varint(buf, pos)
            if pos + ln > n:
                raise ValueError(
                    f"length-delimited field {field} claims {ln} bytes "
                    f"past the end (corrupt protobuf)")
            yield field, wt, bytes(buf[pos:pos + ln])
            pos += ln
        elif wt == 5:
            if pos + 4 > n:
                raise ValueError(f"truncated fixed32 at field {field}")
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
            yield field, wt, v
        elif wt in (3, 4):      # group start/end (deprecated, unused)
            raise ValueError(f"unsupported protobuf group at field {field}")
        else:
            raise ValueError(f"bad wire type {wt} for field {field}")


import contextlib


@contextlib.contextmanager
def wire_context(what: str, exc_type):
    """Translate any parse-time failure into the caller's typed error.

    A corrupt file must fail as the loader's documented error type
    (e.g. BackendError naming the file), never escape as IndexError/
    struct.error/UnicodeDecodeError from the wire internals."""
    try:
        yield
    except (ValueError, IndexError, KeyError, OverflowError,
            struct.error, UnicodeDecodeError) as e:
        raise exc_type(f"{what}: malformed file: {e}") from None


def fields_dict(buf: bytes) -> Dict[int, List[Value]]:
    """Collect all occurrences of each field (repeated-safe)."""
    out: Dict[int, List[Value]] = {}
    for field, _wt, v in iter_fields(buf):
        out.setdefault(field, []).append(v)
    return out


def first(d: Dict[int, List[Value]], field: int, default=None):
    vs = d.get(field)
    return vs[0] if vs else default


def fixed32_to_float(v: int) -> float:
    return struct.unpack("<f", struct.pack("<I", v))[0]


def packed_varints(data: bytes) -> List[int]:
    """Decode a packed repeated varint payload."""
    out = []
    pos = 0
    while pos < len(data):
        v, pos = read_varint(data, pos)
        out.append(v)
    return out
