"""Int8-native execution of quantized TFLite graphs on the MXU.

The dequantize→bf16 lowering in `tflite.py` is numerically robust but
leaves the TPU's integer matrix path unused and doubles HBM traffic
(bf16 activations instead of the file's own 8-bit ones). This module
lowers a *fully quantized* graph (the reference's
`mobilenet_v2_1.0_224_quant.tflite` shape: per-tensor uint8/int8, int32
bias) to integer arithmetic end to end:

- activations flow between ops as **int8** (uint8 tensors are shifted by
  -128 once at the graph input; every zero point is shifted with them,
  which changes no real value),
- convolutions run on the MXU's s8×s8→s32 path
  (`lax.conv_general_dilated(..., preferred_element_type=int32)`),
- zero points are handled by the accumulator decomposition

      Σ (x−zx)(w−zw) = conv(x,w) − zw·Σ_window(x) − zx·Σw + N·zx·zw

  with the runtime term `Σ_window(x)` computed **inside the same conv**
  by appending one all-ones output channel to the weights (the MXU does
  the windowed input sum as channel O; a separate reduce_window here
  measured 25× slower once fused into the graph). `Σw` per output
  channel and `N·zx·zw` fold into the bias at load time. SAME padding
  becomes an explicit pad with the input zero point so every window is
  full and `N` is uniform.
- depthwise convolutions (VPU-bound, no MXU int8 win) instead fold the
  weight zero point exactly into **int16 weights** (`w−zw` ∈ [−255,255])
  so no runtime correction is needed at all; the int8 activations are
  widened to int16 at the conv input. (An int8→float widening fused
  into a grouped conv miscompiles on this backend — ~0.2% wrong lanes —
  so the integer domain is also the safe one.)
- each op requantizes its int32 accumulator with the float multiplier
  `sx·sw/so` in f32 (exact for |acc| < 2²⁴; XLA fuses it into the conv
  epilogue), rounds half-to-even and saturates to the output tensor's
  quantized activation range — the same range TFLite's
  `CalculateActivationRangeQuantized` computes, so fused RELU/RELU6 are
  honored in the integer domain.

Reference contract being re-done TPU-first: the TFLite filter subplugin
delegating to interpreter kernels
(`ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:154`);
the kernels' integer semantics follow tensorflow/lite/kernels/internal
(quantized conv/add/pool), re-derived here for one fused XLA program.

Numerics: bit-exactness with TFLite's fixed-point multiplier is not a
goal (ties differ in the last bit); goldens assert top-1 agreement vs
`tf.lite.Interpreter` like the bf16 path (`tests/test_modelio.py`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.core.errors import BackendError
from nnstreamer_tpu.modelio.tflite import (
    OP, TensorDef, TFLiteGraph, LoweredModel,
    _ACT_NONE, _ACT_RELU, _ACT_RELU_N1_1, _ACT_RELU6, _PAD_SAME,
)

_QOPS = {OP[k] for k in (
    "CONV_2D", "DEPTHWISE_CONV_2D", "FULLY_CONNECTED", "ADD", "MUL",
    "AVERAGE_POOL_2D", "MAX_POOL_2D", "MEAN", "RESHAPE", "SQUEEZE",
    "SOFTMAX", "LOGISTIC", "CONCATENATION", "PAD", "RELU", "RELU6",
    "DEQUANTIZE", "QUANTIZE",
)}


def quantized_graph_supported(graph: TFLiteGraph) -> bool:
    """True when every op is in the integer vocabulary and every
    activation tensor carries per-tensor quantization (a float interior
    — e.g. a DEQUANTIZE→float-conv→QUANTIZE wrapper graph — falls back
    to the float lowering)."""
    from nnstreamer_tpu.modelio.tflite import _static_input_indices

    if len(graph.subgraphs) > 1:     # control-flow models → float path
        return False
    static = _static_input_indices(graph)
    for op in graph.ops:
        if op.code not in _QOPS:
            return False
        if op.code in (OP["DEQUANTIZE"], OP["QUANTIZE"]):
            continue              # the explicit float↔int boundary ops
        for idx in list(op.inputs) + list(op.outputs):
            if idx < 0 or idx in static:
                continue
            t = graph.tensors[idx]
            if t.buffer is not None and t.dtype in (np.int32, np.int64):
                continue          # int32 bias / shape constants
            if not t.quantized or t.dtype not in (np.uint8, np.int8):
                return False
    for idx in graph.inputs + graph.outputs:
        t = graph.tensors[idx]
        if not t.quantized or t.dtype not in (np.uint8, np.int8):
            return False
    return True


def _shift(t: TensorDef) -> int:
    """Stored-domain → int8-domain shift (uint8 tensors move by -128)."""
    return -128 if t.dtype == np.uint8 else 0


def _qparams(t: TensorDef) -> Tuple[np.ndarray, np.ndarray]:
    """(scale, zero_point in the shifted int8 domain) for a tensor."""
    if t.scale is None or t.scale.size == 0:
        raise BackendError(
            f"tensor {t.index} ({t.name!r}) is not quantized; int8-native "
            f"lowering needs a fully quantized graph")
    zp = (t.zero_point if t.zero_point is not None
          else np.zeros_like(t.scale, np.int64))
    return t.scale.astype(np.float64), zp.astype(np.int64) + _shift(t)


def _act_qbounds(act: int, scale: float, zp: int) -> Tuple[int, int]:
    """Fused-activation clamp bounds in the (shifted) int8 domain —
    TFLite's CalculateActivationRangeQuantized."""
    lo, hi = -128, 127

    def q(v: float) -> int:
        return int(round(v / scale)) + zp

    if act == _ACT_RELU:
        lo = max(lo, q(0.0))
    elif act == _ACT_RELU6:
        lo, hi = max(lo, q(0.0)), min(hi, q(6.0))
    elif act == _ACT_RELU_N1_1:
        lo, hi = max(lo, q(-1.0)), min(hi, q(1.0))
    elif act != _ACT_NONE:
        raise BackendError(f"unsupported fused activation {act}")
    return lo, hi


def _same_pads(in_hw, k_hw, stride, dil) -> List[Tuple[int, int]]:
    """TF SAME padding amounts per spatial dim."""
    pads = []
    for n, k, s, d in zip(in_hw, k_hw, stride, dil):
        eff = (k - 1) * d + 1
        out = -(-n // s)
        total = max((out - 1) * s + eff - n, 0)
        pads.append((total // 2, total - total // 2))
    return pads


def lower_tflite_quant(graph: TFLiteGraph,
                       batch: Optional[int] = None) -> LoweredModel:
    """Lower a fully-quantized graph to int8-native XLA."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    tensors = graph.tensors
    r = graph.reader

    orig_batch = None
    if batch is not None and graph.inputs:
        in0 = tensors[graph.inputs[0]]
        orig_batch = in0.shape[0] if in0.shape else None

    def bshape(shape):
        if batch is not None and shape and shape[0] == orig_batch:
            return (batch,) + shape[1:]
        return shape

    # -- load-time constants: shifted int8 weights, int32 biases, Σw ----
    params: Dict[str, Any] = {}
    static_consts: Dict[int, np.ndarray] = {}
    meta: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}   # idx → (s, zp')

    from nnstreamer_tpu.modelio.tflite import _static_input_indices
    consumed_static = _static_input_indices(graph)

    def shifted_const(t: TensorDef) -> np.ndarray:
        if t.dtype == np.uint8:
            return (t.buffer.astype(np.int16) - 128).astype(np.int8)
        return np.asarray(t.buffer)

    weight_of: Dict[int, int] = {}       # weight tensor idx → op position
    for k, op in enumerate(graph.ops):
        if op.code in (OP["CONV_2D"], OP["DEPTHWISE_CONV_2D"],
                       OP["FULLY_CONNECTED"]) and len(op.inputs) > 1:
            weight_of[op.inputs[1]] = k
            if len(op.inputs) > 2 and op.inputs[2] >= 0:
                weight_of[op.inputs[2]] = k   # bias folds into op{k}_b

    for t in tensors:
        if t.buffer is None:
            continue
        if t.index in consumed_static:
            static_consts[t.index] = np.asarray(t.buffer)
            continue
        if t.index in weight_of:
            continue                      # packed per-op below
        params[f"t{t.index}"] = shifted_const(t)

    def qmeta(idx) -> Tuple[np.ndarray, np.ndarray]:
        if idx not in meta:
            meta[idx] = _qparams(tensors[idx])
        return meta[idx]

    # -- per-conv packed weights + fused bias -------------------------------
    # opmeta[k] = dict of static config consumed by fn's conv branch
    opmeta: Dict[int, Dict[str, Any]] = {}

    for k, op in enumerate(graph.ops):
        o = op.opts

        def opt(fid, fmt, default, _o=o):
            # None-safe: ops may omit their options table entirely
            return r.field_scalar(_o, fid, fmt, default) \
                if _o is not None else default
        if op.code in (OP["CONV_2D"], OP["DEPTHWISE_CONV_2D"]):
            depthwise = op.code == OP["DEPTHWISE_CONV_2D"]
            xi, wi = op.inputs[0], op.inputs[1]
            (sx,), (zx,) = _qparams(tensors[xi])
            sw, zw = _qparams(tensors[wi])
            (so,), (zo,) = _qparams(tensors[op.outputs[0]])
            wnp = shifted_const(tensors[wi]).astype(np.int64)
            zw0 = int(zw[0]) if zw.size == 1 else 0
            if zw.size > 1 and np.any(zw != 0):
                raise BackendError(
                    f"per-channel nonzero weight zero points in op {k} "
                    f"are not supported by the int8-native lowering")
            if depthwise:
                kh, kw = wnp.shape[1], wnp.shape[2]
                n_taps = kh * kw
                s_w = (wnp - zw0).sum(axis=(0, 1, 2))    # per out channel
                # exact fold: int16 weights, HWIO = (kh, kw, 1, C·m)
                w_dev = np.transpose(
                    (wnp - zw0).astype(np.int16), (1, 2, 0, 3))
                stride = (opt(2, "<i", 1),
                          opt(1, "<i", 1))
                dil = (opt(6, "<i", 1),
                       opt(5, "<i", 1))
                act = opt(4, "<b", 0)
                augment = False
            else:
                kh, kw = wnp.shape[1], wnp.shape[2]
                n_taps = kh * kw * wnp.shape[3]
                s_w = wnp.sum(axis=(1, 2, 3))
                w_hwio = np.transpose(wnp.astype(np.int8), (1, 2, 3, 0))
                augment = zw0 != 0
                if augment:   # ones out-channel → Σ_window(x) on the MXU
                    ones = np.ones(w_hwio.shape[:3] + (1,), np.int8)
                    w_hwio = np.concatenate([w_hwio, ones], axis=3)
                w_dev = w_hwio
                stride = (opt(2, "<i", 1),
                          opt(1, "<i", 1))
                dil = (opt(5, "<i", 1),
                       opt(4, "<i", 1))
                act = opt(3, "<b", 0)
            bias = np.zeros(s_w.shape, np.int64)
            if len(op.inputs) > 2 and op.inputs[2] >= 0:
                bias = tensors[op.inputs[2]].buffer.astype(np.int64)
            if depthwise:
                # acc already uses exact (w−zw); only −zx·Σ(w−zw) remains
                fused_b = bias - int(zx) * s_w
            else:
                fused_b = (bias - int(zx) * s_w
                           + n_taps * int(zx) * zw0)
            params[f"op{k}_w"] = w_dev
            params[f"op{k}_b"] = fused_b.astype(np.int32)
            mult = (sx * sw / so).astype(np.float32)
            lo, hi = _act_qbounds(act, float(so), int(zo))
            opmeta[k] = dict(
                depthwise=depthwise, stride=stride, dil=dil,
                k_hw=(kh, kw), zx=int(zx), zw=zw0, augment=augment,
                pad_same=opt(0, "<b", 0) == _PAD_SAME,
                n_out=int(s_w.shape[0]),
                mult=(mult if mult.size > 1 else float(mult[0])),
                zo=int(zo), lo=lo, hi=hi)
        elif op.code == OP["FULLY_CONNECTED"]:
            xi, wi = op.inputs[0], op.inputs[1]
            (sx,), (zx,) = _qparams(tensors[xi])
            sw, zw = _qparams(tensors[wi])
            (so,), (zo,) = _qparams(tensors[op.outputs[0]])
            wnp = shifted_const(tensors[wi]).astype(np.int64)   # [O, I]
            zw0 = int(zw[0]) if zw.size == 1 else 0
            if zw.size > 1 and np.any(zw != 0):
                raise BackendError(
                    f"per-channel nonzero weight zero points in op {k} "
                    f"are not supported by the int8-native lowering")
            w_io = wnp.astype(np.int8).T                        # [I, O]
            augment = zw0 != 0
            if augment:
                w_io = np.concatenate(
                    [w_io, np.ones((w_io.shape[0], 1), np.int8)], axis=1)
            bias = np.zeros((wnp.shape[0],), np.int64)
            if len(op.inputs) > 2 and op.inputs[2] >= 0:
                bias = tensors[op.inputs[2]].buffer.astype(np.int64)
            fused_b = (bias - int(zx) * wnp.sum(axis=1)
                       + wnp.shape[1] * int(zx) * zw0)
            params[f"op{k}_w"] = w_io
            params[f"op{k}_b"] = fused_b.astype(np.int32)
            mult = (sx * sw / so).astype(np.float32)
            lo, hi = _act_qbounds(
                opt(0, "<b", 0), float(so), int(zo))
            opmeta[k] = dict(
                zx=int(zx), zw=zw0, augment=augment,
                n_out=int(wnp.shape[0]), in_features=int(wnp.shape[1]),
                mult=(mult if mult.size > 1 else float(mult[0])),
                zo=int(zo), lo=lo, hi=hi)

    def requant(acc_i32, mult, oz: int, lo: int, hi: int):
        """int32 accumulator → int8 output via f32 multiplier."""
        y = jnp.round(acc_i32.astype(jnp.float32)
                      * jnp.asarray(mult, jnp.float32)) + oz
        return jnp.clip(y, lo, hi).astype(jnp.int8)

    def opt_i(o, fid, default=0):
        return r.field_scalar(o, fid, "<i", default) if o is not None \
            else default

    def opt_b(o, fid, default=0):
        return r.field_scalar(o, fid, "<b", default) if o is not None \
            else default

    def opt_f(o, fid, default=0.0):
        return r.field_scalar(o, fid, "<f", default) if o is not None \
            else default

    def fn(p, *inputs):
        if len(inputs) != len(graph.inputs):
            raise BackendError(
                f"model {graph.path!r} expects {len(graph.inputs)} inputs, "
                f"got {len(inputs)}")
        vals: Dict[int, Any] = {}
        for idx, x in zip(graph.inputs, inputs):
            t = tensors[idx]
            x = jnp.asarray(x)
            if t.dtype == np.uint8:
                x = (x.astype(jnp.int32) - 128).astype(jnp.int8)
            vals[idx] = x

        def get(i):
            if i in vals:
                return vals[i]
            key = f"t{i}"
            if key in p:
                return jnp.asarray(p[key])
            raise BackendError(
                f"op input tensor {i} ({tensors[i].name!r}) has no value")

        for k, op in enumerate(graph.ops):
            code, o = op.code, op.opts

            if code in (OP["CONV_2D"], OP["DEPTHWISE_CONV_2D"]):
                m = opmeta[k]
                x = get(op.inputs[0])
                w = jnp.asarray(p[f"op{k}_w"])
                if m["pad_same"]:
                    pads = _same_pads(x.shape[1:3], m["k_hw"],
                                      m["stride"], m["dil"])
                    x = jnp.pad(x, [(0, 0), pads[0], pads[1], (0, 0)],
                                constant_values=np.int8(m["zx"]))
                if m["depthwise"]:
                    acc = lax.conv_general_dilated(
                        x.astype(jnp.int16), w,
                        window_strides=m["stride"], padding="VALID",
                        rhs_dilation=m["dil"],
                        feature_group_count=x.shape[-1],
                        dimension_numbers=("NHWC", "HWIO", "NHWC"),
                        preferred_element_type=jnp.int32)
                else:
                    acc = lax.conv_general_dilated(
                        x, w, window_strides=m["stride"], padding="VALID",
                        rhs_dilation=m["dil"],
                        dimension_numbers=("NHWC", "HWIO", "NHWC"),
                        preferred_element_type=jnp.int32)
                    if m["augment"]:
                        n = m["n_out"]
                        acc = acc[..., :n] - m["zw"] * acc[..., n:]
                acc = acc + jnp.asarray(p[f"op{k}_b"])
                vals[op.outputs[0]] = requant(
                    acc, m["mult"], m["zo"], m["lo"], m["hi"])
                continue

            if code == OP["FULLY_CONNECTED"]:
                m = opmeta[k]
                x = get(op.inputs[0])
                if x.ndim != 2:
                    x = x.reshape((-1, m["in_features"]))
                acc = lax.dot_general(
                    x, jnp.asarray(p[f"op{k}_w"]),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                if m["augment"]:
                    n = m["n_out"]
                    acc = acc[..., :n] - m["zw"] * acc[..., n:]
                acc = acc + jnp.asarray(p[f"op{k}_b"])
                vals[op.outputs[0]] = requant(
                    acc, m["mult"], m["zo"], m["lo"], m["hi"])
                continue

            if code in (OP["ADD"], OP["MUL"]):
                ai, bi = op.inputs[0], op.inputs[1]
                a, b = get(ai), get(bi)
                (sa,), (za,) = qmeta(ai)
                (sb,), (zb,) = qmeta(bi)
                oi = op.outputs[0]
                (so,), (zo,) = qmeta(oi)
                lo, hi = _act_qbounds(opt_b(o, 0), float(so), int(zo))
                af = (a.astype(jnp.float32) - za) * np.float32(sa)
                bf = (b.astype(jnp.float32) - zb) * np.float32(sb)
                y = af + bf if code == OP["ADD"] else af * bf
                y = jnp.round(y / np.float32(so)) + int(zo)
                vals[oi] = jnp.clip(y, lo, hi).astype(jnp.int8)
                continue

            if code in (OP["AVERAGE_POOL_2D"], OP["MAX_POOL_2D"]):
                xi = op.inputs[0]
                x = get(xi)
                oi = op.outputs[0]
                stride = (1, opt_i(o, 2, 1), opt_i(o, 1, 1), 1)
                window = (1, opt_i(o, 4, 1), opt_i(o, 3, 1), 1)
                pad_same = opt_b(o, 0) == _PAD_SAME
                (so,), (zo,) = qmeta(oi)
                lo, hi = _act_qbounds(opt_b(o, 5), float(so), int(zo))
                if code == OP["MAX_POOL_2D"]:
                    y = lax.reduce_window(
                        x, np.int8(-128), lax.max, window, stride,
                        "SAME" if pad_same else "VALID")
                    vals[oi] = jnp.clip(y, lo, hi).astype(jnp.int8)
                else:
                    # TFLite avg-pool shares scale/zp across in/out
                    s = lax.reduce_window(
                        x.astype(jnp.int32), 0, lax.add, window, stride,
                        "SAME" if pad_same else "VALID")
                    ones = jnp.ones(x.shape[1:3], jnp.int32)[None, :, :,
                                                             None]
                    cnt = lax.reduce_window(
                        ones, 0, lax.add, window, stride,
                        "SAME" if pad_same else "VALID")
                    y = jnp.round(s.astype(jnp.float32) / cnt)
                    vals[oi] = jnp.clip(y, lo, hi).astype(jnp.int8)
                continue

            if code == OP["MEAN"]:
                xi = op.inputs[0]
                x = get(xi)
                oi = op.outputs[0]
                axes = tuple(int(a) for a in
                             np.asarray(static_consts.get(
                                 op.inputs[1],
                                 tensors[op.inputs[1]].buffer)).ravel())
                keep = bool(opt_b(o, 0))
                (si,), (zi,) = qmeta(xi)
                (so,), (zo,) = qmeta(oi)
                m = jnp.mean(x.astype(jnp.float32), axis=axes,
                             keepdims=keep)
                y = jnp.round((m - zi) * np.float32(si / so)) + int(zo)
                vals[oi] = jnp.clip(y, -128, 127).astype(jnp.int8)
                continue

            if code in (OP["RESHAPE"], OP["SQUEEZE"]):
                xi = op.inputs[0]
                x = get(xi)
                oi = op.outputs[0]
                out_shape = list(tensors[oi].shape)
                if out_shape and x.size != int(np.prod(out_shape)):
                    out_shape[0] = -1          # runtime batch override
                vals[oi] = x.reshape(out_shape)
                continue

            if code == OP["CONCATENATION"]:
                oi = op.outputs[0]
                (so,), (zo,) = qmeta(oi)
                axis = opt_i(o, 0, 0)
                parts = []
                for i in op.inputs:
                    (si,), (zi,) = qmeta(i)
                    xi_v = get(i)
                    if abs(si - so) < 1e-12 and zi == zo:
                        parts.append(xi_v)
                    else:
                        y = jnp.round((xi_v.astype(jnp.float32) - zi)
                                      * np.float32(si / so)) + int(zo)
                        parts.append(jnp.clip(y, -128, 127)
                                     .astype(jnp.int8))
                vals[oi] = jnp.concatenate(parts, axis=axis)
                continue

            if code == OP["PAD"]:
                xi = op.inputs[0]
                x = get(xi)
                (_,), (zi,) = qmeta(xi)
                pads = np.asarray(static_consts.get(
                    op.inputs[1],
                    tensors[op.inputs[1]].buffer)).reshape(-1, 2)
                vals[op.outputs[0]] = jnp.pad(
                    x, [(int(a), int(b)) for a, b in pads],
                    constant_values=np.int8(zi))
                continue

            if code in (OP["RELU"], OP["RELU6"]):
                xi = op.inputs[0]
                x = get(xi)
                oi = op.outputs[0]
                (so,), (zo,) = qmeta(oi)
                act = _ACT_RELU if code == OP["RELU"] else _ACT_RELU6
                lo, hi = _act_qbounds(act, float(so), int(zo))
                vals[oi] = jnp.clip(x, lo, hi)
                continue

            if code in (OP["SOFTMAX"], OP["LOGISTIC"]):
                xi = op.inputs[0]
                x = get(xi)
                oi = op.outputs[0]
                (si,), (zi,) = qmeta(xi)
                (so,), (zo,) = qmeta(oi)
                xf = (x.astype(jnp.float32) - zi) * np.float32(si)
                if code == OP["SOFTMAX"]:
                    beta = opt_f(o, 0, 1.0)
                    yf = jax.nn.softmax(xf * beta, axis=-1)
                else:
                    yf = jax.nn.sigmoid(xf)
                y = jnp.round(yf / np.float32(so)) + int(zo)
                vals[oi] = jnp.clip(y, -128, 127).astype(jnp.int8)
                continue

            if code in (OP["DEQUANTIZE"], OP["QUANTIZE"]):
                xi = op.inputs[0]
                x = get(xi)
                oi = op.outputs[0]
                ti, to = tensors[xi], tensors[oi]
                if to.quantized and ti.quantized:
                    (si,), (zi,) = qmeta(xi)
                    (so,), (zo,) = qmeta(oi)
                    y = jnp.round((x.astype(jnp.float32) - zi)
                                  * np.float32(si / so)) + int(zo)
                    vals[oi] = jnp.clip(y, -128, 127).astype(jnp.int8)
                elif to.quantized:                 # float → int8 domain
                    (so,), (zo,) = qmeta(oi)
                    y = jnp.round(x / np.float32(so)) + int(zo)
                    vals[oi] = jnp.clip(y, -128, 127).astype(jnp.int8)
                else:                              # int8 domain → float
                    (si,), (zi,) = qmeta(xi)
                    vals[oi] = (x.astype(jnp.float32) - zi) * np.float32(si)
                continue

            raise BackendError(
                f"TFLite op {op.name} is outside the int8-native "
                f"vocabulary; use compute_dtype='bfloat16' for "
                f"{graph.path!r}")

        results = []
        for idx in graph.outputs:
            t = tensors[idx]
            y = vals[idx]
            if t.dtype == np.uint8:
                y = (y.astype(jnp.int32) + 128).astype(jnp.uint8)
            results.append(y)
        return tuple(results)

    def io_dtype(t: TensorDef) -> np.dtype:
        return t.dtype

    return LoweredModel(
        fn=fn, params=params,
        in_shapes=[bshape(tensors[i].shape) for i in graph.inputs],
        in_dtypes=[io_dtype(tensors[i]) for i in graph.inputs],
        out_shapes=[bshape(tensors[i].shape) for i in graph.outputs],
        out_dtypes=[io_dtype(tensors[i]) for i in graph.outputs],
        name=f"{graph.path.rsplit('/', 1)[-1]}[int8]")
