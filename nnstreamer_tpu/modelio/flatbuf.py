"""Minimal FlatBuffers binary reader.

Self-contained decoder for the FlatBuffers wire format (little-endian,
vtable-based tables) — enough to walk a schema'd file like `.tflite`
without the flatbuffers runtime or generated schema code. Read-only and
zero-copy: byte vectors are returned as numpy views into the file buffer.

Wire format summary (flatbuffers internals doc):
- root: uint32 offset at position 0 to the root table.
- table: int32 at table-pos is the *backwards* offset to its vtable;
  vtable = [u16 vtable_bytes, u16 table_bytes, u16 slot_0, u16 slot_1, …]
  where slot_i is the field's offset from table-pos (0 / absent ⇒ field
  not present, use schema default).
- scalar fields are inline at table_pos+slot; reference fields (string /
  vector / table) hold a uint32 forward offset relative to their own
  position.
- vector: u32 count then elements (inline scalars, or u32 offsets).
- string: u32 length then utf-8 bytes.
"""

from __future__ import annotations

import struct
from typing import List, Optional

import numpy as np


class Reader:
    """Cursor-free reader over one flatbuffer."""

    def __init__(self, buf: bytes):
        self.buf = buf

    # -- primitive loads ---------------------------------------------------
    def u8(self, pos: int) -> int:
        return self.buf[pos]

    def i8(self, pos: int) -> int:
        return struct.unpack_from("<b", self.buf, pos)[0]

    def u16(self, pos: int) -> int:
        return struct.unpack_from("<H", self.buf, pos)[0]

    def i32(self, pos: int) -> int:
        return struct.unpack_from("<i", self.buf, pos)[0]

    def u32(self, pos: int) -> int:
        return struct.unpack_from("<I", self.buf, pos)[0]

    def i64(self, pos: int) -> int:
        return struct.unpack_from("<q", self.buf, pos)[0]

    def f32(self, pos: int) -> float:
        return struct.unpack_from("<f", self.buf, pos)[0]

    def f64(self, pos: int) -> float:
        return struct.unpack_from("<d", self.buf, pos)[0]

    # -- tables ------------------------------------------------------------
    def root(self) -> int:
        return self.u32(0)

    def field_pos(self, table: int, fid: int) -> Optional[int]:
        """Absolute position of field `fid` in `table`, or None if absent."""
        vtable = table - self.i32(table)
        entry = 4 + 2 * fid
        if entry >= self.u16(vtable):
            return None
        slot = self.u16(vtable + entry)
        return table + slot if slot else None

    def indirect(self, pos: int) -> int:
        return pos + self.u32(pos)

    # -- typed field accessors (with schema defaults) -----------------------
    def field_scalar(self, table: int, fid: int, fmt: str, default=0):
        pos = self.field_pos(table, fid)
        if pos is None:
            return default
        return struct.unpack_from(fmt, self.buf, pos)[0]

    def field_bool(self, table: int, fid: int, default=False) -> bool:
        return bool(self.field_scalar(table, fid, "<b", int(default)))

    def field_table(self, table: int, fid: int) -> Optional[int]:
        pos = self.field_pos(table, fid)
        return self.indirect(pos) if pos is not None else None

    def field_string(self, table: int, fid: int) -> Optional[str]:
        pos = self.field_pos(table, fid)
        if pos is None:
            return None
        spos = self.indirect(pos)
        n = self.u32(spos)
        return bytes(self.buf[spos + 4:spos + 4 + n]).decode("utf-8")

    # -- vectors -------------------------------------------------------------
    def _vec(self, table: int, fid: int):
        pos = self.field_pos(table, fid)
        if pos is None:
            return None, 0
        vpos = self.indirect(pos)
        return vpos + 4, self.u32(vpos)

    def field_vec_scalars(self, table: int, fid: int,
                          dtype: np.dtype) -> Optional[np.ndarray]:
        """Scalar vector as a zero-copy numpy view (little-endian host)."""
        base, n = self._vec(table, fid)
        if base is None:
            return None
        dtype = np.dtype(dtype)
        return np.frombuffer(self.buf, dtype=dtype, count=n, offset=base)

    def field_vec_tables(self, table: int, fid: int) -> List[int]:
        base, n = self._vec(table, fid)
        if base is None:
            return []
        return [self.indirect(base + 4 * i) for i in range(n)]

    def field_vec_strings(self, table: int, fid: int) -> List[str]:
        base, n = self._vec(table, fid)
        if base is None:
            return []
        out = []
        for i in range(n):
            spos = self.indirect(base + 4 * i)
            ln = self.u32(spos)
            out.append(bytes(self.buf[spos + 4:spos + 4 + ln])
                       .decode("utf-8"))
        return out

    def field_vec_len(self, table: int, fid: int) -> int:
        _, n = self._vec(table, fid)
        return n
