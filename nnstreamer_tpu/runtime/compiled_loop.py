"""Steady-state detector for the scheduler's compiled multi-step loop.

NNStreamer's claim is that pipeline overhead disappears next to the
model; our per-frame hot path still pays Python on every frame —
dispatch decision, tracer stamps, sync-window bookkeeping. For a
pipeline in *steady state* (the same tensor signature frame after
frame, which is what a camera or an open-loop benchmark produces), all
of that work is identical per frame and can be amortized: after the
detector arms, the scheduler sweeps the frames already queued on the
element's channel into one window and hands them to a single jitted
K-step ``jax.lax.scan`` body (`TensorFilter.process_window` →
`XLABackend.invoke_window`), so the host thread touches Python once
per window instead of once per frame.

Entry and exit are *guarded*, never speculative:

- the detector arms only after ``arm_after`` consecutive frames with an
  identical signature (shapes + dtypes + dyn-batch count);
- any divergence drops straight back to per-frame mode with the cause
  accounted (``shape``, ``error``, ``swap``, ``timer``, ``eos``) and
  stats reconciled exactly — a window that fails mid-flight re-runs its
  frames through the ordinary per-frame path so error policies land on
  the precise frame that faulted;
- EOS drains whatever partial window was collected, then cascades.

This module is deliberately host-only: signatures, arming, and the
bail ledger. The jitted window itself lives in the backend.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

#: bail causes, in the order report() prints them
BAIL_CAUSES = ("shape", "error", "swap", "timer", "eos")


def frame_signature(buf) -> Optional[Tuple]:
    """Steady-state identity of one frame: per-tensor (shape, dtype)
    plus the dynamic-batch row count when present. Two frames with
    equal signatures hit the same jitted bucket; a signature change is
    exactly a recompile risk, which is exactly a window bail. Reads
    only ``.shape``/``.dtype`` attributes — never materializes a
    device array (this runs on the scheduler hot path, where an
    implicit host sync would defeat the whole bypass)."""
    try:
        tensors = buf.tensors
    except Exception:
        return None           # non-tensor payloads never enter a window
    rows = []
    for t in tensors:
        dt = getattr(t, "dtype", None)
        if dt is None:        # dtype-less payload: stay per-frame
            return None
        rows.append((tuple(np.shape(t)), str(dt)))
    sig: Tuple = tuple(rows)
    dyn = buf.meta.get("dyn_batch") if isinstance(buf.meta, dict) else None
    if isinstance(dyn, dict) and "n" in dyn:
        sig = sig + (("dyn_n", int(dyn["n"])),)
    return sig


class SteadyStateDetector:
    """Arms after ``arm_after`` consecutive identical-signature frames.

    One detector per (runner, element). ``observe()`` is on the hot
    path — a tuple compare and an int bump, nothing else.
    """

    __slots__ = ("arm_after", "_sig", "_streak")

    def __init__(self, arm_after: int = 4):
        self.arm_after = max(1, int(arm_after))
        self._sig: Optional[Tuple] = None
        self._streak = 0

    def observe(self, sig: Optional[Tuple]) -> bool:
        """Feed one frame's signature; returns True when armed (this
        frame extends an identical streak of >= arm_after)."""
        if sig is None:
            self._sig, self._streak = None, 0
            return False
        if sig == self._sig:
            self._streak += 1
        else:
            self._sig, self._streak = sig, 1
        return self._streak >= self.arm_after

    @property
    def armed(self) -> bool:
        return self._streak >= self.arm_after

    @property
    def signature(self) -> Optional[Tuple]:
        return self._sig

    def reset(self) -> None:
        self._sig, self._streak = None, 0


class LoopStats:
    """Per-element compiled-loop ledger the scheduler owns.

    ``entries`` counts windows entered, ``steps`` counts frames that
    went through a compiled window (so ``steps / buffers`` is the
    compiled-window share report() prints), ``bails`` counts armed
    windows that fell back, by cause.
    """

    __slots__ = ("entries", "steps", "bails")

    def __init__(self):
        self.entries = 0
        self.steps = 0
        self.bails: Dict[str, int] = {}

    def bail(self, cause: str) -> None:
        self.bails[cause] = self.bails.get(cause, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        return {"loop_entries": self.entries,
                "compiled_steps": self.steps,
                "loop_bails": dict(self.bails)}
