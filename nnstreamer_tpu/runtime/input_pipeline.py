"""Double-buffered host→device input staging.

The reference's filter path hands each frame to the framework
synchronously (`gst/nnstreamer/tensor_filter/tensor_filter.c` chain
function: map buffer → invoke → unmap); any H2D copy serializes with
compute. On TPU the equivalent naive loop leaves the chip idle for the
whole transfer (measured 27× slowdown over the tunnel at batch 64 —
`VERDICT.md` round 2 weak #1b). The TPU-first design streams instead:
`jax.device_put` is asynchronous, so staging batch N+1 can ride the DMA
engines while batch N computes. This module provides that overlap as a
reusable component:

- `prefetch_to_device(it, depth)` — wrap any host-batch iterator; a
  background thread issues `device_put` up to `depth` batches ahead and
  a bounded queue provides backpressure.
- `DeviceFeeder` — push-style variant for the streaming pipeline: the
  scheduler thread calls `put(host_batch)` (non-blocking up to the
  buffer depth) and the compute side calls `get()`.

Used by `bench.py`'s batch sweep (pipelined-H2D measurement); designed
as the staging layer for batched offload serving (`QueryServer` +
`MeshDispatcher`).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = ["prefetch_to_device", "DeviceFeeder"]

_STOP = object()


def _default_put(x, device):
    import jax

    if device is None:
        return jax.device_put(x)
    return jax.device_put(x, device)


def prefetch_to_device(it: Iterable[Any], depth: int = 2,
                       device: Any = None,
                       put: Optional[Callable[[Any, Any], Any]] = None
                       ) -> Iterator[Any]:
    """Yield device arrays for each host batch in `it`, staging up to
    `depth` batches ahead of the consumer.

    `put` overrides the transfer function (e.g. a sharded device_put
    with a NamedSharding for multi-chip feeds). Exceptions from the
    source iterator or the transfer re-raise at the consumer.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    put = put or _default_put
    q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
    cancelled = threading.Event()

    def worker():
        try:
            for x in it:
                staged = put(x, device)
                # device_put is async: the DMA overlaps the consumer's
                # compute on the previous batch. Bounded put so an
                # abandoned consumer doesn't pin this thread (and its
                # staged device buffers) forever.
                while not cancelled.is_set():
                    try:
                        q.put(staged, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if cancelled.is_set():
                    return
            item = _STOP
        except BaseException as e:      # surface at the consumer side
            item = e
        while not cancelled.is_set():
            try:
                q.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    t = threading.Thread(target=worker, name="device-prefetch",
                         daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _STOP:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # consumer closed the generator (break / exception): release the
        # worker and drop staged buffers
        cancelled.set()


class DeviceFeeder:
    """Push-style double buffer between a producer thread (pipeline
    scheduler / query server) and the device compute loop.

    put() stages the host batch onto the device immediately (async DMA)
    and enqueues the device array; it blocks only when `depth` staged
    batches are already waiting — that backpressure bounds device-memory
    use. get() returns the next staged batch (blocking), so the compute
    loop always finds its input already on-chip.
    """

    def __init__(self, depth: int = 2, device: Any = None,
                 put: Optional[Callable[[Any, Any], Any]] = None):
        if depth < 1:
            raise ValueError(f"feeder depth must be >= 1, got {depth}")
        # one extra slot is reserved for the close() sentinel so closing
        # never blocks behind staged batches; the semaphore keeps data
        # occupancy at `depth`
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth + 1)
        self._slots = threading.BoundedSemaphore(depth)
        self._device = device
        self._put = put or _default_put
        self._closed = False
        # serializes the closed-check with the enqueue so a concurrent
        # close() cannot slip its sentinel between them (which would
        # silently drop the racing batch behind EOS)
        self._lock = threading.Lock()

    def put(self, host_batch: Any, timeout: Optional[float] = None) -> None:
        if self._closed:      # cheap fast path (re-checked under lock)
            raise RuntimeError("DeviceFeeder is closed")
        if not self._slots.acquire(timeout=timeout):
            raise queue.Full("DeviceFeeder staging buffer is full")
        try:
            staged = self._put(host_batch, self._device)
            with self._lock:
                if self._closed:
                    raise RuntimeError("DeviceFeeder is closed")
                self._q.put(staged)  # nnlint: disable=NNL003 non-blocking by invariant: _slots caps data at depth, maxsize=depth+1
        except BaseException:
            self._slots.release()
            raise

    def close(self) -> None:
        """Signal end of stream; get() returns None after draining."""
        with self._lock:
            if not self._closed:
                self._closed = True
                self._q.put(_STOP)  # nnlint: disable=NNL003 non-blocking by invariant: the +1 queue slot is reserved for this sentinel

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        item = self._q.get(timeout=timeout)
        if item is _STOP:
            self._q.put(_STOP)      # keep returning None for late gets
            return None
        self._slots.release()
        return item

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
