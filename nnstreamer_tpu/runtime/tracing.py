"""Pipeline tracing: span events, interlatency, queue gauges, Chrome trace.

The reference outsources pipeline observability to external GstShark
tracers (SURVEY.md §5.1); here the four tracers that matter for pipeline
tuning are first-class runtime citizens:

- proctime    → always-on `ElementStats` (scheduler.py) + "X" span events
                per element invocation when tracing is on
- interlatency→ per-buffer source-timestamp tagging: every source emit
                stamps `buf.meta[SOURCE_TS_META]`; every downstream
                element records (now - source_ts) into a bounded
                reservoir, giving p50/p95/p99 end-to-end latency *per
                element* (the sink rows are the pipeline latency)
- queuelevel  → queue-depth gauges sampled at enqueue/dequeue ("C"
                counter events) + an always-on per-queue high-water mark
- framerate   → tensor_filter's native throughput prop (stats() rows)

Two implementations share one duck type: `Tracer` (recording) and
`NullTracer` (`NULL_TRACER`, the default). The scheduler keeps hooks out
of the hot path by guarding every call site with `if tracer.active:` —
a traced-off run pays one attribute load per buffer, nothing else.

Ring-buffer discipline: events land in a `collections.deque(maxlen=N)`.
`deque.append` is atomic under the GIL, so worker threads record without
a lock; when the ring wraps, the oldest events fall off and
`events_dropped` in `summary()` says how many.

Export: `to_chrome_trace()` emits the Trace Event Format JSON that
chrome://tracing and Perfetto load — one named track (tid) per element
thread, "X" complete spans for process/timer/flush/backend work, "C"
counters for queue depth, "i" instants for EOS/drops/batch flushes.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

#: TensorBuffer.meta key carrying the pipeline-entry timestamp
#: (time.perf_counter seconds, stamped by the scheduler at source emit).
#: `with_tensors` copies meta, and tensor_batch carries per-frame metas
#: through `dyn_batch.frames`, so the stamp survives every element.
SOURCE_TS_META = "_trace_src_ts"


def percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = max(1, min(len(sorted_vals),
                   math.ceil(p / 100.0 * len(sorted_vals))))
    return sorted_vals[k - 1]


class NullTracer:
    """Do-nothing tracer: the default. Every hook exists so callers can
    skip the `.active` guard where the call is not on a hot path."""

    active = False

    def source_emit(self, name, buf, t):
        pass

    def enqueue(self, dst, depth, t):
        pass

    def dequeue(self, name, depth, t):
        pass

    def record_process(self, name, buf, t0, t1):
        pass

    def record_timer(self, name, t0, t1):
        pass

    def record_flush(self, name, t0, t1):
        pass

    def record_eos(self, name, t):
        pass

    def record_drop(self, name, t):
        pass

    def record_error(self, name, exc_type, t, **args):
        pass

    def record_watchdog(self, name, kind, t, **args):
        pass

    def backend_span(self, name, kind, t0, t1, **args):
        pass

    def record_swap(self, name, t, **args):
        pass

    def record_llm_request(self, name, req_id, t, **args):
        pass

    def record_forced_sync(self, name, t):
        pass

    def record_inflight(self, name, depth, t):
        pass

    def record_shed(self, name, cause, t, **args):
        pass

    def record_worker_event(self, name, wid, kind, t, **args):
        pass

    def instant(self, name, label, t=None, **args):
        pass


#: shared no-op singleton — scheduler, elements and backends all default
#: to this; PipelineRunner(trace=True) swaps in a recording Tracer
NULL_TRACER = NullTracer()

# event tuple layout: (ph, cat, name, label, ts, dur, args)
_Event = Tuple[str, str, str, str, float, float, Any]


class Tracer:
    """Recording tracer fed by the scheduler's hook points.

    All hooks are called from element worker threads; state is designed
    so no lock is needed: the event ring is an atomic-append deque, each
    element's interlatency reservoir is touched only by that element's
    own worker, and the gauge peak update is a benign read-modify-write
    (a lost race costs one sample, never a crash).
    """

    active = True

    def __init__(self, max_events: int = 65536,
                 max_latency_samples: int = 8192):
        self._t0 = time.perf_counter()
        self._events: Deque[_Event] = deque(maxlen=max_events)
        self._total_events = 0
        self._max_latency_samples = max_latency_samples
        # element name -> reservoir of (t_done - t_source_emit) seconds
        self._interlat: Dict[str, Deque[float]] = {}
        # dst element name -> {"peak": max depth ever sampled}
        self._gauges: Dict[str, Dict[str, int]] = {}
        # model hot-swap adoptions (serving/store.py): kept whole (not
        # just ring events) so report() can render every swap even after
        # the event ring wraps
        self._swaps: List[Tuple[str, float, dict]] = []
        # retired LLM requests (llm/engine.py): same keep-whole
        # rationale as swaps
        self._llm_requests: List[Tuple[str, str, float, dict]] = []
        # element name -> count of forced host syncs (runtime/sync.py)
        self._forced: Dict[str, int] = {}
        # element name -> {"peak": max async in-flight depth sampled}
        self._inflight: Dict[str, Dict[str, int]] = {}
        # server name -> {cause: count} of admission sheds/rejections
        # (edge/query.py): kept whole like swaps — per-cause shed
        # totals must survive ring wrap under sustained overload
        self._sheds: Dict[str, Dict[str, int]] = {}
        # worker-pool lifecycle events (serving/pool.py): kept whole —
        # a post-mortem needs the full spawn/kill/restart/degraded
        # sequence even after a chaos run wraps the ring
        self._worker_events: List[Tuple[str, int, str, float, dict]] = []

    # -- scheduler hooks ---------------------------------------------------
    def source_emit(self, name: str, buf, t: float) -> None:
        """Stamp the buffer's pipeline-entry time (interlatency origin)."""
        meta = getattr(buf, "meta", None)
        if isinstance(meta, dict):
            meta[SOURCE_TS_META] = t
        self._append("i", "source", name, "emit", t, 0.0, None)

    def enqueue(self, dst: str, depth: int, t: float) -> None:
        self._gauge(dst, depth, t)

    def dequeue(self, name: str, depth: int, t: float) -> None:
        self._gauge(name, depth, t)

    def record_process(self, name: str, buf, t0: float, t1: float) -> None:
        self._append("X", "element", name, "process", t0, t1 - t0, None)
        src_ts = self._buf_source_ts(buf)
        if src_ts is not None:
            r = self._interlat.get(name)
            if r is None:
                r = self._interlat[name] = deque(
                    maxlen=self._max_latency_samples)
            r.append(t1 - src_ts)

    def record_timer(self, name: str, t0: float, t1: float) -> None:
        self._append("X", "element", name, "timer", t0, t1 - t0, None)

    def record_flush(self, name: str, t0: float, t1: float) -> None:
        self._append("X", "element", name, "flush", t0, t1 - t0, None)

    def record_eos(self, name: str, t: float) -> None:
        self._append("i", "element", name, "eos", t, 0.0, None)

    def record_drop(self, name: str, t: float) -> None:
        self._append("i", "element", name, "buffer_dropped", t, 0.0, None)

    def record_error(self, name: str, exc_type: str, t: float,
                     **args) -> None:
        """A process() exception handled by the element's error policy
        (args carry policy/outcome: skipped, retried, degraded)."""
        args = dict(args, exc=exc_type)
        self._append("i", "error", name, "error", t, 0.0, args)

    def record_watchdog(self, name: str, kind: str, t: float,
                        **args) -> None:
        """A watchdog warning: kind is "stall" (process() over budget)
        or "queue" (input queue at capacity over budget)."""
        self._append("i", "watchdog", name, f"watchdog_{kind}", t, 0.0,
                     args or None)

    def backend_span(self, name: str, kind: str, t0: float, t1: float,
                     **args) -> None:
        """Backend-side span (compile/invoke) attributed to the owning
        tensor_filter's track; args carry bucket/cache-hit details."""
        self._append("X", "backend", name, kind, t0, t1 - t0, args or None)

    def record_swap(self, name: str, t: float, **args) -> None:
        """A store-driven model hot swap adopted by `name`'s backend
        (serving/store.py); args carry model/from_version/to_version/
        epoch/prewarmed."""
        self._swaps.append((name, t, dict(args)))
        self._append("i", "swap", name, "model_swap", t, 0.0,
                     args or None)

    def swap_events(self) -> List[Tuple[str, float, dict]]:
        return list(self._swaps)

    def record_llm_request(self, name: str, req_id: str, t: float,
                           **args) -> None:
        """One retired LLM request (llm/engine.py); args carry the
        request summary: prompt_len/n_tokens/first_token_ms/itl_p50_ms/
        finish_reason. Kept whole like swaps so per-request serving
        latency survives ring wrap."""
        self._llm_requests.append((name, req_id, t, dict(args)))
        self._append("i", "llm", name, "llm_request", t, 0.0,
                     dict(args, req_id=req_id))

    def llm_requests(self) -> List[Tuple[str, str, float, dict]]:
        return list(self._llm_requests)

    def record_forced_sync(self, name: str, t: float) -> None:
        """A semantic host sync (runtime/sync.py device_sync with
        forced=True): a sink draining results, a filter in
        latency_mode=sync, or backend warm-up. These are the host-path
        tax async dispatch exists to remove — count per element."""
        self._forced[name] = self._forced.get(name, 0) + 1
        self._append("i", "sync", name, "forced_sync", t, 0.0, None)

    def forced_syncs(self) -> Dict[str, int]:
        return dict(self._forced)

    def record_inflight(self, name: str, depth: int, t: float) -> None:
        """Async-dispatch window gauge: number of unresolved device
        results a DEVICE_RESIDENT element holds in flight (sampled after
        the window drain, so the recorded peak never exceeds
        [runtime] max_inflight)."""
        g = self._inflight.get(name)
        if g is None:
            g = self._inflight[name] = {"peak": 0}
        if depth > g["peak"]:
            g["peak"] = depth
        self._append("C", "inflight", name, "inflight_dispatch", t, 0.0,
                     depth)

    def inflight_gauges(self) -> Dict[str, dict]:
        return {name: dict(g) for name, g in self._inflight.items()}

    def record_shed(self, name: str, cause: str, t: float,
                    **args) -> None:
        """One request refused or shed at a query server's admission
        queue (edge/query.py). `cause` is the admission taxonomy:
        queue_full / inflight_full / deadline / reject_oldest /
        dispatch_error / shutdown. Dict writes under the GIL — a lost
        race between two reader threads costs one count at worst."""
        c = self._sheds.get(name)
        if c is None:
            c = self._sheds[name] = {}
        c[cause] = c.get(cause, 0) + 1
        self._append("i", "admission", name, f"shed_{cause}", t, 0.0,
                     args or None)

    def shed_counts(self) -> Dict[str, Dict[str, int]]:
        return {name: dict(c) for name, c in self._sheds.items()}

    def record_worker_event(self, name: str, wid: int, kind: str,
                            t: float, **args) -> None:
        """One worker-pool lifecycle event (serving/pool.py). `kind` is
        the supervision taxonomy: spawn / ready / kill / exit / restart
        / reoffer / degraded / swap_commit / swap_abort / drain_stop.
        wid is the pool slot (-1 for pool-level events like swaps)."""
        self._worker_events.append((name, wid, kind, t, dict(args)))
        self._append("i", "worker", f"{name}/w{wid}", f"worker_{kind}",
                     t, 0.0, args or None)

    def worker_events(self) -> List[Tuple[str, int, str, float, dict]]:
        return list(self._worker_events)

    def worker_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-pool event-kind totals (the summary() view; the full
        ordered sequence is worker_events())."""
        out: Dict[str, Dict[str, int]] = {}
        for name, _wid, kind, _t, _args in self._worker_events:
            c = out.setdefault(name, {})
            c[kind] = c.get(kind, 0) + 1
        return out

    def instant(self, name: str, label: str, t: Optional[float] = None,
                **args) -> None:
        if t is None:
            t = time.perf_counter()
        self._append("i", "element", name, label, t, 0.0, args or None)

    # -- internals ---------------------------------------------------------
    def _append(self, ph: str, cat: str, name: str, label: str,
                ts: float, dur: float, args) -> None:
        self._total_events += 1
        self._events.append((ph, cat, name, label, ts, dur, args))

    def _gauge(self, dst: str, depth: int, t: float) -> None:
        g = self._gauges.get(dst)
        if g is None:
            g = self._gauges[dst] = {"peak": 0}
        if depth > g["peak"]:
            g["peak"] = depth
        self._append("C", "queue", dst, "queue_depth", t, 0.0, depth)

    @staticmethod
    def _buf_source_ts(buf) -> Optional[float]:
        """Earliest source timestamp reachable from `buf` — the direct
        stamp, or for a micro-batch the oldest frame's stamp (the
        deadline-bound frame is the one whose latency matters)."""
        meta = getattr(buf, "meta", None)
        if not isinstance(meta, dict):
            return None
        ts = meta.get(SOURCE_TS_META)
        if ts is not None:
            return ts
        db = meta.get("dyn_batch")
        if isinstance(db, dict):
            stamps = [f["meta"][SOURCE_TS_META]
                      for f in db.get("frames", ())
                      if isinstance(f.get("meta"), dict)
                      and SOURCE_TS_META in f["meta"]]
            if stamps:
                return min(stamps)
        return None

    # -- read-out ----------------------------------------------------------
    def events(self) -> List[_Event]:
        return list(self._events)

    @property
    def events_dropped(self) -> int:
        return max(0, self._total_events - len(self._events))

    def interlatency(self) -> Dict[str, dict]:
        """Per-element end-to-end latency percentiles (ms) from source
        emit to completion of that element's process()."""
        out = {}
        for name, r in self._interlat.items():
            vals = sorted(r)
            if not vals:
                continue
            out[name] = {
                "n": len(vals),
                "p50_ms": 1e3 * percentile(vals, 50),
                "p95_ms": 1e3 * percentile(vals, 95),
                "p99_ms": 1e3 * percentile(vals, 99),
                "max_ms": 1e3 * vals[-1],
            }
        return out

    def queue_gauges(self) -> Dict[str, dict]:
        return {name: dict(g) for name, g in self._gauges.items()}

    def summary(self) -> dict:
        return {
            "interlatency": self.interlatency(),
            "queues": self.queue_gauges(),
            "events": len(self._events),
            "events_dropped": self.events_dropped,
            "swaps": len(self._swaps),
            "llm_requests": len(self._llm_requests),
            "forced_syncs": dict(self._forced),
            "inflight": self.inflight_gauges(),
            "sheds": self.shed_counts(),
            "workers": self.worker_counts(),
        }

    def to_chrome_trace(self, pipeline_name: str = "pipeline") -> dict:
        """Trace Event Format dict — `json.dump` it and load the file in
        Perfetto or chrome://tracing. One track (tid) per element, in
        order of first appearance; ts/dur in µs relative to tracer
        creation."""
        trace: List[dict] = [{
            "ph": "M", "name": "process_name", "pid": 0, "tid": 0,
            "args": {"name": pipeline_name},
        }]
        tids: Dict[str, int] = {}

        def tid_of(name: str) -> int:
            t = tids.get(name)
            if t is None:
                t = tids[name] = len(tids) + 1
                trace.append({"ph": "M", "name": "thread_name",
                              "pid": 0, "tid": t,
                              "args": {"name": name}})
            return t

        for ph, cat, name, label, ts, dur, args in list(self._events):
            us = round((ts - self._t0) * 1e6, 3)
            if ph == "X":
                ev = {"ph": "X", "cat": cat, "name": label, "pid": 0,
                      "tid": tid_of(name), "ts": us,
                      "dur": round(dur * 1e6, 3)}
                if args:
                    ev["args"] = dict(args)
            elif ph == "C":
                track = ("inflight" if cat == "inflight"
                         else "queue")
                ev = {"ph": "C", "cat": cat, "name": f"{track}:{name}",
                      "pid": 0, "tid": 0, "ts": us,
                      "args": {"depth": args}}
            else:  # "i" instant, scoped to the element's thread track
                ev = {"ph": "i", "cat": cat, "name": label, "pid": 0,
                      "tid": tid_of(name), "ts": us, "s": "t"}
                if args:
                    ev["args"] = dict(args)
            trace.append(ev)
        return {"traceEvents": trace, "displayTimeUnit": "ms"}
