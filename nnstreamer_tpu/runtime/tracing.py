"""Pipeline tracing: span events, interlatency, queue gauges, Chrome trace.

The reference outsources pipeline observability to external GstShark
tracers (SURVEY.md §5.1); here the four tracers that matter for pipeline
tuning are first-class runtime citizens:

- proctime    → always-on `ElementStats` (scheduler.py) + "X" span events
                per element invocation when tracing is on
- interlatency→ per-buffer source-timestamp tagging: every source emit
                stamps `buf.meta[SOURCE_TS_META]`; every downstream
                element records (now - source_ts) into a bounded
                reservoir, giving p50/p95/p99 end-to-end latency *per
                element* (the sink rows are the pipeline latency)
- queuelevel  → queue-depth gauges sampled at enqueue/dequeue ("C"
                counter events) + an always-on per-queue high-water mark
- framerate   → tensor_filter's native throughput prop (stats() rows)

Two implementations share one duck type: `Tracer` (recording) and
`NullTracer` (`NULL_TRACER`, the default). The scheduler keeps hooks out
of the hot path by guarding every call site with `if tracer.active:` —
a traced-off run pays one attribute load per buffer, nothing else.

Ring-buffer discipline: events land in a `collections.deque(maxlen=N)`.
`deque.append` is atomic under the GIL, so worker threads record without
a lock; when the ring wraps, the oldest events fall off and
`events_dropped` in `summary()` says how many.

Export: `to_chrome_trace()` emits the Trace Event Format JSON that
chrome://tracing and Perfetto load — one named track (tid) per element
thread, "X" complete spans for process/timer/flush/backend work, "C"
counters for queue depth, "i" instants for EOS/drops/batch flushes.

Distributed tracing (docs/observability.md §distributed):

- **trace context** — a request-scoped `trace_id` + hop-stamp list that
  rides frame meta (`meta["_trace_ctx"]`, wire-serializable JSON) from
  the query client through admission, the pool router, the worker pipe,
  the worker's pipeline, and back in the reply. `ensure_trace_ctx`
  creates it exactly once per request (a BUSY retry or a pool
  redelivery REUSES the id — new hops, never a fresh id); `stamp_hop`
  appends one `{hop, t, pid}` record and is a no-op when no context
  rides the buffer, so untraced traffic pays one dict lookup.
- **child tracers** — a worker process runs its own `Tracer` and ships
  `ship_delta()` payloads (drained event batches + monotone counter /
  histogram deltas) over its pipe; the parent's `ingest_child` merges
  them with a per-worker clock offset sampled at handshake, so
  `to_chrome_trace()` renders one Perfetto *process* (track group) per
  worker and `summary()` is pool-wide. Counter merging is delta-based,
  which makes parent totals monotone across worker restarts (a fresh
  worker simply resumes contributing deltas from zero).
"""

from __future__ import annotations

import bisect
import math
import os
import time
import uuid
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

#: TensorBuffer.meta key carrying the pipeline-entry timestamp
#: (time.perf_counter seconds, stamped by the scheduler at source emit).
#: `with_tensors` copies meta, and tensor_batch carries per-frame metas
#: through `dyn_batch.frames`, so the stamp survives every element.
SOURCE_TS_META = "_trace_src_ts"

#: TensorBuffer.meta key carrying the request-scoped trace context:
#: ``{"id": <16-hex>, "hops": [{"hop": str, "t": float, "pid": int,
#: ...extra}, ...]}``. Everything inside is wire-JSON-safe (edge/wire.py
#: serializes nested dicts/lists), so the context crosses the query
#: wire and the worker pipe intact and comes back in the reply.
TRACE_CTX_META = "_trace_ctx"


def new_trace_id() -> str:
    """16-hex request id (random, collision-safe at serving scale)."""
    return uuid.uuid4().hex[:16]


def ensure_trace_ctx(meta: dict, trace_id: Optional[str] = None) -> dict:
    """Get-or-create the trace context in `meta`. Creation happens at
    most once per request: a retry path re-offering the SAME buffer
    finds the existing context and keeps its id — the invariant the
    retry regression tests pin."""
    ctx = meta.get(TRACE_CTX_META)
    if not isinstance(ctx, dict) or "id" not in ctx:
        ctx = meta[TRACE_CTX_META] = {
            "id": trace_id or new_trace_id(), "hops": []}
    elif not isinstance(ctx.get("hops"), list):
        ctx["hops"] = []
    return ctx


def get_trace_ctx(meta) -> Optional[dict]:
    """The trace context riding `meta`, or None (never creates)."""
    if not isinstance(meta, dict):
        return None
    ctx = meta.get(TRACE_CTX_META)
    return ctx if isinstance(ctx, dict) and "id" in ctx else None


def stamp_hop(meta, hop: str, t: Optional[float] = None,
              **extra) -> Optional[dict]:
    """Append one hop record to the trace context in `meta` — a no-op
    (one dict lookup) when no context rides the buffer, so stamping
    sites can live on the hot path unguarded. Returns the hop record
    (or None). Timestamps are `time.perf_counter()` seconds; on Linux
    that is CLOCK_MONOTONIC, shared by every process on the host — the
    per-worker handshake offsets correct any residual skew."""
    ctx = get_trace_ctx(meta)
    if ctx is None:
        return None
    rec = {"hop": hop, "t": time.perf_counter() if t is None else t,
           "pid": os.getpid()}
    if extra:
        rec.update(extra)
    ctx["hops"].append(rec)
    return rec


#: canonical serving-path hop order (docs/observability.md schema);
#: hop_spans() derives the per-stage decomposition from it
HOP_STAGES = (
    ("admission_wait_ms", "admit", "dequeue"),
    ("route_ms", "dequeue", "dispatch"),
    ("worker_queue_ms", "dispatch", "worker_recv"),
    ("service_ms", "worker_recv", "worker_done"),
    ("reply_ms", "worker_done", "reply"),
)


def hop_spans(hops: List[dict]) -> Dict[str, Any]:
    """Per-stage latency decomposition (ms) from a hop list: admission
    wait / route / worker queue / service / reply, plus total. For a
    redelivered request the LAST occurrence of each hop wins (the
    attempt that produced the reply); earlier occurrences show up in
    `retries`/`redeliveries` counts instead of corrupting the stage
    math."""
    last: Dict[str, dict] = {}
    for h in hops:
        if isinstance(h, dict) and "hop" in h and "t" in h:
            last[h["hop"]] = h
    out: Dict[str, Any] = {}
    for key, a, b in HOP_STAGES:
        if a in last and b in last:
            dt = (last[b]["t"] - last[a]["t"]) * 1e3
            if dt >= 0:
                out[key] = round(dt, 3)
    ts = [h["t"] for h in hops
          if isinstance(h, dict) and "t" in h]
    if len(ts) >= 2:
        out["total_ms"] = round((max(ts) - min(ts)) * 1e3, 3)
    n_send = sum(1 for h in hops if isinstance(h, dict)
                 and h.get("hop") == "client_send")
    if n_send > 1:
        out["retries"] = n_send - 1
    n_re = sum(1 for h in hops if isinstance(h, dict)
               and h.get("hop") == "reoffer")
    if n_re:
        out["redeliveries"] = n_re
    # host-level hops (serving/mesh.py): the router's dispatch records
    # carry the host name — a cross-host redelivered request lists
    # every host its timeline touched, in first-dispatch order
    hosts: List[str] = []
    for h in hops:
        if isinstance(h, dict) and h.get("hop") == "dispatch" \
                and "host" in h and str(h["host"]) not in hosts:
            hosts.append(str(h["host"]))
    if hosts:
        out["hosts"] = hosts
    return out


#: the hop chain every REPLIED frame's trace must carry on the serving
#: path (client_send/client_recv are recorded locally by the loadgen,
#: not serialized, so they are not part of the reply's context). A
#: redelivered frame repeats hops; completeness only asks that each
#: stage appears at least once.
REQUIRED_REPLY_HOPS = ("admit", "dequeue", "dispatch", "worker_recv",
                       "worker_done", "reply")


def missing_hops(hops: List[dict],
                 required: tuple = REQUIRED_REPLY_HOPS) -> tuple:
    """The required hop names absent from a trace's hop list, in
    canonical order — empty tuple means the chain is complete."""
    seen = {h.get("hop") for h in hops if isinstance(h, dict)}
    return tuple(r for r in required if r not in seen)


def trace_chain_complete(hops: List[dict],
                         required: tuple = REQUIRED_REPLY_HOPS) -> bool:
    """True iff the trace carries the full serving hop chain — the
    trace-completeness invariant the scenario checker
    (scenario/checker.py) evaluates for every replied frame."""
    return not missing_hops(hops, required)


#: histogram bucket upper bounds (seconds) for per-element proctime —
#: log-spaced 10µs → 10s, the range a pipeline stage can plausibly
#: occupy; rendered as Prometheus `le` buckets by serving/metrics.py
HIST_BOUNDS_S = tuple(
    round(10.0 ** (e / 3.0), 9) for e in range(-15, 4))  # 1e-5 .. 10.0


class _Hist:
    """Fixed-bound cumulative histogram: monotone counts (never
    recomputed from a windowed reservoir — two consecutive metric
    scrapes must never see a bucket count decrease)."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        self.counts = [0] * (len(HIST_BOUNDS_S) + 1)   # +1 = +Inf
        self.sum = 0.0
        self.count = 0

    def record(self, v: float) -> None:
        self.counts[bisect.bisect_left(HIST_BOUNDS_S, v)] += 1
        self.sum += v
        self.count += 1

    def add_counts(self, counts: List[int], s: float, n: int) -> None:
        for i, c in enumerate(counts[:len(self.counts)]):
            self.counts[i] += c
        self.sum += s
        self.count += n

    def snapshot(self) -> dict:
        return {"bounds": list(HIST_BOUNDS_S),
                "counts": list(self.counts),
                "sum": self.sum, "count": self.count}


def percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over an already-sorted sample."""
    if not sorted_vals:
        return 0.0
    k = max(1, min(len(sorted_vals),
                   math.ceil(p / 100.0 * len(sorted_vals))))
    return sorted_vals[k - 1]


class NullTracer:
    """Do-nothing tracer: the default. Every hook exists so callers can
    skip the `.active` guard where the call is not on a hot path."""

    active = False

    def source_emit(self, name, buf, t):
        pass

    def enqueue(self, dst, depth, t):
        pass

    def dequeue(self, name, depth, t):
        pass

    def record_process(self, name, buf, t0, t1):
        pass

    def record_timer(self, name, t0, t1):
        pass

    def record_flush(self, name, t0, t1):
        pass

    def record_eos(self, name, t):
        pass

    def record_drop(self, name, t):
        pass

    def record_error(self, name, exc_type, t, **args):
        pass

    def record_watchdog(self, name, kind, t, **args):
        pass

    def watchdog_counts(self):
        return {}

    def record_flight(self, kind, t, **args):
        pass

    def flight_dumps(self):
        return []

    def record_device_counter(self, name, value, t):
        pass

    def worker_counts(self):
        return {}

    def backend_span(self, name, kind, t0, t1, **args):
        pass

    def device_span(self, device, kind, t0, t1, **args):
        pass

    def record_swap(self, name, t, **args):
        pass

    def record_llm_request(self, name, req_id, t, **args):
        pass

    def record_forced_sync(self, name, t):
        pass

    def record_inflight(self, name, depth, t):
        pass

    def record_compiled_window(self, name, k, t0, t1):
        pass

    def compiled_windows(self):
        return {}

    def record_loop_bail(self, name, cause, t):
        pass

    def loop_bails(self):
        return {}

    def record_shed(self, name, cause, t, **args):
        pass

    def record_worker_event(self, name, wid, kind, t, **args):
        pass

    def record_request(self, name, trace_id, hops, t, **args):
        pass

    def record_autotune(self, name, knob, t, **args):
        pass

    def tenant_summary(self):
        return {}

    def kernel_spans(self):
        return {}

    def instant(self, name, label, t=None, **args):
        pass


#: shared no-op singleton — scheduler, elements and backends all default
#: to this; PipelineRunner(trace=True) swaps in a recording Tracer
NULL_TRACER = NullTracer()

# event tuple layout: (ph, cat, name, label, ts, dur, args)
_Event = Tuple[str, str, str, str, float, float, Any]


class Tracer:
    """Recording tracer fed by the scheduler's hook points.

    All hooks are called from element worker threads; state is designed
    so no lock is needed: the event ring is an atomic-append deque, each
    element's interlatency reservoir is touched only by that element's
    own worker, and the gauge peak update is a benign read-modify-write
    (a lost race costs one sample, never a crash).
    """

    active = True

    def __init__(self, max_events: int = 65536,
                 max_latency_samples: int = 8192):
        self._t0 = time.perf_counter()
        self._events: Deque[_Event] = deque(maxlen=max_events)
        self._total_events = 0
        self._max_latency_samples = max_latency_samples
        # element name -> reservoir of (t_done - t_source_emit) seconds
        self._interlat: Dict[str, Deque[float]] = {}
        # dst element name -> {"peak": max depth ever sampled}
        self._gauges: Dict[str, Dict[str, int]] = {}
        # model hot-swap adoptions (serving/store.py): kept whole (not
        # just ring events) so report() can render every swap even after
        # the event ring wraps
        self._swaps: List[Tuple[str, float, dict]] = []
        # retired LLM requests (llm/engine.py): same keep-whole
        # rationale as swaps
        self._llm_requests: List[Tuple[str, str, float, dict]] = []
        # element name -> count of forced host syncs (runtime/sync.py)
        self._forced: Dict[str, int] = {}
        # (element, kernel) -> backend spans tagged with a kernel=
        # arg (llm_exec prefill/chunk/decode): kept whole like _forced
        # so per-kernel attribution survives ring wrap
        self._kernel_spans: Dict[Tuple[str, str], int] = {}
        # element name -> {"peak": max async in-flight depth sampled}
        self._inflight: Dict[str, Dict[str, int]] = {}
        # element name -> {"windows": n, "frames": n} compiled
        # steady-state windows (runtime/compiled_loop.py): kept whole
        # like _forced so the compiled-window share survives ring wrap
        self._compiled: Dict[str, Dict[str, int]] = {}
        # element name -> {cause: count} of armed windows that fell
        # back to per-frame mode (same keep-whole rationale)
        self._loop_bails: Dict[str, Dict[str, int]] = {}
        # server name -> {cause: count} of admission sheds/rejections
        # (edge/query.py): kept whole like swaps — per-cause shed
        # totals must survive ring wrap under sustained overload
        self._sheds: Dict[str, Dict[str, int]] = {}
        # worker-pool lifecycle events (serving/pool.py): kept whole —
        # a post-mortem needs the full spawn/kill/restart/degraded
        # sequence even after a chaos run wraps the ring
        self._worker_events: List[Tuple[str, int, str, float, dict]] = []
        # element name -> cumulative proctime histogram (seconds).
        # Cumulative by construction so the metrics plane can render
        # Prometheus buckets that never decrease between scrapes.
        self._hists: Dict[str, _Hist] = {}
        # completed request timelines (name, trace_id, t_done, hops,
        # args): kept whole (bounded) so end-to-end timelines survive
        # ring wrap; rendered as async b/n/e tracks in to_chrome_trace
        self._requests: List[Tuple[str, str, float, list, dict]] = []
        self._max_requests = 4096
        self._requests_dropped = 0
        # element name -> {kind: count} of watchdog warnings: kept
        # whole so the flight recorder's watchdog trigger sees totals
        # that survive ring wrap
        self._watchdogs: Dict[str, Dict[str, int]] = {}
        # flight-recorder dumps (runtime/flightrec.py): kept whole —
        # a forensic bundle is exactly the event a post-mortem is for
        self._flights: List[Tuple[str, float, dict]] = []
        # autotuner decisions (serving/autotune.py): bounded keep-whole
        # list with the same FIFO drop scheme as _requests, plus
        # per-knob/outcome counts that survive the drop — the decision
        # accounting stays exact even after the list wraps
        self._autotune: List[Tuple[str, str, float, dict]] = []
        self._max_autotune = 1024
        self._autotune_dropped = 0
        self._autotune_counts: Dict[str, Dict[str, int]] = {}
        # -- worker-side shipping state (enable_shipping/ship_delta) --
        self._shipping = False
        self._ship_samples: Dict[str, List[float]] = {}
        self._shipped_events = 0
        self._ship_prev: Dict[str, Any] = {}
        # -- parent-side child-merge state (ingest_child) --
        # wid -> ring of offset-adjusted child events (own drop budget,
        # so a wrapped parent ring never silently eats child telemetry)
        self._child_events: Dict[int, Deque[_Event]] = {}
        self._child_meta: Dict[int, dict] = {}
        self._child_max_events = max(1024, max_events // 4)

    # -- scheduler hooks ---------------------------------------------------
    def source_emit(self, name: str, buf, t: float) -> None:
        """Stamp the buffer's pipeline-entry time (interlatency origin)."""
        meta = getattr(buf, "meta", None)
        if isinstance(meta, dict):
            meta[SOURCE_TS_META] = t
        self._append("i", "source", name, "emit", t, 0.0, None)

    def enqueue(self, dst: str, depth: int, t: float) -> None:
        self._gauge(dst, depth, t)

    def dequeue(self, name: str, depth: int, t: float) -> None:
        self._gauge(name, depth, t)

    def record_process(self, name: str, buf, t0: float, t1: float) -> None:
        self._append("X", "element", name, "process", t0, t1 - t0, None)
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = _Hist()
        h.record(t1 - t0)
        src_ts = self._buf_source_ts(buf)
        if src_ts is not None:
            r = self._interlat.get(name)
            if r is None:
                r = self._interlat[name] = deque(
                    maxlen=self._max_latency_samples)
            r.append(t1 - src_ts)
            if self._shipping:
                s = self._ship_samples.get(name)
                if s is None:
                    s = self._ship_samples[name] = []
                if len(s) < self._max_latency_samples:
                    s.append(t1 - src_ts)

    def record_timer(self, name: str, t0: float, t1: float) -> None:
        self._append("X", "element", name, "timer", t0, t1 - t0, None)

    def record_flush(self, name: str, t0: float, t1: float) -> None:
        self._append("X", "element", name, "flush", t0, t1 - t0, None)

    def record_eos(self, name: str, t: float) -> None:
        self._append("i", "element", name, "eos", t, 0.0, None)

    def record_drop(self, name: str, t: float) -> None:
        self._append("i", "element", name, "buffer_dropped", t, 0.0, None)

    def record_error(self, name: str, exc_type: str, t: float,
                     **args) -> None:
        """A process() exception handled by the element's error policy
        (args carry policy/outcome: skipped, retried, degraded)."""
        args = dict(args, exc=exc_type)
        self._append("i", "error", name, "error", t, 0.0, args)

    def record_watchdog(self, name: str, kind: str, t: float,
                        **args) -> None:
        """A watchdog warning: kind is "stall" (process() over budget)
        or "queue" (input queue at capacity over budget). Counted per
        (element, kind) wrap-proof — the flight recorder's watchdog
        trigger watches these totals, so they must survive ring wrap."""
        c = self._watchdogs.get(name)
        if c is None:
            c = self._watchdogs[name] = {}
        c[kind] = c.get(kind, 0) + 1
        self._append("i", "watchdog", name, f"watchdog_{kind}", t, 0.0,
                     args or None)

    def watchdog_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-element watchdog-kind totals (wrap-proof)."""
        return {name: dict(c) for name, c in self._watchdogs.items()}

    def backend_span(self, name: str, kind: str, t0: float, t1: float,
                     **args) -> None:
        """Backend-side span (compile/invoke) attributed to the owning
        tensor_filter's track; args carry bucket/cache-hit details. A
        ``kernel=`` arg (the LLM executor's pallas/xla attribution) is
        additionally counted per (element, kernel) — wrap-proof, read
        back via `kernel_spans()`."""
        kern = (args or {}).get("kernel")
        if kern is not None:
            key = (name, str(kern))
            self._kernel_spans[key] = self._kernel_spans.get(key, 0) + 1
        self._append("X", "backend", name, kind, t0, t1 - t0, args or None)

    def kernel_spans(self) -> Dict[Tuple[str, str], int]:
        """(element, kernel) -> count of kernel-tagged backend spans."""
        return dict(self._kernel_spans)

    def device_span(self, device: int, kind: str, t0: float, t1: float,
                    **args) -> None:
        """Per-device span (replica invoke / segment stage): one track
        per chip (``dev0``..``devN``), so the trace viewer shows which
        device ran what and where the pipeline bubbles are. args carry
        the owning element / frame count."""
        self._append("X", "device", f"dev{int(device)}", kind, t0,
                     t1 - t0, args or None)

    def record_swap(self, name: str, t: float, **args) -> None:
        """A store-driven model hot swap adopted by `name`'s backend
        (serving/store.py); args carry model/from_version/to_version/
        epoch/prewarmed."""
        self._swaps.append((name, t, dict(args)))
        self._append("i", "swap", name, "model_swap", t, 0.0,
                     args or None)

    def swap_events(self) -> List[Tuple[str, float, dict]]:
        return list(self._swaps)

    def record_llm_request(self, name: str, req_id: str, t: float,
                           **args) -> None:
        """One retired LLM request (llm/engine.py); args carry the
        request summary: prompt_len/n_tokens/first_token_ms/itl_p50_ms/
        finish_reason. Kept whole like swaps so per-request serving
        latency survives ring wrap."""
        self._llm_requests.append((name, req_id, t, dict(args)))
        self._append("i", "llm", name, "llm_request", t, 0.0,
                     dict(args, req_id=req_id))

    def llm_requests(self) -> List[Tuple[str, str, float, dict]]:
        return list(self._llm_requests)

    def record_forced_sync(self, name: str, t: float) -> None:
        """A semantic host sync (runtime/sync.py device_sync with
        forced=True): a sink draining results, a filter in
        latency_mode=sync, or backend warm-up. These are the host-path
        tax async dispatch exists to remove — count per element."""
        self._forced[name] = self._forced.get(name, 0) + 1
        self._append("i", "sync", name, "forced_sync", t, 0.0, None)

    def forced_syncs(self) -> Dict[str, int]:
        return dict(self._forced)

    def record_inflight(self, name: str, depth: int, t: float) -> None:
        """Async-dispatch window gauge: number of unresolved device
        results a DEVICE_RESIDENT element holds in flight (sampled after
        the window drain, so the recorded peak never exceeds
        [runtime] max_inflight)."""
        g = self._inflight.get(name)
        if g is None:
            g = self._inflight[name] = {"peak": 0}
        if depth > g["peak"]:
            g["peak"] = depth
        self._append("C", "inflight", name, "inflight_dispatch", t, 0.0,
                     depth)

    def inflight_gauges(self) -> Dict[str, dict]:
        return {name: dict(g) for name, g in self._inflight.items()}

    def record_compiled_window(self, name: str, k: int, t0: float,
                               t1: float) -> None:
        """One compiled steady-state window (scheduler bypass,
        runtime/compiled_loop.py): `k` frames ran as a single jitted
        lax.scan dispatch. Counted wrap-proof per element so report()'s
        compiled-window share survives ring wrap."""
        c = self._compiled.get(name)
        if c is None:
            c = self._compiled[name] = {"windows": 0, "frames": 0}
        c["windows"] += 1
        c["frames"] += int(k)
        self._append("X", "element", name, "compiled_window", t0,
                     t1 - t0, {"frames": int(k)})

    def compiled_windows(self) -> Dict[str, Dict[str, int]]:
        """Per-element {"windows": n, "frames": n} totals (wrap-proof)."""
        return {name: dict(c) for name, c in self._compiled.items()}

    def record_loop_bail(self, name: str, cause: str, t: float) -> None:
        """An armed compiled window fell back to per-frame mode; cause
        is one of compiled_loop.BAIL_CAUSES."""
        c = self._loop_bails.get(name)
        if c is None:
            c = self._loop_bails[name] = {}
        c[cause] = c.get(cause, 0) + 1
        self._append("i", "element", name, f"loop_bail_{cause}", t,
                     0.0, None)

    def loop_bails(self) -> Dict[str, Dict[str, int]]:
        """Per-element {cause: count} bail totals (wrap-proof)."""
        return {name: dict(c) for name, c in self._loop_bails.items()}

    def record_shed(self, name: str, cause: str, t: float,
                    **args) -> None:
        """One request refused or shed at a query server's admission
        queue (edge/query.py). `cause` is the admission taxonomy:
        queue_full / inflight_full / deadline / reject_oldest /
        dispatch_error / shutdown. Dict writes under the GIL — a lost
        race between two reader threads costs one count at worst."""
        c = self._sheds.get(name)
        if c is None:
            c = self._sheds[name] = {}
        c[cause] = c.get(cause, 0) + 1
        self._append("i", "admission", name, f"shed_{cause}", t, 0.0,
                     args or None)

    def shed_counts(self) -> Dict[str, Dict[str, int]]:
        return {name: dict(c) for name, c in self._sheds.items()}

    def record_worker_event(self, name: str, wid: int, kind: str,
                            t: float, **args) -> None:
        """One worker-pool lifecycle event (serving/pool.py). `kind` is
        the supervision taxonomy: spawn / ready / kill / exit / restart
        / reoffer / degraded / swap_commit / swap_abort / drain_stop.
        wid is the pool slot (-1 for pool-level events like swaps)."""
        self._worker_events.append((name, wid, kind, t, dict(args)))
        self._append("i", "worker", f"{name}/w{wid}", f"worker_{kind}",
                     t, 0.0, args or None)

    def worker_events(self) -> List[Tuple[str, int, str, float, dict]]:
        return list(self._worker_events)

    def record_autotune(self, name: str, knob: str, t: float,
                        **args) -> None:
        """One autotuner decision (serving/autotune.py); args carry
        old/new/outcome plus the sensor evidence that justified it.
        Single writer (the controller thread); dict writes under the
        GIL, same discipline as record_shed."""
        self._autotune.append((name, knob, t, dict(args)))
        if len(self._autotune) > self._max_autotune:
            drop = max(1, self._max_autotune // 4)
            del self._autotune[:drop]
            self._autotune_dropped += drop
        c = self._autotune_counts.get(knob)
        if c is None:
            c = self._autotune_counts[knob] = {}
        outcome = str(args.get("outcome", "unknown"))
        c[outcome] = c.get(outcome, 0) + 1
        self._append("i", "autotune", name, f"tune_{knob}", t, 0.0,
                     args or None)

    def record_flight(self, kind: str, t: float, **args) -> None:
        """One flight-recorder dump (runtime/flightrec.py); args carry
        the bundle path and trigger cause. Kept whole — dumps are rare
        and each one is a post-mortem anchor."""
        self._flights.append((kind, t, dict(args)))
        self._append("i", "flight", "flightrec", f"flight_{kind}", t,
                     0.0, args or None)

    def flight_dumps(self) -> List[Tuple[str, float, dict]]:
        return list(self._flights)

    def record_device_counter(self, name: str, value: float,
                              t: float) -> None:
        """Device-plane counter sample (runtime/devprof.py): MFU per
        bucket and HBM per device, rendered as Chrome-trace counter
        tracks alongside queue depth and in-flight windows."""
        self._append("C", "devprof", name, "devprof", t, 0.0, value)

    def autotune_events(self) -> List[Tuple[str, str, float, dict]]:
        return list(self._autotune)

    def autotune_counts(self) -> Dict[str, Dict[str, int]]:
        return {k: dict(v) for k, v in self._autotune_counts.items()}

    def worker_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-pool event-kind totals (the summary() view; the full
        ordered sequence is worker_events())."""
        out: Dict[str, Dict[str, int]] = {}
        for name, _wid, kind, _t, _args in self._worker_events:
            c = out.setdefault(name, {})
            c[kind] = c.get(kind, 0) + 1
        return out

    def record_request(self, name: str, trace_id: str, hops: List[dict],
                       t: float, **args) -> None:
        """One completed request timeline: `hops` is the trace-context
        hop list that came back with the reply (edge/query.py or
        serving/pool.py). Kept whole (bounded FIFO) so timelines
        survive ring wrap; to_chrome_trace renders each as an async
        b/n/e track keyed by trace_id."""
        if len(self._requests) >= self._max_requests:
            del self._requests[:self._max_requests // 4]
            self._requests_dropped += self._max_requests // 4
        self._requests.append(
            (name, trace_id, t, [dict(h) for h in hops
                                 if isinstance(h, dict)], dict(args)))
        self._append("i", "request", name, "request_done", t, 0.0,
                     dict(args, trace_id=trace_id, hops=len(hops)))

    def requests(self) -> List[Tuple[str, str, float, list, dict]]:
        return list(self._requests)

    def tenant_summary(self) -> Dict[str, dict]:
        """Per-tenant rollup over the bounded request window: completed
        count, completion rate across the window span, and server-side
        latency percentiles (first recorded hop → completion). Only
        requests recorded with a ``tenant=`` arg contribute (the query
        server adds it when admission stamped a tenant class) — this is
        what the ScalingController reads for per-tenant demand and what
        metrics_snapshot exports as nns_tenant_latency gauges."""
        acc: Dict[str, dict] = {}
        for _name, _tid, t, hops, args in list(self._requests):
            tenant = args.get("tenant")
            if tenant is None:
                continue
            row = acc.setdefault(
                tenant, {"count": 0, "lat": [], "t0": t, "t1": t})
            row["count"] += 1
            row["t0"] = min(row["t0"], t)
            row["t1"] = max(row["t1"], t)
            ts = [h["t"] for h in hops
                  if isinstance(h.get("t"), (int, float))]
            if ts:
                row["lat"].append(max(0.0, t - min(ts)))
        out: Dict[str, dict] = {}
        for tenant, row in acc.items():
            lat = sorted(row["lat"])
            span = row["t1"] - row["t0"]
            out[tenant] = {
                "count": row["count"],
                "rate_hz": (row["count"] - 1) / span
                if row["count"] > 1 and span > 0 else float(row["count"]),
                "p50_ms": 1e3 * percentile(lat, 50.0),
                "p99_ms": 1e3 * percentile(lat, 99.0),
            }
        return out

    def instant(self, name: str, label: str, t: Optional[float] = None,
                **args) -> None:
        if t is None:
            t = time.perf_counter()
        self._append("i", "element", name, label, t, 0.0, args or None)

    # -- worker-side shipping ----------------------------------------------
    def enable_shipping(self) -> None:
        """Mark this tracer as a worker-side child that will be drained
        by periodic `ship_delta()` calls (serving/worker.py heartbeat
        thread). Turns on the interlatency sample side-buffer; without
        shipping enabled that buffer is never touched."""
        self._shipping = True

    def ship_delta(self) -> Optional[dict]:
        """Drain everything recorded since the last ship into one
        picklable payload for the supervisor pipe, or None when nothing
        happened. Counters and histograms ship as DELTAS, not
        cumulative values: the parent adds them, which keeps pool-level
        totals monotone across worker restarts (a replacement worker
        simply resumes contributing deltas from zero)."""
        prev = self._ship_prev
        payload: Dict[str, Any] = {}

        events = []
        try:
            while True:
                events.append(self._events.popleft())
        except IndexError:
            pass
        if events:
            self._shipped_events += len(events)
            payload["events"] = events
        total_prev = prev.get("total_events", 0)
        if self._total_events != total_prev:
            payload["events_total_delta"] = self._total_events - total_prev
            prev["total_events"] = self._total_events
        dropped = max(0, self._total_events - self._shipped_events
                      - len(self._events))
        drop_prev = prev.get("events_dropped", 0)
        if dropped != drop_prev:
            payload["events_dropped_delta"] = dropped - drop_prev
            prev["events_dropped"] = dropped

        hist_prev = prev.setdefault("hists", {})
        hist_out = {}
        for name, h in self._hists.items():
            p = hist_prev.get(name)
            if p is None:
                p = hist_prev[name] = {
                    "counts": [0] * len(h.counts), "sum": 0.0, "count": 0}
            if h.count != p["count"]:
                hist_out[name] = {
                    "counts": [c - pc for c, pc
                               in zip(h.counts, p["counts"])],
                    "sum": h.sum - p["sum"],
                    "count": h.count - p["count"],
                }
                p["counts"] = list(h.counts)
                p["sum"], p["count"] = h.sum, h.count
        if hist_out:
            payload["hists"] = hist_out

        forced_prev = prev.setdefault("forced", {})
        forced_out = {}
        for name, n in self._forced.items():
            d = n - forced_prev.get(name, 0)
            if d:
                forced_out[name] = d
                forced_prev[name] = n
        if forced_out:
            payload["forced"] = forced_out

        shed_prev = prev.setdefault("sheds", {})
        shed_out: Dict[str, Dict[str, int]] = {}
        for name, causes in self._sheds.items():
            p = shed_prev.setdefault(name, {})
            for cause, n in causes.items():
                d = n - p.get(cause, 0)
                if d:
                    shed_out.setdefault(name, {})[cause] = d
                    p[cause] = n
        if shed_out:
            payload["sheds"] = shed_out

        if self._ship_samples:
            payload["interlat"] = self._ship_samples
            self._ship_samples = {}

        for key, src in (("swaps", self._swaps),
                         ("worker_events", self._worker_events),
                         ("requests", self._requests)):
            i = prev.get(f"n_{key}", 0)
            if len(src) > i:
                payload[key] = src[i:]
                prev[f"n_{key}"] = len(src)

        gauges = {name: g["peak"] for name, g in self._gauges.items()}
        if gauges != prev.get("gauges"):
            payload["gauges"] = gauges
            prev["gauges"] = dict(gauges)
        inflight = {name: g["peak"] for name, g in self._inflight.items()}
        if inflight != prev.get("inflight"):
            payload["inflight"] = inflight
            prev["inflight"] = dict(inflight)

        return payload or None

    # -- parent-side child merge -------------------------------------------
    def ingest_child(self, wid: int, pid: int, payload: dict,
                     offset_s: float = 0.0,
                     label: Optional[str] = None) -> None:
        """Merge one `ship_delta()` payload from worker slot `wid`.
        Child element names are namespaced `w{wid}/` so per-element
        stats never collide across workers; child events land in a
        per-worker ring (own drop budget) with `offset_s` applied, so a
        wrapped parent ring never silently eats child telemetry and
        `to_chrome_trace()` can render one process track group per
        worker."""
        meta = self._child_meta.get(wid)
        if meta is None:
            meta = self._child_meta[wid] = {
                "pid": pid, "label": label or f"worker{wid}",
                "offset_s": offset_s, "events_total": 0,
                "events_dropped_child": 0, "batches": 0}
        else:
            # a restarted slot reuses the ring but tracks the new pid
            meta["pid"] = pid
            meta["offset_s"] = offset_s
            if label:
                meta["label"] = label
        meta["batches"] += 1
        pfx = f"w{wid}/"

        events = payload.get("events")
        if events:
            ring = self._child_events.get(wid)
            if ring is None:
                ring = self._child_events[wid] = deque(
                    maxlen=self._child_max_events)
            for ev in events:
                ph, cat, name, lbl, ts, dur, args = ev
                ring.append((ph, cat, name, lbl, ts + offset_s, dur,
                             args))
            meta["events_total"] += len(events)
        meta["events_dropped_child"] += payload.get(
            "events_dropped_delta", 0)

        for name, h in payload.get("hists", {}).items():
            dst = self._hists.get(pfx + name)
            if dst is None:
                dst = self._hists[pfx + name] = _Hist()
            dst.add_counts(h["counts"], h["sum"], h["count"])

        for name, d in payload.get("forced", {}).items():
            key = pfx + name
            self._forced[key] = self._forced.get(key, 0) + d

        for name, causes in payload.get("sheds", {}).items():
            c = self._sheds.setdefault(pfx + name, {})
            for cause, d in causes.items():
                c[cause] = c.get(cause, 0) + d

        for name, samples in payload.get("interlat", {}).items():
            r = self._interlat.get(pfx + name)
            if r is None:
                r = self._interlat[pfx + name] = deque(
                    maxlen=self._max_latency_samples)
            r.extend(samples)

        for name, t, args in payload.get("swaps", ()):
            self._swaps.append((pfx + name, t + offset_s, dict(args)))
        for name, w, kind, t, args in payload.get("worker_events", ()):
            self._worker_events.append(
                (pfx + name, w, kind, t + offset_s, dict(args)))
        for name, tid_, t, hops, args in payload.get("requests", ()):
            self.record_request(pfx + name, tid_, hops, t + offset_s,
                                **args)

        for name, peak in payload.get("gauges", {}).items():
            g = self._gauges.setdefault(pfx + name, {"peak": 0})
            if peak > g["peak"]:
                g["peak"] = peak
        for name, peak in payload.get("inflight", {}).items():
            g = self._inflight.setdefault(pfx + name, {"peak": 0})
            if peak > g["peak"]:
                g["peak"] = peak

    def children(self) -> Dict[int, dict]:
        """Per-worker merge bookkeeping: pid, label, clock offset,
        events ingested, and the two drop budgets (child-reported +
        parent-ring)."""
        out = {}
        for wid, meta in self._child_meta.items():
            m = dict(meta)
            ring = self._child_events.get(wid)
            kept = len(ring) if ring is not None else 0
            m["events_kept"] = kept
            m["events_dropped"] = (m["events_dropped_child"]
                                   + max(0, m["events_total"] - kept))
            out[wid] = m
        return out

    # -- internals ---------------------------------------------------------
    def _append(self, ph: str, cat: str, name: str, label: str,
                ts: float, dur: float, args) -> None:
        self._total_events += 1
        self._events.append((ph, cat, name, label, ts, dur, args))

    def _gauge(self, dst: str, depth: int, t: float) -> None:
        g = self._gauges.get(dst)
        if g is None:
            g = self._gauges[dst] = {"peak": 0}
        if depth > g["peak"]:
            g["peak"] = depth
        self._append("C", "queue", dst, "queue_depth", t, 0.0, depth)

    @staticmethod
    def _buf_source_ts(buf) -> Optional[float]:
        """Earliest source timestamp reachable from `buf` — the direct
        stamp, or for a micro-batch the oldest frame's stamp (the
        deadline-bound frame is the one whose latency matters)."""
        meta = getattr(buf, "meta", None)
        if not isinstance(meta, dict):
            return None
        ts = meta.get(SOURCE_TS_META)
        if ts is not None:
            return ts
        db = meta.get("dyn_batch")
        if isinstance(db, dict):
            stamps = [f["meta"][SOURCE_TS_META]
                      for f in db.get("frames", ())
                      if isinstance(f.get("meta"), dict)
                      and SOURCE_TS_META in f["meta"]]
            if stamps:
                return min(stamps)
        return None

    # -- read-out ----------------------------------------------------------
    def events(self) -> List[_Event]:
        return list(self._events)

    @property
    def total_events(self) -> int:
        """Monotone count of every event ever recorded in the tree
        (never decreases when the ring wraps — the metrics-plane
        counter; ring length is `len(events())`)."""
        n = self._total_events
        for m in self._child_meta.values():
            n += m["events_total"]
        return n

    @property
    def events_dropped(self) -> int:
        """Events lost anywhere in the tree: this ring's wrap losses
        (events shipped to a parent are NOT drops) plus, on a pool
        parent, every child's wrap losses — child-reported and
        parent-ring alike. The cross-process ring-wrap tests pin this
        staying exact."""
        own = max(0, self._total_events - self._shipped_events
                  - len(self._events))
        for m in self.children().values():
            own += m["events_dropped"]
        return own

    def hists(self) -> Dict[str, dict]:
        """Per-element cumulative proctime histograms (snapshot dicts);
        on a pool parent, includes `w{wid}/`-prefixed merged child
        histograms."""
        return {name: h.snapshot() for name, h in self._hists.items()}

    def interlatency(self) -> Dict[str, dict]:
        """Per-element end-to-end latency percentiles (ms) from source
        emit to completion of that element's process()."""
        out = {}
        for name, r in self._interlat.items():
            vals = sorted(r)
            if not vals:
                continue
            out[name] = {
                "n": len(vals),
                "p50_ms": 1e3 * percentile(vals, 50),
                "p95_ms": 1e3 * percentile(vals, 95),
                "p99_ms": 1e3 * percentile(vals, 99),
                "max_ms": 1e3 * vals[-1],
            }
        return out

    def queue_gauges(self) -> Dict[str, dict]:
        return {name: dict(g) for name, g in self._gauges.items()}

    def summary(self) -> dict:
        return {
            "interlatency": self.interlatency(),
            "queues": self.queue_gauges(),
            "events": len(self._events),
            "events_dropped": self.events_dropped,
            "swaps": len(self._swaps),
            "llm_requests": len(self._llm_requests),
            "forced_syncs": dict(self._forced),
            "inflight": self.inflight_gauges(),
            "sheds": self.shed_counts(),
            "workers": self.worker_counts(),
            "autotune": self.autotune_counts(),
            "requests": len(self._requests) + self._requests_dropped,
            "children": {str(wid): m
                         for wid, m in self.children().items()},
        }

    def to_chrome_trace(self, pipeline_name: str = "pipeline") -> dict:
        """Trace Event Format dict — `json.dump` it and load the file in
        Perfetto or chrome://tracing.

        Track layout: pid 0 is this process (one tid per element, in
        order of first appearance); each ingested worker gets its own
        pid (= wid + 1) and so renders as its own Perfetto *process*
        track group, named from the handshake label. Completed request
        timelines render as async b/n/e events keyed by trace_id on a
        dedicated "requests" track, one "n" instant per hop — the
        end-to-end admission→worker→reply view. ts/dur in µs relative
        to tracer creation."""
        trace: List[dict] = []
        tids_by_pid: Dict[int, Dict[str, int]] = {}

        def add_process(pid: int, pname: str) -> None:
            trace.append({"ph": "M", "name": "process_name",
                          "pid": pid, "tid": 0,
                          "args": {"name": pname}})

        def tid_of(pid: int, name: str) -> int:
            tids = tids_by_pid.setdefault(pid, {})
            t = tids.get(name)
            if t is None:
                t = tids[name] = len(tids) + 1
                trace.append({"ph": "M", "name": "thread_name",
                              "pid": pid, "tid": t,
                              "args": {"name": name}})
            return t

        def emit(pid: int, events) -> None:
            for ph, cat, name, label, ts, dur, args in events:
                us = round((ts - self._t0) * 1e6, 3)
                if ph == "X":
                    ev = {"ph": "X", "cat": cat, "name": label,
                          "pid": pid, "tid": tid_of(pid, name),
                          "ts": us, "dur": round(dur * 1e6, 3)}
                    if args:
                        ev["args"] = dict(args)
                elif ph == "C":
                    if cat == "devprof":
                        # device-plane counter tracks: name already
                        # carries the mfu:/hbm: prefix, value is the
                        # sampled counter value (not a queue depth)
                        ev = {"ph": "C", "cat": cat, "name": name,
                              "pid": pid, "tid": 0, "ts": us,
                              "args": {"value": args}}
                    else:
                        track = ("inflight" if cat == "inflight"
                                 else "queue")
                        ev = {"ph": "C", "cat": cat,
                              "name": f"{track}:{name}",
                              "pid": pid, "tid": 0, "ts": us,
                              "args": {"depth": args}}
                else:  # "i" instant, scoped to the element's track
                    ev = {"ph": "i", "cat": cat, "name": label,
                          "pid": pid, "tid": tid_of(pid, name),
                          "ts": us, "s": "t"}
                    if args:
                        ev["args"] = dict(args)
                trace.append(ev)

        add_process(0, pipeline_name)
        emit(0, list(self._events))
        for wid in sorted(self._child_events):
            meta = self._child_meta.get(wid, {})
            add_process(wid + 1,
                        f"{meta.get('label', f'worker{wid}')} "
                        f"(pid {meta.get('pid', '?')})")
            emit(wid + 1, list(self._child_events[wid]))

        # async request timelines: one b/n.../e chain per trace_id on
        # the parent's "requests" track; hop name + stamping pid in args
        req_tid = None
        for name, trace_id, _t, hops, rargs in self._requests:
            ts_hops = [h for h in hops if "t" in h]
            if len(ts_hops) < 2:
                continue
            if req_tid is None:
                req_tid = tid_of(0, "requests")
            ts0 = min(h["t"] for h in ts_hops)
            ts1 = max(h["t"] for h in ts_hops)
            base = {"cat": "request", "id": trace_id, "pid": 0,
                    "tid": req_tid, "name": f"req:{trace_id}"}
            trace.append(dict(
                base, ph="b", ts=round((ts0 - self._t0) * 1e6, 3),
                args=dict(rargs, server=name)))
            for h in sorted(ts_hops, key=lambda h: h["t"]):
                extra = {k: v for k, v in h.items()
                         if k not in ("hop", "t")}
                trace.append(dict(
                    base, ph="n",
                    ts=round((h["t"] - self._t0) * 1e6, 3),
                    args=dict(extra, hop=h.get("hop", "?"))))
            trace.append(dict(
                base, ph="e", ts=round((ts1 - self._t0) * 1e6, 3)))
        return {"traceEvents": trace, "displayTimeUnit": "ms"}


def merge_chrome_traces(docs: List[dict],
                        labels: Optional[List[str]] = None) -> dict:
    """Merge several Trace Event Format documents (each from
    `to_chrome_trace`) into one, remapping pids so every input keeps
    its own process track groups — the `trace --merge` CLI. `labels`
    (optional, parallel to `docs`) prefix each input's process names so
    the Perfetto sidebar says which file a track came from."""
    merged: List[dict] = []
    base = 0
    for i, doc in enumerate(docs):
        events = doc.get("traceEvents", []) if isinstance(doc, dict) \
            else list(doc)
        label = labels[i] if labels and i < len(labels) else None
        top = 0
        for ev in events:
            pid = ev.get("pid", 0)
            top = max(top, pid if isinstance(pid, int) else 0)
            ev = dict(ev, pid=(pid if isinstance(pid, int) else 0)
                      + base)
            if (label and ev.get("ph") == "M"
                    and ev.get("name") == "process_name"):
                args = dict(ev.get("args") or {})
                args["name"] = f"{label}/{args.get('name', '?')}"
                ev["args"] = args
            merged.append(ev)
        base += top + 1
    return {"traceEvents": merged, "displayTimeUnit": "ms"}
