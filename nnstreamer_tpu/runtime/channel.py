"""Condition-variable MPSC channel — the hot link between elements.

Replaces the per-element ``queue.Queue`` + timeout-poll loops the
scheduler used to run. ``queue.Queue`` forced two compromises on the
host path:

- **polling wakeups**: consumers slept in ``get(timeout=0.1)`` and
  producers retried ``put(timeout=0.1)`` so teardown could be noticed —
  a 100 ms latency floor on an idle hop and constant spurious wakeups
  on a busy one;
- **lost teardown wakeups**: ``stop()`` nudged sleepers with
  ``put_nowait((None, EOS, 0.0))``, which silently drops on a full
  queue, leaving the worker to ride out its poll timeout.

``Channel`` fixes both with one lock and two condition variables:
``put`` wakes the consumer the instant a buffer lands, ``get`` wakes a
blocked producer the instant a slot frees, and ``close()`` does
``notify_all`` on both conditions — a teardown wakeup that *cannot* be
lost, full queue or not. Waits are untimed (or bounded by the caller's
deadline for timer elements), so an idle pipeline burns zero CPU and an
enqueue→dequeue handoff costs one lock round-trip instead of up to
100 ms.

Depth accounting rides along for free: ``put``/``get`` return the
queue depth observed *under the already-held lock*, so the scheduler's
always-on ``queue_peak`` high-water mark and the tracer's queuelevel
gauges no longer pay an extra ``qsize()`` lock acquisition per buffer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Optional, Tuple


class _Sentinel:
    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __repr__(self):
        return self._name


#: ``get()`` result when the channel was closed (teardown) and empty.
CLOSED = _Sentinel("CLOSED")
#: ``get(deadline=...)`` result when the deadline passed with no item.
TIMED_OUT = _Sentinel("TIMED_OUT")


class Channel:
    """Bounded multi-producer / single-consumer channel.

    - ``put(item)`` blocks while full, returns the post-append depth —
      or ``None`` when the channel closed while (or before) waiting,
      meaning the item was **not** delivered.
    - ``get(deadline=None)`` blocks until an item is available and
      returns ``(item, depth_after_pop)``; returns ``(CLOSED, 0)`` once
      the channel is closed *and* drained, or ``(TIMED_OUT, 0)`` when
      the ``time.perf_counter()``-based deadline expires first.
    - ``close()`` wakes every waiter on both sides, exactly once each.

    Items already buffered when ``close()`` lands are still handed out
    (consumers check the runner's stop event themselves); only *new*
    puts are refused.
    """

    __slots__ = ("_buf", "_cap", "_closed", "_lock", "_not_empty",
                 "_not_full", "peak")

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"channel capacity must be >= 1, got "
                             f"{capacity}")
        self._buf: deque = deque()
        self._cap = capacity
        self._closed = False
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        #: high-water mark, maintained under the put-side lock hold
        self.peak = 0

    # -- producer side -----------------------------------------------------
    def put(self, item: Any) -> Optional[int]:
        with self._not_full:
            while len(self._buf) >= self._cap and not self._closed:
                self._not_full.wait()
            if self._closed:
                return None
            self._buf.append(item)
            depth = len(self._buf)
            if depth > self.peak:
                self.peak = depth
            self._not_empty.notify()
            return depth

    def try_put(self, item: Any) -> Optional[int]:
        """Non-blocking put: depth on success, ``None`` when full or
        closed (leaky-mode / best-effort producers)."""
        with self._not_full:
            if self._closed or len(self._buf) >= self._cap:
                return None
            self._buf.append(item)
            depth = len(self._buf)
            if depth > self.peak:
                self.peak = depth
            self._not_empty.notify()
            return depth

    # -- consumer side -----------------------------------------------------
    def get(self, deadline: Optional[float] = None) -> Tuple[Any, int]:
        with self._not_empty:
            while not self._buf:
                if self._closed:
                    return CLOSED, 0
                if deadline is None:
                    self._not_empty.wait()
                else:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0.0:
                        return TIMED_OUT, 0
                    self._not_empty.wait(remaining)
            item = self._buf.popleft()
            self._not_full.notify()
            return item, len(self._buf)

    def get_nowait(self) -> Tuple[Any, int]:
        """Non-blocking get for compiled-window collection: ``(item,
        depth_after_pop)`` when a buffered item exists, ``(CLOSED, 0)``
        when closed *and* drained, ``(TIMED_OUT, 0)`` when merely empty.
        Never sleeps — the scheduler's steady-state loop uses this to
        sweep already-queued frames into one jitted window without ever
        stalling the window boundary on a slow producer."""
        with self._not_empty:
            if not self._buf:
                return (CLOSED, 0) if self._closed else (TIMED_OUT, 0)
            item = self._buf.popleft()
            self._not_full.notify()
            return item, len(self._buf)

    # -- lifecycle / introspection ----------------------------------------
    def close(self) -> None:
        """Refuse further puts and wake every waiter (guaranteed
        teardown wakeup — nothing to lose to a full buffer)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def capacity(self) -> int:
        return self._cap

    def qsize(self) -> int:
        return len(self._buf)      # len() is GIL-atomic; no lock needed

    def full(self) -> bool:
        return len(self._buf) >= self._cap
