"""Device performance plane: live MFU, cost registry, HBM ledger.

The hardware-level numbers that decide every "as fast as the hardware
allows" question — FLOPs per compiled program, achieved TFLOP/s, MFU
against the chip's peak, HBM residency and headroom — used to exist
only inside bench.py's batch sweep.  This module makes them a *serving*
plane: backends report every compile (XLA cost-model FLOPs + bytes
accessed + compile wall-time, keyed by (filter, bucket)), the
`device_sync` choke point samples invoke durations into per-bucket
reservoirs, and `stats()` folds both into achieved TFLOP/s, MFU,
roofline classification and a per-device HBM ledger that
`serving.metrics.metrics_snapshot(devprof=...)` exports as
``nns_jit_*`` / ``nns_invoke_*`` / ``nns_device_hbm_*`` families.

This is the ONLY blessed home (nnlint NNL010) for XLA cost-model reads
(``lower().cost_analysis()``), device memory ledgers
(``memory_stats()``) and peak-FLOPs/bandwidth tables inside the
package — one accounting site means one place where "peak" and
"achieved" can silently diverge, and the audit rule keeps it that way
(bench.py, outside the package, keeps its own sweep-local copy).

Accounting model
----------------
- **Compile time**: backends call :meth:`DeviceProfiler.capture_cost`
  right after a cache-miss invoke, passing the jitted callable and the
  concrete args.  The profiler re-lowers (no second XLA compile —
  ``Lowered.cost_analysis()`` is an HLO-level estimate) and records
  flops / bytes accessed / compile wall seconds into the cost
  registry.  Compile events are rare by design (bucketed caches), so
  the extra trace+lower never rides the steady-state hot path.
- **Invoke time**: backends mark the dispatch
  (:meth:`DeviceProfiler.note_dispatch`, a thread-local stamp), and the
  next ``device_sync`` on the same thread closes the sample —
  dispatch→sync-complete wall time is the device-time observation,
  taken exactly where the runtime already forces device completion so
  the tracer's forced-sync accounting stays untouched.  Sampling is
  opportunistic (async-mode sinks on another thread simply do not
  sample); *cumulative* invoke seconds per bucket stay exact for the
  samples taken, which is what the proctime reconciliation check uses.
- **MFU**: achieved TFLOP/s = registry flops / median sampled invoke
  seconds.  Against a declared TPU peak that is MFU; on CPU emulation
  (tier-1) there is no meaningful peak, so ``mfu`` reports 0 and
  ``mfu_calibrated`` falls back to the best achieved TFLOP/s observed
  so far as a measured calibration peak — ratios stay comparable
  across buckets even where the absolute denominator is unknowable.
- **Roofline**: arithmetic intensity (flops / bytes accessed) against
  the ridge point (peak flops / peak bandwidth) classifies each bucket
  compute- vs memory-bound; without both peaks the verdict is
  "unknown", never a guess.

Kept dependency-light (stdlib + lazy jax) so `runtime.sync` can import
it without pulling the package graph in.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from collections import deque

#: declared bf16 dense peak TFLOP/s per TPU generation (per chip) —
#: public spec-sheet numbers; the MFU denominator on real hardware
PEAK_TFLOPS = {
    "TPU v2": 45.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v4i": 138.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}

#: declared HBM bandwidth GB/s per TPU generation (per chip) — the
#: roofline's memory peak; ridge point = peak flops / peak bandwidth
PEAK_HBM_GBPS = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v4i": 614.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


def peak_for(device_kind: str) -> Tuple[float, float]:
    """(peak TFLOP/s, peak HBM GB/s) for a jax ``device_kind`` string;
    (0, 0) when the platform has no declared peak (CPU emulation,
    unknown chip) — callers treat 0 as "denominator unknown"."""
    k = str(device_kind or "").strip()
    if k in PEAK_TFLOPS:
        return PEAK_TFLOPS[k], PEAK_HBM_GBPS.get(k, 0.0)
    # longest-prefix match tolerates suffixed kinds ("TPU v4 pod slice")
    best = ""
    for known in PEAK_TFLOPS:
        if k.lower().startswith(known.lower()) and len(known) > len(best):
            best = known
    if best:
        return PEAK_TFLOPS[best], PEAK_HBM_GBPS.get(best, 0.0)
    return 0.0, 0.0


class DeviceProfiler:
    """Process-wide cost registry + invoke reservoirs + HBM ledger.

    Off by default: every hot-path hook starts with an ``enabled``
    check, so the plane costs one attribute read until something
    (serve --metrics-port, bench's devprof arm, a test) turns it on.
    Thread model: registry and reservoirs are dict/deque appends under
    one lock taken only on compile events and sync samples (both
    orders of magnitude rarer than frames); the dispatch stamp is
    thread-local and lock-free.
    """

    def __init__(self, reservoir: int = 128,
                 peak_tflops: Optional[float] = None,
                 peak_hbm_gbps: Optional[float] = None):
        self._lock = threading.Lock()
        self.enabled = False
        self._reservoir = int(reservoir)
        # (filter, bucket) -> {"flops", "bytes_accessed", "compile_s",
        #                      "compiles"} — cumulative, never reset
        self._cost: Dict[Tuple[str, str], Dict[str, float]] = {}
        # (filter, bucket) -> {"ring": deque, "seconds": float,
        #                      "count": int} — ring is the reservoir,
        # seconds/count are exact cumulative totals for reconciliation
        self._invoke: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._tl = threading.local()
        # label -> weakref to a backend exposing resident_bytes()
        self._models: Dict[str, Any] = {}
        self._calib_tflops = 0.0      # best achieved — the CPU "peak"
        self._peak_override = (peak_tflops, peak_hbm_gbps)
        self._device_info: Optional[Dict[str, Any]] = None

    # -- lifecycle ---------------------------------------------------------
    def enable(self, on: bool = True) -> "DeviceProfiler":
        self.enabled = bool(on)
        return self

    def reset(self) -> None:
        """Drop all accounting (tests and bench arms)."""
        with self._lock:
            self._cost.clear()
            self._invoke.clear()
            self._models.clear()
            self._calib_tflops = 0.0
            self._device_info = None
        self._tl = threading.local()

    # -- compile-time capture ----------------------------------------------
    def note_compile(self, filt: str, bucket: str, *, seconds: float,
                     flops: float = 0.0,
                     bytes_accessed: float = 0.0) -> None:
        """Record one compile event into the cost registry.  The
        flops/bytes of a (filter, bucket) key are a property of the
        program, so re-compiles (LRU evictions, swaps) overwrite the
        estimate and accumulate wall seconds."""
        if not self.enabled:
            return
        with self._lock:
            e = self._cost.setdefault(
                (str(filt), str(bucket)),
                {"flops": 0.0, "bytes_accessed": 0.0,
                 "compile_s": 0.0, "compiles": 0})
            if flops:
                e["flops"] = float(flops)
            if bytes_accessed:
                e["bytes_accessed"] = float(bytes_accessed)
            e["compile_s"] += max(0.0, float(seconds))
            e["compiles"] += 1

    def capture_cost(self, filt: str, bucket: str, jitted: Any,
                     args: tuple, *, seconds: float,
                     kwargs: Optional[dict] = None) -> None:
        """Compile-event hook for backends: re-lower ``jitted`` over the
        concrete ``args`` (+ ``kwargs`` for static argnames) and harvest
        the XLA cost model (flops, bytes accessed).  Lowering is
        trace-level work — no second device compile — and only runs on
        cache misses.  Any failure (abstract args, exotic backend)
        degrades to a seconds-only entry."""
        if not self.enabled:
            return
        flops = bytes_accessed = 0.0
        try:
            cost = jitted.lower(*args, **(kwargs or {})) \
                .cost_analysis() or {}
            if isinstance(cost, (list, tuple)):   # per-computation form
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0) or 0.0)
            bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
        except Exception:
            pass
        self.note_compile(filt, bucket, seconds=seconds, flops=flops,
                          bytes_accessed=bytes_accessed)

    # -- invoke-time sampling ----------------------------------------------
    def note_dispatch(self, filt: str, bucket: str,
                      t0: Optional[float] = None) -> None:
        """Stamp this thread's in-flight dispatch; the next
        ``device_sync`` on the same thread closes the sample."""
        if not self.enabled:
            return
        self._tl.pending = (str(filt), str(bucket),
                            time.perf_counter() if t0 is None else t0)

    def sample_sync(self, t_end: Optional[float] = None) -> None:
        """Close the pending dispatch stamp (called from
        ``runtime.sync.device_sync`` right after the block completes).
        No pending stamp on this thread → no sample; the reservoirs are
        a sampling plane, not an accounting ledger."""
        if not self.enabled:
            return
        pending = getattr(self._tl, "pending", None)
        if pending is None:
            return
        self._tl.pending = None
        filt, bucket, t0 = pending
        end = time.perf_counter() if t_end is None else t_end
        if end > t0:
            self.note_invoke(filt, bucket, end - t0)

    def note_invoke(self, filt: str, bucket: str, seconds: float) -> None:
        """Record one sampled device-time observation."""
        if not self.enabled or seconds <= 0:
            return
        with self._lock:
            e = self._invoke.setdefault(
                (str(filt), str(bucket)),
                {"ring": deque(maxlen=self._reservoir),
                 "seconds": 0.0, "count": 0})
            e["ring"].append(float(seconds))
            e["seconds"] += float(seconds)
            e["count"] += 1

    # -- HBM ledger ---------------------------------------------------------
    def attach_model(self, label: str, backend: Any) -> None:
        """Register a backend for per-model HBM attribution: its
        ``resident_bytes()`` (and ``resident_bytes_by_version()`` when
        present) show up as ``model:<label>`` rows in the ledger.  Held
        by weakref — a released model silently leaves the ledger."""
        if not label:
            return
        with self._lock:
            self._models[str(label)] = weakref.ref(backend)

    def _device_meta(self) -> Dict[str, Any]:
        """Platform/device-kind/count, cached after first read (device
        topology does not change mid-process)."""
        if self._device_info is not None:
            return self._device_info
        info = {"platform": "none", "device_kind": "none", "devices": 0}
        try:
            import jax

            devs = jax.devices()
            if devs:
                info = {"platform": devs[0].platform,
                        "device_kind": devs[0].device_kind,
                        "devices": len(devs)}
        except Exception:
            pass
        self._device_info = info
        return info

    def hbm_rows(self) -> List[Dict[str, Any]]:
        """Per-device memory ledger rows {device, kind, bytes} from
        ``memory_stats()`` (absent on CPU emulation — rows simply do
        not appear), plus ``model:<label>`` attribution rows from
        attached backends."""
        rows: List[Dict[str, Any]] = []
        try:
            import jax

            for d in jax.devices():
                try:
                    ms = d.memory_stats()
                except Exception:
                    ms = None
                if not ms:
                    continue
                dev = f"{d.platform}:{d.id}"
                for kind in ("bytes_in_use", "bytes_limit",
                             "peak_bytes_in_use"):
                    if kind in ms:
                        rows.append({"device": dev, "kind": kind,
                                     "bytes": float(ms[kind])})
        except Exception:
            pass
        with self._lock:
            models = list(self._models.items())
        for label, ref in models:
            be = ref()
            if be is None:
                with self._lock:
                    self._models.pop(label, None)
                continue
            try:
                by_ver = getattr(be, "resident_bytes_by_version", None)
                vers = by_ver() if by_ver is not None else None
                if vers:
                    for v, b in sorted(vers.items()):
                        rows.append({"device": "-",
                                     "kind": f"model:{label}@{v}",
                                     "bytes": float(b)})
                else:
                    rows.append({"device": "-", "kind": f"model:{label}",
                                 "bytes": float(be.resident_bytes())})
            except Exception:
                continue
        return rows

    # -- read-out -----------------------------------------------------------
    def _peaks(self) -> Tuple[float, float]:
        ot, ob = self._peak_override
        if ot is not None:
            return float(ot), float(ob or 0.0)
        meta = self._device_meta()
        return peak_for(meta["device_kind"])

    def stats(self) -> Dict[str, Any]:
        """One coherent snapshot for the metrics plane / top / bundles:
        ``jit`` rows (cost registry), ``invoke`` rows (reservoir-derived
        achieved TFLOP/s + MFU + cumulative seconds), ``hbm`` +
        ``headroom`` rows, and the peak table actually applied."""
        meta = self._device_meta()
        peak_tf, peak_bw = self._peaks()
        ridge = (peak_tf * 1e12) / (peak_bw * 1e9) if peak_tf and peak_bw \
            else 0.0
        with self._lock:
            cost = {k: dict(v) for k, v in self._cost.items()}
            invoke = {k: {"samples": list(v["ring"]),
                          "seconds": v["seconds"], "count": v["count"]}
                      for k, v in self._invoke.items()}
        jit_rows = []
        for (filt, bucket), e in sorted(cost.items()):
            ai = e["flops"] / e["bytes_accessed"] \
                if e["bytes_accessed"] else 0.0
            if not e["flops"] or not ridge:
                roofline = "unknown"
            else:
                roofline = "compute" if ai >= ridge else "memory"
            jit_rows.append({
                "filter": filt, "bucket": bucket,
                "flops": e["flops"],
                "bytes_accessed": e["bytes_accessed"],
                "compile_s": e["compile_s"], "compiles": e["compiles"],
                "ai": round(ai, 3), "roofline": roofline,
            })
        # calibration peak: best achieved TFLOP/s across every bucket —
        # the measured denominator where no declared peak exists
        achieved: Dict[Tuple[str, str], float] = {}
        for key, e in invoke.items():
            samples = sorted(e["samples"])
            if not samples:
                continue
            med = samples[len(samples) // 2]
            flops = cost.get(key, {}).get("flops", 0.0)
            tf = flops / med / 1e12 if flops and med > 0 else 0.0
            achieved[key] = (tf, med)
            if tf > self._calib_tflops:
                self._calib_tflops = tf
        invoke_rows = []
        for (filt, bucket), e in sorted(invoke.items()):
            tf, med = achieved.get((filt, bucket), (0.0, 0.0))
            invoke_rows.append({
                "filter": filt, "bucket": bucket,
                "device": meta["device_kind"],
                "seconds_total": e["seconds"],
                "samples_total": e["count"],
                "p50_ms": round(med * 1e3, 4),
                "achieved_tflops": round(tf, 4),
                "mfu": round(tf / peak_tf, 4) if peak_tf else 0.0,
                "mfu_calibrated": round(tf / self._calib_tflops, 4)
                if self._calib_tflops else 0.0,
            })
        hbm = self.hbm_rows()
        headroom = []
        by_dev: Dict[str, Dict[str, float]] = {}
        for r in hbm:
            if r["device"] != "-":
                by_dev.setdefault(r["device"], {})[r["kind"]] = r["bytes"]
        for dev, kinds in sorted(by_dev.items()):
            limit = kinds.get("bytes_limit", 0.0)
            if limit:
                headroom.append({
                    "device": dev,
                    "frac": round(kinds.get("bytes_in_use", 0.0) / limit,
                                  6)})
        return {
            "enabled": self.enabled,
            "platform": meta["platform"],
            "device_kind": meta["device_kind"],
            "devices": meta["devices"],
            "peak_tflops": peak_tf,
            "peak_hbm_gbps": peak_bw,
            "calibration_tflops": round(self._calib_tflops, 4),
            "compile_seconds_total": round(
                sum(r["compile_s"] for r in jit_rows), 6),
            "compiles_total": sum(r["compiles"] for r in jit_rows),
            "jit": jit_rows,
            "invoke": invoke_rows,
            "hbm": hbm,
            "headroom": headroom,
        }

    def counter_tracks(self) -> List[Tuple[str, float]]:
        """(name, value) counter samples for Chrome-trace counter
        tracks: per-bucket MFU (calibrated where no declared peak) and
        per-device HBM in-use."""
        st = self.stats()
        out: List[Tuple[str, float]] = []
        for r in st["invoke"]:
            v = r["mfu"] if st["peak_tflops"] else r["mfu_calibrated"]
            out.append((f"mfu:{r['filter']}/{r['bucket']}", v))
        for r in st["hbm"]:
            if r["kind"] == "bytes_in_use":
                out.append((f"hbm:{r['device']}", r["bytes"]))
        return out


#: process-wide profiler — backends and `device_sync` all talk to this
#: one instance; off until something enables it
_PROFILER = DeviceProfiler()


def get() -> DeviceProfiler:
    return _PROFILER


def bucket_label(basekey: tuple) -> str:
    """Compact bounded-cardinality label for a backend bucket key:
    ``("fix", ((1, 224, 224, 3), "uint8"), ...)`` → ``fix:1x224x224x3``,
    ``("dynb", 8, ...)`` → ``dynb:8``.  Cardinality is bounded by the
    backend's own bucketing (pow2 batches, served-shape set)."""
    if not basekey:
        return "static"
    kind = str(basekey[0])
    if kind == "fix" and len(basekey) > 1:
        shape = basekey[1][0] if isinstance(basekey[1], tuple) else ()
        return f"fix:{'x'.join(str(d) for d in shape)}"
    if kind == "dynb" and len(basekey) > 1:
        return f"dynb:{basekey[1]}"
    return kind
