"""Streaming runtime — the scheduler substrate GStreamer provides the
reference (threads, queues, backpressure, EOS/error propagation)."""

from nnstreamer_tpu.runtime.scheduler import EOS, PipelineRunner, run_pipeline

__all__ = ["PipelineRunner", "run_pipeline", "EOS"]
