"""Streaming runtime — the scheduler substrate GStreamer provides the
reference (threads, queues, backpressure, EOS/error propagation)."""

from nnstreamer_tpu.runtime.tracing import NULL_TRACER, NullTracer, Tracer
from nnstreamer_tpu.runtime.scheduler import EOS, PipelineRunner, run_pipeline
from nnstreamer_tpu.runtime.input_pipeline import (
    DeviceFeeder, prefetch_to_device)
from nnstreamer_tpu.runtime.sync import device_sync, forced_sync_count

__all__ = ["PipelineRunner", "run_pipeline", "EOS",
           "Tracer", "NullTracer", "NULL_TRACER",
           "DeviceFeeder", "prefetch_to_device",
           "device_sync", "forced_sync_count"]
