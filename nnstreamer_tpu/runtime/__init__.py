"""Streaming runtime — the scheduler substrate GStreamer provides the
reference (threads, queues, backpressure, EOS/error propagation)."""

from nnstreamer_tpu.runtime.tracing import NULL_TRACER, NullTracer, Tracer
from nnstreamer_tpu.runtime.scheduler import EOS, PipelineRunner, run_pipeline
from nnstreamer_tpu.runtime.input_pipeline import (
    DeviceFeeder, prefetch_to_device)

__all__ = ["PipelineRunner", "run_pipeline", "EOS",
           "Tracer", "NullTracer", "NULL_TRACER",
           "DeviceFeeder", "prefetch_to_device"]
