"""SLO-breach flight recorder: always-on forensic capture.

An incident investigation needs the telemetry from *around* the
incident — and by the time a human is looking, the event ring has
wrapped and the bad minute is gone.  The flight recorder keeps a
bounded ring of periodic metric snapshots next to the tracer's own
(always-on, bounded) event ring, watches a small set of trigger
predicates, and on the first firing dumps an atomic bundle directory:
Chrome trace, Prometheus text, autotune audit ring, env snapshot,
snapshot ring, and the trigger cause.  ROADMAP item 6's adversarial
drills read these bundles instead of asking "can you reproduce it".

Trigger taxonomy (each independently rate-limited by a per-trigger
cooldown so a sustained breach produces one bundle per window, not a
disk flood):

- ``slo_breach``     — observed p99 above the SLOSpec budget (fed by
  serving/autotune's sensor, or directly via :meth:`note_slo_breach`)
- ``conservation``   — admission ledger mismatch (offered ≠ replied +
  rejected + shed + depth + inflight) on two *consecutive* scans; one
  scan's worth of slack absorbs the benign mid-flight read races the
  conservation tests allow
- ``worker_fence``   — a worker/host kill|fence lifecycle event
- ``kernel_fallback``— a requested Pallas path served on XLA
- ``watchdog``       — a watchdog incident recorded by the tracer
- ``manual``         — operator-requested dump (CLI / tests)

Counter-derived triggers (fence, fallback, watchdog) are watermarked:
the first observation of a source only sets the baseline, so attaching
the recorder to a system with historical faults does not dump.

Atomicity: bundles are written to a dot-prefixed temp directory and
``os.rename``d into place — a reader listing the flight dir never sees
a partial bundle (``list_bundles`` additionally ignores dot-entries).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from nnstreamer_tpu.core.log import get_logger

log = get_logger("runtime.flightrec")

#: the trigger kinds a recorder can fire (fixed taxonomy; cause.json
#: carries the evidence)
TRIGGERS = ("slo_breach", "conservation", "worker_fence",
            "kernel_fallback", "watchdog", "manual",
            "scenario_violation")

DEFAULT_COOLDOWN_S = 60.0


class FlightRecorder:
    """Bounded snapshot ring + trigger predicates + atomic bundle dump.

    ``clock`` is injectable (tests drive cooldown windows without
    sleeping).  All state is under one lock; predicates and dumps run
    on whatever thread polls (the serve loop's poller thread or a
    metrics scrape), never on the frame hot path.
    """

    def __init__(self, out_dir: str, *,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 snap_ring: int = 32,
                 clock: Callable[[], float] = time.monotonic):
        self.out_dir = str(out_dir)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._snaps: deque = deque(maxlen=max(1, int(snap_ring)))
        self._last_dump: Dict[str, float] = {}
        self._seq = 0
        self._counts: Dict[str, int] = {}        # fired, by kind
        self._suppressed: Dict[str, int] = {}    # cooldown-gated, by kind
        self._watermarks: Dict[str, float] = {}  # monotone-source baselines
        self._conservation_streak = 0
        # attached telemetry sources (all optional)
        self._tracer = None
        self._autotune = None
        self._prom: Optional[Callable[[], str]] = None
        self._env: Optional[Callable[[], dict]] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        os.makedirs(self.out_dir, exist_ok=True)

    # -- wiring -------------------------------------------------------------
    def attach(self, *, tracer=None, autotune=None,
               prom: Optional[Callable[[], str]] = None,
               env: Optional[Callable[[], dict]] = None
               ) -> "FlightRecorder":
        """Attach telemetry sources consulted at dump time: the tracer
        (Chrome trace + worker/watchdog counters), the autotuner (audit
        ring + SLO), a ``prom()`` callable returning exposition text,
        and an ``env()`` callable returning a JSON-able snapshot."""
        if tracer is not None:
            self._tracer = tracer
        if autotune is not None:
            self._autotune = autotune
            setattr(autotune, "flight", self)
        if prom is not None:
            self._prom = prom
        if env is not None:
            self._env = env
        return self

    # -- periodic snapshot ring ----------------------------------------------
    def tick(self, snapshot: Optional[dict] = None) -> None:
        """Append one periodic metric snapshot to the ring (bounded —
        always-on costs a fixed amount of memory)."""
        with self._lock:
            self._snaps.append({"t": self._clock(),
                                "snapshot": snapshot or {}})

    # -- trigger feeds --------------------------------------------------------
    def note_slo_breach(self, p99_ms: float, budget_ms: float,
                        **ctx) -> Optional[str]:
        """Direct SLO-breach feed (serving/autotune's sensor calls this
        when the observed p99 exceeds the budget)."""
        return self.trigger("slo_breach", dict(
            ctx, p99_ms=round(float(p99_ms), 3),
            p99_budget_ms=float(budget_ms)))

    def scan(self, *, p99_ms: Optional[float] = None,
             p99_budget_ms: Optional[float] = None,
             admission: Optional[dict] = None,
             worker_counts: Optional[dict] = None,
             watchdog_counts: Optional[dict] = None,
             kernel_fallbacks: Optional[float] = None) -> List[str]:
        """Evaluate every predicate against one round of signals and
        dump for each that fires; returns the kinds that dumped."""
        fired: List[str] = []

        def hit(kind: str, cause: dict) -> None:
            if self.trigger(kind, cause) is not None:
                fired.append(kind)

        if p99_ms is not None and p99_budget_ms and p99_ms > p99_budget_ms:
            hit("slo_breach", {"p99_ms": round(p99_ms, 3),
                               "p99_budget_ms": p99_budget_ms})
        if admission is not None:
            accounted = (
                float(admission.get("replied", 0))
                + sum(admission.get("rejected", {}).values())
                + sum(admission.get("shed", {}).values())
                + float(admission.get("depth", 0))
                + float(admission.get("inflight", 0)))
            offered = float(admission.get("offered", 0))
            if offered != accounted:
                with self._lock:
                    self._conservation_streak += 1
                    streak = self._conservation_streak
                if streak >= 2:
                    hit("conservation", {
                        "offered": offered, "accounted": accounted,
                        "delta": offered - accounted,
                        "consecutive_scans": streak})
            else:
                with self._lock:
                    self._conservation_streak = 0
        for kind, counts in (("worker_fence", worker_counts),
                             ("watchdog", watchdog_counts)):
            if counts:
                total = sum(float(v) for sub in counts.values()
                            for v in (sub.values()
                                      if isinstance(sub, dict) else [sub]))
                if self._rose(kind, total):
                    hit(kind, {"count": total, "events": {
                        k: (dict(v) if isinstance(v, dict) else v)
                        for k, v in counts.items()}})
        if kernel_fallbacks is not None \
                and self._rose("kernel_fallback", float(kernel_fallbacks)):
            hit("kernel_fallback", {"count": float(kernel_fallbacks)})
        return fired

    def poll(self, *, admission: Optional[dict] = None,
             llm: Optional[Dict[str, dict]] = None) -> List[str]:
        """One recorder pass over attached + passed sources: snapshot
        tick, then scan.  The serve loop's poller calls this."""
        self.tick()
        kw: Dict[str, Any] = {"admission": admission}
        tr = self._tracer
        if tr is not None and getattr(tr, "active", False):
            kw["worker_counts"] = {
                n: {k: v for k, v in kinds.items()
                    if k in ("kill", "fence", "fenced", "killed")}
                for n, kinds in tr.worker_counts().items()}
            kw["watchdog_counts"] = tr.watchdog_counts()
        if llm:
            kw["kernel_fallbacks"] = sum(
                float(st.get("executor", st).get("kernel_fallback", 0))
                for st in llm.values())
        return self.scan(**kw)

    def _rose(self, key: str, total: float) -> bool:
        """Watermark test: True when a monotone source increased past
        its last-seen value; the first observation only baselines."""
        with self._lock:
            prev = self._watermarks.get(key)
            self._watermarks[key] = total
        return prev is not None and total > prev

    # -- dumping --------------------------------------------------------------
    def trigger(self, kind: str, cause: Optional[dict] = None
                ) -> Optional[str]:
        """Fire one trigger: within the kind's cooldown window this is
        counted and suppressed; otherwise a complete bundle directory
        is atomically published and its path returned."""
        kind = str(kind)
        now = self._clock()
        with self._lock:
            last = self._last_dump.get(kind)
            if last is not None and now - last < self.cooldown_s:
                self._suppressed[kind] = self._suppressed.get(kind, 0) + 1
                return None
            self._last_dump[kind] = now
            self._seq += 1
            seq = self._seq
            self._counts[kind] = self._counts.get(kind, 0) + 1
        try:
            path = self._dump(kind, seq, dict(cause or {}), now)
        except Exception as e:
            log.exception("flight dump for %s failed", kind)
            with self._lock:   # a failed dump must not eat the window
                if self._last_dump.get(kind) == now:
                    del self._last_dump[kind]
            raise RuntimeError(f"flight dump failed: {e}") from e
        tr = self._tracer
        if tr is not None and getattr(tr, "active", False):
            tr.record_flight(kind, time.perf_counter(), path=path)
        log.warning("flight recorder fired: %s -> %s", kind, path)
        return path

    def _dump(self, kind: str, seq: int, cause: dict, now: float) -> str:
        """Assemble the bundle in a dot-prefixed temp dir, then publish
        with one ``os.rename`` — partial bundles are never visible."""
        name = f"flight-{seq:04d}-{kind}"
        tmp = os.path.join(self.out_dir, f".tmp-{name}-{os.getpid()}")
        final = os.path.join(self.out_dir, name)
        os.makedirs(tmp, exist_ok=True)

        def put(fname: str, payload: Any, raw: bool = False) -> None:
            with open(os.path.join(tmp, fname), "w") as f:
                if raw:
                    f.write(payload)
                else:
                    json.dump(payload, f, indent=2, default=str)
                    f.write("\n")

        put("cause.json", {
            "kind": kind, "seq": seq, "cause": cause,
            "monotonic": now, "wall_time": time.time(),
            "cooldown_s": self.cooldown_s})
        with self._lock:
            snaps = list(self._snaps)
        put("snapshots.json", snaps)
        tr = self._tracer
        if tr is not None and getattr(tr, "active", False):
            try:
                put("trace.json", tr.to_chrome_trace("flight"))
            except Exception as e:
                put("trace.error", f"{type(e).__name__}: {e}\n", raw=True)
        if self._prom is not None:
            try:
                put("metrics.prom", self._prom(), raw=True)
            except Exception as e:
                put("metrics.error", f"{type(e).__name__}: {e}\n",
                    raw=True)
        at = self._autotune
        if at is not None:
            try:
                put("autotune.json", {"audit": at.audit(),
                                      "stats": at.stats()})
            except Exception as e:
                put("autotune.error", f"{type(e).__name__}: {e}\n",
                    raw=True)
        if self._env is not None:
            try:
                put("env.json", self._env())
            except Exception as e:
                put("env.error", f"{type(e).__name__}: {e}\n", raw=True)
        os.rename(tmp, final)
        return final

    # -- background poller ----------------------------------------------------
    def run_background(self, signal_fn: Optional[Callable[[], dict]] = None,
                       interval_s: float = 2.0) -> "FlightRecorder":
        """Start the poller thread: every ``interval_s`` it calls
        ``poll(**signal_fn())`` (``signal_fn`` returns the poll kwargs —
        fresh admission counters, llm stats — or {})."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.poll(**(signal_fn() if signal_fn else {}))
                except Exception:
                    log.exception("flight poll failed")

        self._thread = threading.Thread(
            target=run, name="flight-recorder", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- read-out -------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "out_dir": self.out_dir,
                "cooldown_s": self.cooldown_s,
                "snapshots": len(self._snaps),
                "dumps": dict(self._counts),
                "suppressed": dict(self._suppressed),
                "dumps_total": sum(self._counts.values()),
                "suppressed_total": sum(self._suppressed.values()),
            }


# -- bundle inspection (CLI + tests) ------------------------------------------

def list_bundles(out_dir: str) -> List[Dict[str, Any]]:
    """Complete bundles under ``out_dir``, oldest first.  Dot-entries
    (in-progress temp dirs) are invisible by construction."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        return out
    for name in names:
        if name.startswith(".") or not name.startswith("flight-"):
            continue
        path = os.path.join(out_dir, name)
        if not os.path.isdir(path):
            continue
        info: Dict[str, Any] = {"name": name, "path": path,
                                "files": sorted(os.listdir(path))}
        try:
            with open(os.path.join(path, "cause.json")) as f:
                c = json.load(f)
            info.update({"kind": c.get("kind"), "seq": c.get("seq"),
                         "wall_time": c.get("wall_time"),
                         "cause": c.get("cause")})
        except Exception:
            info["kind"] = "?"
        out.append(info)
    return out


def load_bundle(path: str) -> Dict[str, Any]:
    """Parse every bundle artifact into one dict (JSON files parsed,
    .prom/.error text inlined)."""
    out: Dict[str, Any] = {"path": path}
    for name in sorted(os.listdir(path)):
        p = os.path.join(path, name)
        if not os.path.isfile(p):
            continue
        key = name.rsplit(".", 1)[0]
        try:
            if name.endswith(".json"):
                with open(p) as f:
                    out[key] = json.load(f)
            else:
                with open(p) as f:
                    out[name] = f.read()
        except Exception as e:
            out[name] = f"<unreadable: {e}>"
    return out
