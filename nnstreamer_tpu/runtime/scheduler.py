"""Push-model streaming scheduler.

Design (SURVEY.md §7 step 3): one worker thread per element with a bounded
input queue per element — the analog of GStreamer's streaming threads +
queue elements, but uniform: every link is naturally double-buffered, so a
filter's device dispatch overlaps upstream conversion (the async-dispatch
property the reference loses to per-frame cudaDeviceSynchronize,
tensor_filter_tensorrt.cc:239).

Dataflow rules:
- Sources run a pump thread iterating `generate()`.
- Every buffer delivered to `Element.process(pad, buf)`; emissions are
  routed by (element, src_pad) → link → destination queue.
- EOS: a sentinel per pad; when all sink pads of an element saw EOS, the
  element's `flush()` drains (aggregation windows…), then EOS cascades.
- Errors: any exception in a worker stops the pipeline and re-raises from
  `wait()` (GST_FLOW_ERROR analog: fail loud, never hang).
- Backpressure: bounded queues block the producer ([runtime]
  queue_capacity), or drop oldest when an element opts into leaky mode.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.core.config import get_config
from nnstreamer_tpu.core.errors import PipelineError, StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.graph.pipeline import Element, Link, Pipeline, SourceElement
from nnstreamer_tpu.runtime.tracing import NULL_TRACER, Tracer
from nnstreamer_tpu.tensor.buffer import TensorBuffer

log = get_logger("runtime")


class _EOSType:
    def __repr__(self):
        return "EOS"


#: end-of-stream sentinel
EOS = _EOSType()


class ElementStats:
    """Per-element processing-time counters — the GstShark proctime tracer
    analog (SURVEY.md §5.1: tools/tracing/README.md:34-41), first-class
    instead of out-sourced. Read via PipelineRunner.stats()."""

    __slots__ = ("buffers", "total_s", "max_s", "wait_s", "wait_max_s",
                 "timer_fires", "dropped", "queue_peak")

    def __init__(self):
        self.buffers = 0
        self.total_s = 0.0
        self.max_s = 0.0
        # time buffers spent parked in this element's input queue —
        # separates "this element is slow" (proctime) from "this element
        # is starved/stalled behind others" (queue wait), the split the
        # composite-tail diagnosis needs (GstShark interlatency analog)
        self.wait_s = 0.0
        self.wait_max_s = 0.0
        # deadline wakeups delivered to on_timer() (tensor_batch
        # max-latency flushes fire through here)
        self.timer_fires = 0
        # buffers this element emitted that teardown aborted mid-put
        # (counted on the *producer* so the loss is attributable)
        self.dropped = 0
        # high-water mark of this element's input queue (queuelevel
        # tracer analog; capacity is the runner's queue_capacity)
        self.queue_peak = 0

    def record(self, dt: float) -> None:
        self.buffers += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt

    def record_wait(self, dt: float) -> None:
        self.wait_s += dt
        if dt > self.wait_max_s:
            self.wait_max_s = dt

    @property
    def avg_us(self) -> float:
        return 1e6 * self.total_s / self.buffers if self.buffers else 0.0

    def as_dict(self) -> dict:
        return {"buffers": self.buffers, "proctime_avg_us": self.avg_us,
                "proctime_max_us": 1e6 * self.max_s,
                "proctime_total_s": self.total_s,
                "queue_wait_avg_us": (1e6 * self.wait_s / self.buffers
                                      if self.buffers else 0.0),
                "queue_wait_max_us": 1e6 * self.wait_max_s,
                "timer_fires": self.timer_fires,
                "dropped": self.dropped,
                "queue_peak": self.queue_peak}


class PipelineRunner:
    def __init__(self, pipeline: Pipeline, queue_capacity: Optional[int] = None,
                 optimize: bool = True, trace=False):
        self.pipeline = pipeline
        self._optimize = optimize
        # trace=False → NULL_TRACER (hot path pays one attribute load);
        # trace=True → fresh Tracer; or pass a Tracer/NullTracer directly
        if hasattr(trace, "active"):
            self.tracer = trace
        elif trace:
            self.tracer = Tracer()
        else:
            self.tracer = NULL_TRACER
        cap = queue_capacity or get_config().get_int("runtime", "queue_capacity", 4)
        self._cap = max(1, cap)
        self._queues: Dict[str, "queue.Queue"] = {}
        # built in start(), AFTER transform fusion removed elements —
        # fused-away elements must not appear as zero-count stats rows
        self._stats: Dict[str, ElementStats] = {}
        self._threads: List[threading.Thread] = []
        self._stop_evt = threading.Event()
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._started = False
        self._route: Dict[Tuple[str, int], Link] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "PipelineRunner":
        if self._started:
            raise PipelineError("runner already started")
        pipe = self.pipeline
        if not pipe._negotiated:
            if self._optimize:
                from nnstreamer_tpu.graph.optimize import fuse_transforms

                fuse_transforms(pipe)
            pipe.negotiate()
        for name in pipe.elements:
            self._stats.setdefault(name, ElementStats())
        for e in pipe.elements.values():
            e._event_router = self._route_upstream
            # tracer handed down before start() so elements can forward
            # it further (tensor_filter → backend invoke/compile spans)
            e._tracer = self.tracer
            e.start()
        for l in pipe.links:
            self._route[(l.src.name, l.src_pad)] = l
        for e in pipe.elements.values():
            if not isinstance(e, SourceElement):
                self._queues[e.name] = queue.Queue(maxsize=self._cap)
        for e in pipe.elements.values():
            if isinstance(e, SourceElement):
                t = threading.Thread(target=self._pump, args=(e,),
                                     name=f"src:{e.name}", daemon=True)
            else:
                t = threading.Thread(target=self._work, args=(e,),
                                     name=f"elem:{e.name}", daemon=True)
            self._threads.append(t)
        self._started = True
        for t in self._threads:
            t.start()
        return self

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every element finished (EOS fully propagated)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            t.join(remaining)
            if t.is_alive():
                self.stop()
                if self._error is not None:
                    # the hang is a symptom: a worker already failed and
                    # a peer never drained — surface the root cause, not
                    # a bare timeout that swallows it
                    raise StreamError(
                        f"pipeline {self.pipeline.name!r} failed: "
                        f"{self._error} (thread {t.name} then did not "
                        f"finish within {timeout}s)"
                    ) from self._error
                raise StreamError(
                    f"pipeline {self.pipeline.name!r} did not finish within "
                    f"{timeout}s (thread {t.name} still running)"
                )
        if self._error is not None:
            raise StreamError(
                f"pipeline {self.pipeline.name!r} failed: {self._error}"
            ) from self._error

    def stop(self) -> None:
        """Request teardown; safe to call multiple times."""
        self._stop_evt.set()
        # unblock sources stuck in generate() (e.g. appsrc waiting for push)
        for e in self.pipeline.elements.values():
            if isinstance(e, SourceElement):
                try:
                    e.interrupt()
                except Exception:
                    log.exception("error interrupting %s", e.name)
        # unblock workers waiting on get()
        for q in self._queues.values():
            try:
                q.put_nowait((None, EOS, 0.0))
            except queue.Full:
                pass
        for e in self.pipeline.elements.values():
            try:
                e.stop()
            except Exception:  # teardown must not mask the first error
                log.exception("error stopping %s", e.name)

    def run(self, timeout: Optional[float] = None) -> None:
        self.start()
        try:
            self.wait(timeout)
        finally:
            self.stop()

    def stats(self) -> Dict[str, dict]:
        """Per-element proctime/buffer counters (tracing, §5.1).

        tensor_filter elements additionally expose their own
        latency_us/throughput props (the reference's two counters)."""
        out = {}
        for name, s in self._stats.items():
            d = s.as_dict()
            e = self.pipeline.elements.get(name)
            if hasattr(e, "latency_us"):
                d["invoke_latency_us"] = e.latency_us
                d["invoke_throughput"] = e.throughput
            # element-specific counters (tensor_batch occupancy histogram
            # + flush reasons, …) merge into the same stats row
            extra = getattr(e, "extra_stats", None)
            if extra is not None:
                d.update(extra())
            out[name] = d
        return out

    def report(self) -> str:
        """Human-readable observability report: per-element proctime
        table (sorted by total processing time, heaviest first), per-link
        queue high-water marks, and — when tracing is on — interlatency
        percentiles per element with sinks marked (the sink rows are the
        end-to-end pipeline latency) and backend compile/cache counters.
        """
        st = self.stats()
        lines = [f"pipeline {self.pipeline.name!r} — element report",
                 "",
                 f"{'element':<22} {'buffers':>8} {'total ms':>9} "
                 f"{'avg µs':>9} {'max µs':>9} {'wait µs':>9} "
                 f"{'q.peak':>6} {'drop':>5} {'timer':>6}"]
        for name, d in sorted(st.items(),
                              key=lambda kv: -kv[1]["proctime_total_s"]):
            lines.append(
                f"{name:<22} {d['buffers']:>8} "
                f"{d['proctime_total_s'] * 1e3:>9.2f} "
                f"{d['proctime_avg_us']:>9.1f} {d['proctime_max_us']:>9.1f} "
                f"{d['queue_wait_avg_us']:>9.1f} {d['queue_peak']:>6} "
                f"{d['dropped']:>5} {d['timer_fires']:>6}")
        lines.append("")
        lines.append(f"queue high-water (capacity {self._cap}):")
        for l in self.pipeline.links:
            d = st.get(l.dst.name)
            if d is None:
                continue
            lines.append(f"  {l.src.name} → {l.dst.name}: "
                         f"peak {d['queue_peak']}/{self._cap}")
        tr = self.tracer
        if tr.active:
            inter = tr.interlatency()
            if inter:
                sinks = {e.name for e in self.pipeline.elements.values()
                         if not self.pipeline.links_from(e)}
                lines.append("")
                lines.append("interlatency source → element (ms):")
                lines.append(f"  {'element':<22} {'n':>6} {'p50':>8} "
                             f"{'p95':>8} {'p99':>8} {'max':>8}")
                for name, r in sorted(inter.items(),
                                      key=lambda kv: kv[1]["p50_ms"]):
                    mark = " (sink)" if name in sinks else ""
                    lines.append(
                        f"  {name + mark:<22} {r['n']:>6} "
                        f"{r['p50_ms']:>8.3f} {r['p95_ms']:>8.3f} "
                        f"{r['p99_ms']:>8.3f} {r['max_ms']:>8.3f}")
            if tr.events_dropped:
                lines.append("")
                lines.append(f"note: event ring wrapped, "
                             f"{tr.events_dropped} oldest events dropped")
        backend_rows = [
            (name, {k: v for k, v in d.items() if k.startswith("backend_")})
            for name, d in st.items()]
        backend_rows = [(n, b) for n, b in backend_rows if b]
        if backend_rows:
            lines.append("")
            lines.append("backend counters:")
            for name, b in backend_rows:
                kv = " ".join(f"{k[len('backend_'):]}={v}"
                              for k, v in sorted(b.items()))
                lines.append(f"  {name}: {kv}")
        return "\n".join(lines)

    # -- internals ---------------------------------------------------------
    def _route_upstream(self, origin: Element, event: dict) -> None:
        """Walk the link graph upstream from `origin`, offering `event`
        to each element until consumed (upstream QoS event path)."""
        seen = {origin.name}
        frontier = [origin]
        while frontier:
            e = frontier.pop()
            for l in self.pipeline.links_to(e):
                u = l.src
                if u.name in seen:
                    continue
                seen.add(u.name)
                try:
                    consumed = u.handle_upstream_event(event)
                except Exception:
                    log.exception("upstream event failed at %s", u.name)
                    consumed = True
                if not consumed:
                    frontier.append(u)

    def _fail(self, elem: Element, exc: BaseException) -> None:
        with self._error_lock:
            if self._error is None:
                self._error = exc
        log.error("element %s failed: %s", elem.name, exc)
        self._stop_evt.set()
        for q in self._queues.values():
            try:
                q.put_nowait((None, EOS, 0.0))
            except queue.Full:
                pass

    def _emit(self, elem: Element, src_pad: int, item) -> None:
        link = self._route.get((elem.name, src_pad))
        if link is None:
            raise PipelineError(
                f"element {elem.name} emitted on unlinked src pad {src_pad}"
            )
        if link.dst.WANTS_HOST and isinstance(item, TensorBuffer) \
                and item.on_device:
            # start the D2H transfer now; the consumer's to_host() then
            # overlaps with compute of other in-flight frames
            item.prefetch_host()
        q = self._queues[link.dst.name]
        t_enq = time.perf_counter()
        tr = self.tracer
        while not self._stop_evt.is_set():
            try:
                q.put((link.dst_pad, item, t_enq), timeout=0.1)
            except queue.Full:
                continue
            # queuelevel gauge: the high-water mark is always-on (one
            # qsize() per enqueue, same spirit as the wait counters);
            # the full depth time-series is tracer-gated
            depth = q.qsize()
            dst_stats = self._stats.get(link.dst.name)
            if dst_stats is not None and depth > dst_stats.queue_peak:
                dst_stats.queue_peak = depth
            if tr.active:
                tr.enqueue(link.dst.name, depth, time.perf_counter())
            return
        # _stop_evt aborted the put loop: the buffer is lost. Count it
        # so teardown/failure losses are visible in stats() instead of
        # vanishing silently (EOS is not a payload — no loss to count).
        if item is not EOS:
            stats = self._stats.get(elem.name)
            if stats is not None:
                stats.dropped += 1
            log.debug("teardown dropped a buffer from %s -> %s (pts=%s)",
                      elem.name, link.dst.name, getattr(item, "pts", None))
            if tr.active:
                tr.record_drop(elem.name, time.perf_counter())

    def _broadcast_eos(self, elem: Element) -> None:
        for l in self.pipeline.links_from(elem):
            self._emit(elem, l.src_pad, EOS)

    def _pump(self, src: SourceElement) -> None:
        tr = self.tracer
        try:
            for buf in src.generate():
                if self._stop_evt.is_set():
                    break
                if tr.active:
                    # interlatency origin: stamp the pipeline-entry time
                    tr.source_emit(src.name, buf, time.perf_counter())
                self._emit(src, 0, buf)
            self._broadcast_eos(src)
        except Exception as e:
            self._fail(src, e)
            try:
                self._broadcast_eos(src)
            except Exception:
                pass

    def _work(self, elem: Element) -> None:
        q = self._queues[elem.name]
        n_pads = max(1, len(self.pipeline.links_to(elem)))
        eos_pads = set()
        stats = self._stats[elem.name]
        tr = self.tracer
        try:
            while not self._stop_evt.is_set():
                # deadline-aware wait: an element holding half-assembled
                # state (tensor_batch) publishes its next flush instant;
                # the queue wait shortens to meet it so a partial batch
                # ships on time even when no further buffer ever arrives
                deadline = elem.next_deadline()
                if deadline is None:
                    timeout = 0.1
                else:
                    now = time.perf_counter()
                    if now >= deadline:
                        stats.timer_fires += 1
                        for sp, b in elem.on_timer():
                            self._emit(elem, sp, b)
                        if tr.active:
                            tr.record_timer(elem.name, now,
                                            time.perf_counter())
                        continue
                    timeout = min(0.1, deadline - now)
                try:
                    pad, item, t_enq = q.get(timeout=timeout)
                except queue.Empty:
                    continue
                if tr.active:
                    tr.dequeue(elem.name, q.qsize(), time.perf_counter())
                if item is EOS:
                    if pad is None:  # teardown wakeup
                        return
                    eos_pads.add(pad)
                    if len(eos_pads) >= n_pads:
                        t0 = time.perf_counter()
                        for sp, b in elem.flush():
                            self._emit(elem, sp, b)
                        if tr.active:
                            tr.record_flush(elem.name, t0,
                                            time.perf_counter())
                            tr.record_eos(elem.name, time.perf_counter())
                        self._broadcast_eos(elem)
                        return
                    continue
                t0 = time.perf_counter()
                if t_enq:
                    stats.record_wait(t0 - t_enq)
                emissions = elem.process(pad, item)
                t1 = time.perf_counter()
                stats.record(t1 - t0)
                if tr.active:
                    tr.record_process(elem.name, item, t0, t1)
                for sp, b in emissions:
                    self._emit(elem, sp, b)
        except Exception as e:
            self._fail(elem, e)
            try:
                self._broadcast_eos(elem)
            except Exception:
                pass


def run_pipeline(pipeline: Pipeline, timeout: Optional[float] = None,
                 optimize: bool = True) -> None:
    """Negotiate (with transform fusion by default), run to EOS, tear
    down. The gst-launch behavior."""
    PipelineRunner(pipeline, optimize=optimize).run(timeout)
