"""Push-model streaming scheduler.

Design (SURVEY.md §7 step 3): one worker thread per element with a bounded
input queue per element — the analog of GStreamer's streaming threads +
queue elements, but uniform: every link is naturally double-buffered, so a
filter's device dispatch overlaps upstream conversion (the async-dispatch
property the reference loses to per-frame cudaDeviceSynchronize,
tensor_filter_tensorrt.cc:239).

Dataflow rules:
- Sources run a pump thread iterating `generate()`.
- Every buffer delivered to `Element.process(pad, buf)`; emissions are
  routed by (element, src_pad) → link → destination channel.
- EOS: a sentinel per pad; when all sink pads of an element saw EOS, the
  element's `flush()` drains (aggregation windows…), then EOS cascades.
- Errors: any exception in a worker stops the pipeline and re-raises from
  `wait()` (GST_FLOW_ERROR analog: fail loud, never hang).
- Backpressure: bounded channels block the producer ([runtime]
  queue_capacity), or drop oldest when an element opts into leaky mode.

Host-path design (docs/performance.md):

- Links are `runtime/channel.py` condition-variable channels, not
  `queue.Queue`s: consumers wake on enqueue, producers on dequeue —
  no 100 ms poll floor, no idle CPU, and teardown (`Channel.close()`)
  wakes every waiter unconditionally. Timer elements (`next_deadline()`)
  get a deadline-bounded wait instead of a fixed 0.1 s tick.
- **Chain fusion** ([runtime] chain_fusion, default on): maximal linear
  runs of cheap single-in/single-out elements with `error-policy=fail`
  (converter→transform→decoder chains) execute in ONE worker thread
  with direct call-through — per-frame GIL handoffs drop from
  O(elements) to O(stages). tensor_filter (CHAIN_FUSABLE=False: its
  thread is what overlaps device dispatch with upstream conversion),
  sources/sinks, fan-in/fan-out, non-fail policies and `next_deadline`
  users keep dedicated threads. Stats, interlatency tracing and
  EOS/flush ordering stay attributed per element.
- **Device segments** ([runtime] device_segments, default on): before
  transform fusion, maximal filter→transform→filter runs collapse into
  one surviving head filter whose backend traces every member model into
  a single bucketed jit (`graph/optimize.fuse_segments`) — one dispatch
  per segment, tensors resident in HBM end-to-end.
- **Async dispatch window** ([runtime] max_inflight, default 8): a
  DEVICE_RESIDENT element's worker enqueues unresolved device arrays
  downstream without blocking, then bounds the number of in-flight
  dispatches by syncing the OLDEST emitted output once the window
  overflows. Host-bound elements (WANTS_HOST sinks/encoders) stay the
  pipeline's sync points; EOS drains the window before propagating.
- **Compiled steady-state loop** ([runtime] compiled_loop, default on):
  after `compiled_loop_arm` consecutive identical-signature frames, an
  eligible tensor_filter's worker sweeps the frames already queued on
  its channel into one window (≤ `compiled_loop_window`) and runs them
  as a SINGLE jitted `jax.lax.scan` dispatch
  (`TensorFilter.process_window` → `XLABackend.invoke_window`) — the
  per-frame Python loop is bypassed entirely in steady state. Any
  divergence (signature change, error, pending model swap, armed
  timer, EOS) bails back to per-frame mode with the cause accounted
  and stats reconciled exactly (runtime/compiled_loop.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from nnstreamer_tpu.core.config import get_config
from nnstreamer_tpu.core.errors import PipelineError, StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.graph.pipeline import Element, Link, Pipeline, SourceElement
from nnstreamer_tpu.runtime.channel import CLOSED, TIMED_OUT, Channel
from nnstreamer_tpu.runtime.compiled_loop import (LoopStats,
                                                 SteadyStateDetector,
                                                 frame_signature)
from nnstreamer_tpu.runtime.sync import device_sync
from nnstreamer_tpu.runtime.tracing import NULL_TRACER, Tracer
from nnstreamer_tpu.tensor.buffer import TensorBuffer

log = get_logger("runtime")


class _EOSType:
    def __repr__(self):
        return "EOS"


#: end-of-stream sentinel
EOS = _EOSType()


class _ChainFailure(Exception):
    """Internal: a fused-chain member's process()/flush() raised; carries
    the failing element so `_fail` attributes the error correctly."""

    def __init__(self, elem: Element, exc: BaseException):
        super().__init__(str(exc))
        self.elem = elem
        self.exc = exc


class ElementStats:
    """Per-element processing-time counters — the GstShark proctime tracer
    analog (SURVEY.md §5.1: tools/tracing/README.md:34-41), first-class
    instead of out-sourced. Read via PipelineRunner.stats()."""

    __slots__ = ("buffers", "total_s", "max_s", "wait_s", "wait_max_s",
                 "timer_fires", "dropped", "queue_peak", "errors",
                 "retries", "skipped", "degraded", "watchdog_warnings",
                 "event_errors")

    def __init__(self):
        self.buffers = 0
        self.total_s = 0.0
        self.max_s = 0.0
        # time buffers spent parked in this element's input queue —
        # separates "this element is slow" (proctime) from "this element
        # is starved/stalled behind others" (queue wait), the split the
        # composite-tail diagnosis needs (GstShark interlatency analog)
        self.wait_s = 0.0
        self.wait_max_s = 0.0
        # deadline wakeups delivered to on_timer() (tensor_batch
        # max-latency flushes fire through here)
        self.timer_fires = 0
        # buffers this element emitted that teardown aborted mid-put
        # (counted on the *producer* so the loss is attributable)
        self.dropped = 0
        # high-water mark of this element's input queue (queuelevel
        # tracer analog; capacity is the runner's queue_capacity)
        self.queue_peak = 0
        # -- robustness counters (error-policy machinery) ------------------
        # process() exceptions caught under this element's error policy
        # (every failed attempt counts, so retries show up here too)
        self.errors = 0
        # re-invocations attempted under retry:N
        self.retries = 0
        # input buffers abandoned after an error (skip policy, or retry
        # budget exhausted). Conservation invariant per pipeline:
        # emitted + skipped + dropped == generated
        self.skipped = 0
        # input buffers routed to the fallback src pad (degrade policy)
        self.degraded = 0
        # watchdog incidents flagged against this element (stalled
        # process() or input queue pinned at capacity)
        self.watchdog_warnings = 0
        # handle_upstream_event() exceptions (event swallowed, not
        # consumed — propagation continues past this element)
        self.event_errors = 0

    def record(self, dt: float) -> None:
        self.buffers += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt

    def record_wait(self, dt: float) -> None:
        self.wait_s += dt
        if dt > self.wait_max_s:
            self.wait_max_s = dt

    @property
    def avg_us(self) -> float:
        return 1e6 * self.total_s / self.buffers if self.buffers else 0.0

    def as_dict(self) -> dict:
        return {"buffers": self.buffers, "proctime_avg_us": self.avg_us,
                "proctime_max_us": 1e6 * self.max_s,
                "proctime_total_s": self.total_s,
                "queue_wait_avg_us": (1e6 * self.wait_s / self.buffers
                                      if self.buffers else 0.0),
                "queue_wait_max_us": 1e6 * self.wait_max_s,
                "timer_fires": self.timer_fires,
                "dropped": self.dropped,
                "queue_peak": self.queue_peak,
                "errors": self.errors,
                "retries": self.retries,
                "skipped": self.skipped,
                "degraded": self.degraded,
                "watchdog_warnings": self.watchdog_warnings,
                "event_errors": self.event_errors}


class PipelineRunner:
    """Runs a negotiated pipeline: one worker thread per element.

    Fault-tolerance knobs (docs/robustness.md):

    - per-element `error-policy` properties are enforced in `_work`
      (fail | skip | retry:N[:backoff_ms] | degrade);
    - `max_consecutive_errors` (default from config, 100): after that
      many policy-handled errors with no successful process() anywhere
      in the pipeline, the run escalates to failure — a poison stream
      under skip/retry still dies loudly instead of spinning forever.
      0 disables escalation;
    - `watchdog` (default on): a monitor thread that flags elements
      whose process() exceeds `stall_budget_s` and input queues pinned
      at capacity beyond `queue_stall_budget_s`. `watchdog_action`
      "warn" emits structured warnings + stats; "fail" tears the
      pipeline down with WatchdogStall — the "fail loud, never hang"
      promise extended from exceptions to hangs.
    """

    def __init__(self, pipeline: Pipeline, queue_capacity: Optional[int] = None,
                 optimize: bool = True, trace=False,
                 max_consecutive_errors: Optional[int] = None,
                 watchdog: Optional[bool] = None,
                 stall_budget_s: Optional[float] = None,
                 queue_stall_budget_s: Optional[float] = None,
                 watchdog_action: Optional[str] = None,
                 chain_fusion: Optional[bool] = None,
                 device_segments: Optional[bool] = None,
                 max_inflight: Optional[int] = None,
                 compiled_loop: Optional[bool] = None,
                 compiled_loop_window: Optional[int] = None,
                 compiled_loop_arm: Optional[int] = None):
        self.pipeline = pipeline
        self._optimize = optimize
        # trace=False → NULL_TRACER (hot path pays one attribute load);
        # trace=True → fresh Tracer; or pass a Tracer/NullTracer directly
        if hasattr(trace, "active"):
            self.tracer = trace
        elif trace:
            self.tracer = Tracer()
        else:
            self.tracer = NULL_TRACER
        cap = queue_capacity or get_config().get_int("runtime", "queue_capacity", 4)
        self._cap = max(1, cap)
        self._queues: Dict[str, Channel] = {}
        # chain fusion: head name -> ordered member list, member name ->
        # head name (built in start(), after transform fusion)
        if chain_fusion is None:
            chain_fusion = get_config().get_bool(
                "runtime", "chain_fusion", True)
        self._chain_fusion = bool(chain_fusion)
        # device segments: fuse filter→transform→filter runs into one
        # composed jit before transform fusion (graph/optimize)
        if device_segments is None:
            device_segments = get_config().get_bool(
                "runtime", "device_segments", True)
        self._device_segments = bool(device_segments)
        # async-dispatch window depth for DEVICE_RESIDENT elements
        # (0 = sync after every dispatch)
        if max_inflight is None:
            max_inflight = get_config().get_int(
                "runtime", "max_inflight", 8)
        self._max_inflight = max(0, max_inflight)
        # compiled steady-state loop (scheduler bypass): arm after N
        # identical-signature frames, then sweep ≤ K queued frames into
        # one jitted lax.scan window per iteration
        if compiled_loop is None:
            compiled_loop = get_config().get_bool(
                "runtime", "compiled_loop", True)
        self._compiled_loop = bool(compiled_loop)
        if compiled_loop_window is None:
            compiled_loop_window = get_config().get_int(
                "runtime", "compiled_loop_window", 8)
        self._loop_window = max(2, compiled_loop_window)
        if compiled_loop_arm is None:
            compiled_loop_arm = get_config().get_int(
                "runtime", "compiled_loop_arm", 4)
        self._loop_arm = max(1, compiled_loop_arm)
        # element name -> LoopStats; populated in _work only for
        # elements that actually run with the loop enabled
        self._loop_stats: Dict[str, LoopStats] = {}
        self._chains: Dict[str, List[Element]] = {}
        self._chain_member: Dict[str, str] = {}
        # built in start(), AFTER transform fusion removed elements —
        # fused-away elements must not appear as zero-count stats rows
        self._stats: Dict[str, ElementStats] = {}
        self._threads: List[threading.Thread] = []
        self._stop_evt = threading.Event()
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._started = False
        self._route: Dict[Tuple[str, int], Link] = {}
        # -- fault-tolerance state -----------------------------------------
        cfg = get_config()
        if max_consecutive_errors is None:
            max_consecutive_errors = cfg.get_int(
                "runtime", "max_consecutive_errors", 100)
        self._max_consec = max(0, max_consecutive_errors)
        # shared run-level counter: reset by ANY successful process();
        # plain int ops under the GIL — a lost race costs one count,
        # never a wrong escalation by more than a few buffers
        self._consec_errors = 0
        if watchdog is None:
            watchdog = cfg.get_bool("runtime", "watchdog", True)
        self._watchdog_enabled = bool(watchdog)
        if stall_budget_s is None:
            stall_budget_s = cfg.get_float(
                "runtime", "stall_budget_s", 30.0)
        self._stall_budget_s = max(0.01, stall_budget_s)
        if queue_stall_budget_s is None:
            queue_stall_budget_s = cfg.get_float(
                "runtime", "queue_stall_budget_s", self._stall_budget_s)
        self._queue_stall_budget_s = max(0.01, queue_stall_budget_s)
        action = watchdog_action or cfg.get(
            "runtime", "watchdog_action", "warn") or "warn"
        if action not in ("warn", "fail"):
            raise PipelineError(
                f"watchdog_action must be 'warn' or 'fail', got {action!r}")
        self._watchdog_action = action
        self._watchdog_thread: Optional[threading.Thread] = None
        # element name -> monotonic instant its worker entered process()
        # (or flush()); written/cleared by the worker, read by the
        # watchdog — GIL-atomic dict ops, no lock needed
        self._inflight: Dict[str, float] = {}
        # watchdog incident bookkeeping — pruned the moment an element
        # (or its queue) recovers, so the dicts stay bounded by the set
        # of *currently* wedged elements, not everything ever warned
        self._wd_warned_proc: Dict[str, float] = {}
        self._wd_q_full_since: Dict[str, float] = {}
        self._wd_warned_q: Dict[str, float] = {}
        # admission-queue incidents (serversrc): name -> (since,
        # replied-at-arm) / name -> since-warned; same prune-on-recovery
        # discipline as the other _wd_* dicts
        self._wd_adm_since: Dict[str, tuple] = {}
        self._wd_warned_adm: Dict[str, float] = {}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "PipelineRunner":
        if self._started:
            raise PipelineError("runner already started")
        pipe = self.pipeline
        if not pipe._negotiated:
            if self._optimize:
                from nnstreamer_tpu.graph.optimize import (fuse_segments,
                                                           fuse_transforms)

                # segments first: the head's pre chain, the post chain
                # behind the last member and a trailing device decoder
                # are then absorbed by the ordinary transform pass
                if self._device_segments:
                    fuse_segments(pipe)
                fuse_transforms(pipe)
            pipe.negotiate()
        for name in pipe.elements:
            self._stats.setdefault(name, ElementStats())
        for e in pipe.elements.values():
            e._event_router = self._route_upstream
            # tracer handed down before start() so elements can forward
            # it further (tensor_filter → backend invoke/compile spans)
            e._tracer = self.tracer
            # teardown signal, so blocking elements (repo puts, injected
            # delays) can abort instead of riding out their timeouts
            e._stop_evt = self._stop_evt
            e.start()
        for l in pipe.links:
            self._route[(l.src.name, l.src_pad)] = l
        self._build_chains()
        # only elements that receive buffers over a link need a channel:
        # mid-chain members are fed by direct call-through
        for e in pipe.elements.values():
            if not isinstance(e, SourceElement) \
                    and e.name not in self._chain_member:
                self._queues[e.name] = Channel(self._cap)
        for e in pipe.elements.values():
            if isinstance(e, SourceElement):
                t = threading.Thread(target=self._pump, args=(e,),
                                     name=f"src:{e.name}", daemon=True)
            elif e.name in self._chains:
                t = threading.Thread(target=self._chain_work,
                                     args=(self._chains[e.name],),
                                     name=f"chain:{e.name}", daemon=True)
            elif e.name in self._chain_member:
                continue
            else:
                t = threading.Thread(target=self._work, args=(e,),
                                     name=f"elem:{e.name}", daemon=True)
            self._threads.append(t)
        self._started = True
        for t in self._threads:
            t.start()
        if self._watchdog_enabled:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop,
                name=f"watchdog:{pipe.name}", daemon=True)
            self._watchdog_thread.start()
        return self

    #: how long wait() gives remaining workers to drain once a worker
    #: error is already recorded and no caller deadline bounds the join
    _error_drain_grace_s = 5.0

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every element finished (EOS fully propagated)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            while t.is_alive():
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.stop()
                        if self._error is not None:
                            # the hang is a symptom: a worker already
                            # failed and a peer never drained — surface
                            # the root cause, not a bare timeout that
                            # swallows it
                            raise StreamError(
                                f"pipeline {self.pipeline.name!r} failed: "
                                f"{self._error} (thread {t.name} then did "
                                f"not finish within {timeout}s)"
                            ) from self._error
                        raise StreamError(
                            f"pipeline {self.pipeline.name!r} did not "
                            f"finish within {timeout}s (thread {t.name} "
                            f"still running)"
                        )
                    t.join(min(0.2, remaining))
                elif self._error is not None:
                    # no caller deadline, but the pipeline already
                    # failed: give the stragglers a bounded grace, then
                    # leak them (they are daemons) rather than hang the
                    # caller forever behind a stuck process()
                    self.stop()
                    t.join(self._error_drain_grace_s)
                    if t.is_alive():
                        log.warning(
                            "pipeline %r: thread %s still running %.0fs "
                            "after pipeline failure — leaking it (daemon "
                            "thread; likely stuck in process())",
                            self.pipeline.name, t.name,
                            self._error_drain_grace_s)
                    break
                else:
                    t.join(0.2)
        if self._error is not None:
            raise StreamError(
                f"pipeline {self.pipeline.name!r} failed: {self._error}"
            ) from self._error

    def stop(self) -> None:
        """Request teardown; safe to call multiple times."""
        self._stop_evt.set()
        # unblock sources stuck in generate() (e.g. appsrc waiting for push)
        for e in self.pipeline.elements.values():
            if isinstance(e, SourceElement):
                try:
                    e.interrupt()
                except Exception:
                    log.exception("error interrupting %s", e.name)
        # unblock workers waiting on get() and producers blocked on a
        # full channel — close() wakes every waiter unconditionally, so
        # the wakeup cannot be lost the way put_nowait-on-full used to be
        for ch in self._queues.values():
            ch.close()
        for e in self.pipeline.elements.values():
            try:
                e.stop()
            except Exception:  # teardown must not mask the first error
                log.exception("error stopping %s", e.name)
        wt = self._watchdog_thread
        if wt is not None and wt is not threading.current_thread():
            wt.join(2.0)  # exits on the next poll tick (stop_evt set)
            if wt.is_alive():
                log.warning("watchdog thread %s did not stop within 2s; "
                            "leaking it (daemon thread)", wt.name)

    def run(self, timeout: Optional[float] = None) -> None:
        self.start()
        try:
            self.wait(timeout)
        finally:
            self.stop()

    def stats(self) -> Dict[str, dict]:
        """Per-element proctime/buffer counters (tracing, §5.1).

        tensor_filter elements additionally expose their own
        latency_us/throughput props (the reference's two counters)."""
        out = {}
        for name, s in self._stats.items():
            d = s.as_dict()
            e = self.pipeline.elements.get(name)
            if hasattr(e, "latency_us"):
                d["invoke_latency_us"] = e.latency_us
                d["invoke_throughput"] = e.throughput
            # element-specific counters (tensor_batch occupancy histogram
            # + flush reasons, …) merge into the same stats row
            extra = getattr(e, "extra_stats", None)
            if extra is not None:
                d.update(extra())
            ls = self._loop_stats.get(name)
            if ls is not None:
                # loop_entries / compiled_steps / loop_bails{cause}
                d.update(ls.snapshot())
            out[name] = d
        return out

    def report(self) -> str:
        """Human-readable observability report: per-element proctime
        table (sorted by total processing time, heaviest first), per-link
        queue high-water marks, and — when tracing is on — interlatency
        percentiles per element with sinks marked (the sink rows are the
        end-to-end pipeline latency) and backend compile/cache counters.
        """
        st = self.stats()
        lines = [f"pipeline {self.pipeline.name!r} — element report",
                 "",
                 f"{'element':<22} {'buffers':>8} {'total ms':>9} "
                 f"{'avg µs':>9} {'max µs':>9} {'wait µs':>9} "
                 f"{'q.peak':>6} {'drop':>5} {'timer':>6}"]
        for name, d in sorted(st.items(),
                              key=lambda kv: -kv[1]["proctime_total_s"]):
            lines.append(
                f"{name:<22} {d['buffers']:>8} "
                f"{d['proctime_total_s'] * 1e3:>9.2f} "
                f"{d['proctime_avg_us']:>9.1f} {d['proctime_max_us']:>9.1f} "
                f"{d['queue_wait_avg_us']:>9.1f} {d['queue_peak']:>6} "
                f"{d['dropped']:>5} {d['timer_fires']:>6}")
        if self._chains:
            lines.append("")
            lines.append("fused chains (one worker thread, direct "
                         "call-through):")
            for chain in self._chains.values():
                lines.append("  " + " → ".join(m.name for m in chain))
        segs = self.device_segments()
        if segs:
            lines.append("")
            lines.append("device segments (one composed dispatch per "
                         "segment):")
            for s in segs:
                lines.append(
                    f"  {s['segment']}: {s['size']} filters, "
                    f"{'composed jit' if s['composed'] else 'host fallback'}")
        lines.append("")
        lines.append(f"queue high-water (capacity {self._cap}):")
        for l in self.pipeline.links:
            d = st.get(l.dst.name)
            if d is None or l.dst.name in self._chain_member:
                continue     # mid-chain links have no queue at all
            lines.append(f"  {l.src.name} → {l.dst.name}: "
                         f"peak {d['queue_peak']}/{self._cap}")
        loops = [(name, ls) for name, ls in sorted(self._loop_stats.items())
                 if ls.entries or ls.steps or ls.bails]
        if loops:
            lines.append("")
            lines.append("compiled steady-state windows (scheduler "
                         "bypass, [runtime] compiled_loop):")
            for name, ls in loops:
                total = st.get(name, {}).get("buffers", 0)
                share = 100.0 * ls.steps / total if total else 0.0
                bails = " ".join(f"{c}={ls.bails[c]}"
                                 for c in sorted(ls.bails)) or "none"
                lines.append(
                    f"  {name}: windows={ls.entries} "
                    f"compiled_frames={ls.steps} ({share:.0f}% of "
                    f"{total}) bails: {bails}")
        rob = [(name, d) for name, d in sorted(st.items())
               if any(d.get(k) for k in
                      ("errors", "retries", "skipped", "degraded",
                       "watchdog_warnings", "event_errors"))]
        if rob:
            lines.append("")
            lines.append("robustness (error-policy / watchdog counters):")
            for name, d in rob:
                lines.append(
                    f"  {name}: errors={d['errors']} "
                    f"retries={d['retries']} skipped={d['skipped']} "
                    f"degraded={d['degraded']} "
                    f"watchdog={d['watchdog_warnings']} "
                    f"event_errors={d['event_errors']}")
        tr = self.tracer
        if tr.active:
            inter = tr.interlatency()
            if inter:
                sinks = {e.name for e in self.pipeline.elements.values()
                         if not self.pipeline.links_from(e)}
                lines.append("")
                lines.append("interlatency source → element (ms):")
                lines.append(f"  {'element':<22} {'n':>6} {'p50':>8} "
                             f"{'p95':>8} {'p99':>8} {'max':>8}")
                for name, r in sorted(inter.items(),
                                      key=lambda kv: kv[1]["p50_ms"]):
                    mark = " (sink)" if name in sinks else ""
                    lines.append(
                        f"  {name + mark:<22} {r['n']:>6} "
                        f"{r['p50_ms']:>8.3f} {r['p95_ms']:>8.3f} "
                        f"{r['p99_ms']:>8.3f} {r['max_ms']:>8.3f}")
            forced = tr.forced_syncs()
            gauges = tr.inflight_gauges()
            if forced or gauges:
                lines.append("")
                lines.append("async dispatch (forced syncs / in-flight "
                             "window peaks):")
                for name, n in sorted(forced.items()):
                    lines.append(f"  {name}: forced_syncs={n}")
                for name, g in sorted(gauges.items()):
                    lines.append(f"  {name}: inflight_peak={g['peak']} "
                                 f"(window {self._max_inflight})")
            if tr.events_dropped:
                lines.append("")
                lines.append(f"note: event ring wrapped, "
                             f"{tr.events_dropped} oldest events dropped")
        backend_rows = [
            (name, {k: v for k, v in d.items() if k.startswith("backend_")})
            for name, d in st.items()]
        backend_rows = [(n, b) for n, b in backend_rows if b]
        if backend_rows:
            lines.append("")
            lines.append("backend counters:")
            for name, b in backend_rows:
                kv = " ".join(f"{k[len('backend_'):]}={v}"
                              for k, v in sorted(b.items()))
                lines.append(f"  {name}: {kv}")
        swaps = tr.swap_events() if tr.active else []
        if swaps:
            lines.append("")
            lines.append("model swaps (store:// epoch adoptions):")
            for name, t, args in swaps:
                lines.append(
                    f"  {name}: {args.get('model', '?')} "
                    f"v{args.get('from_version', '?')} → "
                    f"v{args.get('to_version', '?')} "
                    f"epoch={args.get('epoch', '?')} "
                    f"prewarmed={args.get('prewarmed', 0)}")
        return "\n".join(lines)

    # -- internals ---------------------------------------------------------
    def _route_upstream(self, origin: Element, event: dict) -> None:
        """Walk the link graph upstream from `origin`, offering `event`
        to each element until consumed (upstream QoS event path)."""
        seen = {origin.name}
        frontier = [origin]
        while frontier:
            e = frontier.pop()
            for l in self.pipeline.links_to(e):
                u = l.src
                if u.name in seen:
                    continue
                seen.add(u.name)
                try:
                    consumed = u.handle_upstream_event(event)
                except Exception:
                    # a broken handler must not silently terminate the
                    # walk: treat the event as NOT consumed so it keeps
                    # propagating toward the sources, and count the
                    # failure where it happened
                    log.exception("upstream event failed at %s", u.name)
                    stats = self._stats.get(u.name)
                    if stats is not None:
                        stats.event_errors += 1
                    consumed = False
                if not consumed:
                    frontier.append(u)

    # -- chain fusion ------------------------------------------------------
    def _chain_eligible(self, e: Element) -> bool:
        """Can `e` run as a member of a fused chain? Only cheap linear
        call-through elements qualify: exactly one in-link and one
        out-link (no fan-in/fan-out, which excludes sources and sinks),
        fail-fast error policy (skip/retry/degrade need the per-element
        worker's policy loop), no timer deadlines (a fused member cannot
        be woken independently of the chain head), and not opted out via
        CHAIN_FUSABLE (tensor_filter: its thread IS the async dispatch
        overlap)."""
        if isinstance(e, SourceElement) or not e.CHAIN_FUSABLE:
            return False
        if e.error_policy.kind != "fail":
            return False
        if len(self.pipeline.links_to(e)) != 1 \
                or len(self.pipeline.links_from(e)) != 1:
            return False
        cls = type(e)
        if cls.next_deadline is not Element.next_deadline \
                or cls.on_timer is not Element.on_timer:
            return False
        return True

    def _build_chains(self) -> None:
        """Group maximal linear runs of eligible elements into fused
        chains. Runs in start() after transform fusion, so fused-away
        transforms never appear as chain members."""
        if not self._chain_fusion:
            return
        pipe = self.pipeline
        elig = {e.name for e in pipe.elements.values()
                if self._chain_eligible(e)}
        for e in pipe.elements.values():
            if e.name not in elig:
                continue
            # heads are eligible elements whose single upstream is not
            # eligible (an eligible upstream's only out-link feeds us,
            # so it extends the same chain and we are mid-chain)
            if pipe.links_to(e)[0].src.name in elig:
                continue
            chain = [e]
            cur = e
            while True:
                nxt = pipe.links_from(cur)[0].dst
                if nxt.name not in elig:
                    break
                chain.append(nxt)
                cur = nxt
            if len(chain) < 2:
                continue          # nothing to fuse with
            self._chains[e.name] = chain
            for m in chain[1:]:
                self._chain_member[m.name] = e.name
            log.debug("pipeline %r: chain-fused %s (one worker thread)",
                      pipe.name, " → ".join(m.name for m in chain))

    def fused_chains(self) -> List[List[str]]:
        """Element-name chains the scheduler fused (after start())."""
        return [[m.name for m in chain]
                for chain in self._chains.values()]

    def device_segments(self) -> List[dict]:
        """Device segments formed by `fuse_segments` (after start()):
        one dict per surviving head filter with absorbed members —
        {head, segment (joined member names), size, composed} where
        composed=False means the backend declined and the member stages
        run host-side (bit-identical results, no single-dispatch win)."""
        out = []
        for e in self.pipeline.elements.values():
            seg = getattr(e, "segment_name", None)
            if seg is None or not seg():
                continue
            out.append({
                "head": e.name,
                "segment": seg(),
                "size": 1 + len(e._members),
                "composed": bool(e._segment_in_backend),
            })
        return out

    def _chain_work(self, chain: List[Element]) -> None:
        """Worker loop for a fused chain: one channel read at the head,
        then direct call-through over every member — no thread or
        channel hop between them."""
        head, tail = chain[0], chain[-1]
        ch = self._queues[head.name]
        head_stats = self._stats[head.name]
        tr = self.tracer
        try:
            while not self._stop_evt.is_set():
                msg, depth = ch.get()
                if msg is CLOSED:     # teardown wakeup
                    return
                pad, item, t_enq = msg
                if tr.active:
                    tr.dequeue(head.name, depth, time.perf_counter())
                if item is EOS:
                    # heads have exactly one in-link, so the first EOS
                    # completes the chain: flush members in order (each
                    # flush emission still flows through the rest of the
                    # chain, preserving unfused EOS/flush ordering)
                    self._chain_flush(chain)
                    self._broadcast_eos(tail)
                    return
                if t_enq:
                    head_stats.record_wait(time.perf_counter() - t_enq)
                self._chain_deliver(chain, 0, pad, item)
        except _ChainFailure as cf:
            self._fail(cf.elem, cf.exc)
            try:
                self._broadcast_eos(tail)
            except Exception:
                pass
        except Exception as e:
            self._fail(head, e)
            try:
                self._broadcast_eos(tail)
            except Exception:
                pass

    def _chain_deliver(self, chain: List[Element], start_idx: int,
                       pad: int, item) -> None:
        """Push one buffer through chain[start_idx:] by direct calls.
        Depth-first over emissions so buffer order at the tail matches
        the unfused schedule (all descendants of an element's first
        emission drain before its second). Stats, watchdog stamps and
        trace spans stay attributed to the member that did the work."""
        tr = self.tracer
        last = len(chain) - 1
        stack = [(start_idx, pad, item)]
        while stack:
            i, pad, buf = stack.pop()
            elem = chain[i]
            t0 = time.perf_counter()
            self._inflight[elem.name] = time.monotonic()
            try:
                emissions = elem.process(pad, buf)
            except Exception as exc:
                raise _ChainFailure(elem, exc) from exc
            finally:
                self._inflight.pop(elem.name, None)
            t1 = time.perf_counter()
            self._stats[elem.name].record(t1 - t0)
            self._consec_errors = 0
            if tr.active:
                tr.record_process(elem.name, buf, t0, t1)
            if i == last:
                for sp, b in emissions:
                    self._emit(elem, sp, b)
                continue
            nxt = chain[i + 1].name
            pending = []
            for sp, b in emissions:
                link = self._route[(elem.name, sp)]
                if link.dst.name == nxt:
                    pending.append((i + 1, link.dst_pad, b))
                else:          # defensive: members have one out-link
                    self._emit(elem, sp, b)
            stack.extend(reversed(pending))

    def _chain_flush(self, chain: List[Element]) -> None:
        """EOS drain for a fused chain: flush members head→tail, each
        member's flush emissions flowing through the remaining members
        before those flush — exactly the order the unfused cascade
        produces."""
        tr = self.tracer
        last = len(chain) - 1
        for i, elem in enumerate(chain):
            t0 = time.perf_counter()
            self._inflight[elem.name] = time.monotonic()
            try:
                emissions = elem.flush()
            except Exception as exc:
                raise _ChainFailure(elem, exc) from exc
            finally:
                self._inflight.pop(elem.name, None)
            if tr.active:
                t1 = time.perf_counter()
                tr.record_flush(elem.name, t0, t1)
                tr.record_eos(elem.name, t1)
            if i == last:
                for sp, b in emissions:
                    self._emit(elem, sp, b)
                continue
            nxt = chain[i + 1].name
            for sp, b in emissions:
                link = self._route[(elem.name, sp)]
                if link.dst.name == nxt:
                    self._chain_deliver(chain, i + 1, link.dst_pad, b)
                else:
                    self._emit(elem, sp, b)

    # -- error policies ----------------------------------------------------
    def _process_with_policy(self, elem: Element, stats: ElementStats,
                             policy, pad: int, item, tr):
        """Run elem.process under a non-fail error policy.

        Returns the emissions list, or None when the buffer was consumed
        by the policy (skipped, degraded, or lost to teardown). Raises
        only for escalation (max_consecutive_errors) — which the worker
        loop's outer handler turns into pipeline failure — or teardown.
        """
        from nnstreamer_tpu.core.errors import CircuitOpenError

        attempts = 0
        while True:
            self._inflight[elem.name] = time.monotonic()
            try:
                return elem.process(pad, item)
            except Exception as e:
                stats.errors += 1
                if tr.active:
                    tr.record_error(elem.name, type(e).__name__,
                                    time.perf_counter(),
                                    policy=policy.kind, pts=getattr(
                                        item, "pts", None))
                self._note_error(elem, e)   # may raise (escalation)
                # a circuit breaker short-circuit is by definition not
                # transient — retrying it just burns the backoff budget
                retryable = (policy.kind == "retry"
                             and attempts < policy.retries
                             and not isinstance(e, CircuitOpenError))
                if retryable:
                    attempts += 1
                    stats.retries += 1
                    delay_s = policy.backoff_ms * (2 ** (attempts - 1)) / 1e3
                    log.debug(
                        "element %s: process failed (%s); retry %d/%d "
                        "in %.0fms", elem.name, e, attempts,
                        policy.retries, delay_s * 1e3)
                    if delay_s and self._stop_evt.wait(delay_s):
                        stats.dropped += 1    # teardown mid-backoff
                        return None
                    continue
                if policy.kind == "degrade":
                    fb = elem.fallback_src_pad
                    if fb is not None:
                        stats.degraded += 1
                        log.warning(
                            "element %s: process failed on buffer pts=%s "
                            "(%s); degrading — routing input to fallback "
                            "pad %d", elem.name,
                            getattr(item, "pts", None), e, fb)
                        self._emit(elem, fb, item)
                        return None
                stats.skipped += 1
                log.warning(
                    "element %s: process failed on buffer pts=%s (%s); "
                    "%s — buffer dropped", elem.name,
                    getattr(item, "pts", None), e,
                    "retry budget exhausted" if policy.kind == "retry"
                    else f"error-policy={policy.kind}")
                return None
            finally:
                self._inflight.pop(elem.name, None)

    def _note_error(self, elem: Element, exc: BaseException) -> None:
        """Track run-level consecutive errors; escalate to failure when
        the pipeline makes no progress between errors (poison stream)."""
        self._consec_errors += 1
        if self._max_consec and self._consec_errors >= self._max_consec:
            raise StreamError(
                f"element {elem.name}: {self._consec_errors} consecutive "
                f"errors with no successful buffer anywhere in the "
                f"pipeline (max_consecutive_errors={self._max_consec}) — "
                f"escalating to failure; last error: {exc}"
            ) from exc

    # -- watchdog ----------------------------------------------------------
    def _watchdog_loop(self) -> None:
        """Flags elements stuck in process() beyond the stall budget and
        input queues pinned at capacity beyond theirs. One warning per
        incident (per stuck call / per contiguous full period), counted
        in the element's stats and traced; watchdog_action='fail' also
        tears the pipeline down with WatchdogStall."""
        poll = max(0.02, min(1.0, min(self._stall_budget_s,
                                      self._queue_stall_budget_s) / 4.0))
        while not self._stop_evt.wait(poll):
            if self._watchdog_scan(time.monotonic()):
                return

    def _watchdog_scan(self, now: float) -> bool:
        """One watchdog pass at monotonic instant `now`; True when a
        watchdog_action='fail' teardown fired (the loop must exit).
        Separated from the loop so tests can drive it with synthetic
        clocks; bookkeeping lives on the runner (`_wd_*` dicts) and is
        pruned the moment an element/queue recovers, so long-running
        pipelines never grow it monotonically."""
        from nnstreamer_tpu.core.errors import WatchdogStall

        budget = self._stall_budget_s
        q_budget = self._queue_stall_budget_s
        tr = self.tracer
        warned_proc = self._wd_warned_proc
        q_full_since = self._wd_q_full_since
        warned_q = self._wd_warned_q
        # prune bookkeeping for recovered elements: a stale warned_proc
        # entry means that stuck call returned (or a new one started —
        # a different stamp re-arms the warning anyway)
        inflight = dict(self._inflight)
        for name in list(warned_proc):
            if inflight.get(name) != warned_proc[name]:
                del warned_proc[name]
        for name, t0 in inflight.items():
            stalled = now - t0
            if stalled <= budget or warned_proc.get(name) == t0:
                continue
            warned_proc[name] = t0
            stats = self._stats.get(name)
            if stats is not None:
                stats.watchdog_warnings += 1
            log.warning(
                "watchdog: element %s has been inside process()/"
                "flush() for %.2fs (stall budget %.2fs)",
                name, stalled, budget)
            if tr.active:
                tr.record_watchdog(name, "stall", time.perf_counter(),
                                   stalled_s=round(stalled, 3),
                                   budget_s=budget)
            if self._watchdog_action == "fail":
                elem = self.pipeline.elements.get(name)
                self._fail(elem, WatchdogStall(
                    f"element {name} exceeded its stall budget: "
                    f"process() has not returned for {stalled:.2f}s "
                    f"(budget {budget:.2f}s)"))
                return True
        for name, ch in self._queues.items():
            if not ch.full():
                # recovered: drop the whole incident record so the
                # dicts stay bounded by currently-wedged queues only
                q_full_since.pop(name, None)
                warned_q.pop(name, None)
                continue
            since = q_full_since.setdefault(name, now)
            full_for = now - since
            if full_for <= q_budget or warned_q.get(name) == since:
                continue
            warned_q[name] = since
            stats = self._stats.get(name)
            if stats is not None:
                stats.watchdog_warnings += 1
            log.warning(
                "watchdog: input queue of %s has been at capacity "
                "(%d) for %.2fs (budget %.2fs) — the element is not "
                "draining; upstream is blocked", name, self._cap,
                full_for, q_budget)
            if tr.active:
                tr.record_watchdog(name, "queue", time.perf_counter(),
                                   full_for_s=round(full_for, 3),
                                   budget_s=q_budget,
                                   capacity=self._cap)
            if self._watchdog_action == "fail":
                elem = self.pipeline.elements.get(name)
                self._fail(elem, WatchdogStall(
                    f"input queue of element {name} stayed at "
                    f"capacity ({self._cap}) for {full_for:.2f}s "
                    f"(budget {q_budget:.2f}s)"))
                return True
        # wedged admission: a serversrc whose admission queue sits
        # pinned at max_pending with ZERO replies for the queue stall
        # budget. Depth alone is healthy under overload (BUSY at the
        # door is the design); depth pinned AND no reply progress means
        # the service plane behind the queue is gone while clients
        # still burn their timeouts — exactly what a supervisor must
        # hear about before the retries pile up.
        adm_since = self._wd_adm_since
        warned_adm = self._wd_warned_adm
        for name, elem in list(self.pipeline.elements.items()):
            probe = getattr(elem, "admission_counters", None)
            if probe is None:
                continue
            try:
                c = probe()
            except Exception:
                continue
            pinned = c["depth"] >= c["max_pending"]
            if not pinned:
                adm_since.pop(name, None)
                warned_adm.pop(name, None)
                continue
            since, replied0 = adm_since.setdefault(
                name, (now, c["replied"]))
            if c["replied"] != replied0:
                # progress: re-arm the incident at the new reply count
                adm_since[name] = (now, c["replied"])
                warned_adm.pop(name, None)
                continue
            wedged_for = now - since
            if wedged_for <= q_budget or warned_adm.get(name) == since:
                continue
            warned_adm[name] = since
            stats = self._stats.get(name)
            if stats is not None:
                stats.watchdog_warnings += 1
            log.warning(
                "watchdog: admission queue of %s wedged — depth pinned "
                "at max_pending (%d) with zero replies for %.2fs "
                "(budget %.2fs); the service plane is not draining",
                name, c["max_pending"], wedged_for, q_budget)
            if tr.active:
                tr.record_watchdog(
                    name, "wedged-admission", time.perf_counter(),
                    wedged_for_s=round(wedged_for, 3),
                    budget_s=q_budget, max_pending=c["max_pending"],
                    replied=c["replied"])
            if self._watchdog_action == "fail":
                self._fail(elem, WatchdogStall(
                    f"wedged-admission: admission queue of {name} "
                    f"stayed pinned at max_pending "
                    f"({c['max_pending']}) with zero replies for "
                    f"{wedged_for:.2f}s (budget {q_budget:.2f}s)"))
                return True
        return False

    def _fail(self, elem: Element, exc: BaseException) -> None:
        with self._error_lock:
            if self._error is None:
                self._error = exc
        log.error("element %s failed: %s", elem.name, exc)
        self._stop_evt.set()
        for ch in self._queues.values():
            ch.close()

    def _emit(self, elem: Element, src_pad: int, item) -> None:
        link = self._route.get((elem.name, src_pad))
        if link is None:
            raise PipelineError(
                f"element {elem.name} emitted on unlinked src pad {src_pad}"
            )
        if link.dst.WANTS_HOST and isinstance(item, TensorBuffer) \
                and item.on_device:
            # start the D2H transfer now; the consumer's to_host() then
            # overlaps with compute of other in-flight frames
            item.prefetch_host()
        ch = self._queues[link.dst.name]
        t_enq = time.perf_counter()
        tr = self.tracer
        # blocking put: wakes the consumer immediately, parks this
        # producer without polling while the channel is full, and
        # returns the post-append depth measured under the channel's
        # own lock — the always-on queue_peak high-water mark costs no
        # extra qsize() lock acquisition
        depth = ch.put((link.dst_pad, item, t_enq))
        if depth is not None:
            dst_stats = self._stats.get(link.dst.name)
            if dst_stats is not None and depth > dst_stats.queue_peak:
                dst_stats.queue_peak = depth
            if tr.active:
                tr.enqueue(link.dst.name, depth, time.perf_counter())
            return
        # the channel closed (teardown/failure) before the put landed:
        # the buffer is lost. Count it so teardown/failure losses are
        # visible in stats() instead of vanishing silently (EOS is not
        # a payload — no loss to count).
        if item is not EOS:
            stats = self._stats.get(elem.name)
            if stats is not None:
                stats.dropped += 1
            log.debug("teardown dropped a buffer from %s -> %s (pts=%s)",
                      elem.name, link.dst.name, getattr(item, "pts", None))
            if tr.active:
                tr.record_drop(elem.name, time.perf_counter())

    def _broadcast_eos(self, elem: Element) -> None:
        for l in self.pipeline.links_from(elem):
            self._emit(elem, l.src_pad, EOS)

    def _pump(self, src: SourceElement) -> None:
        tr = self.tracer
        try:
            for buf in src.generate():
                if self._stop_evt.is_set():
                    break
                if tr.active:
                    # interlatency origin: stamp the pipeline-entry time
                    tr.source_emit(src.name, buf, time.perf_counter())
                self._emit(src, 0, buf)
            self._broadcast_eos(src)
        except Exception as e:
            self._fail(src, e)
            try:
                self._broadcast_eos(src)
            except Exception:
                pass

    def _run_compiled_window(self, elem, ch: Channel, stats: ElementStats,
                             lstats: LoopStats,
                             detector: SteadyStateDetector,
                             pending: deque, window, tr, pad: int, item,
                             t_enq: float, sig) -> bool:
        """One compiled steady-state window attempt, starting at `item`
        (detector already armed). Returns True when the frame was fully
        consumed here — a window ran, or its frames were handed back
        via `pending` for per-frame re-run; False when the caller must
        process `item` through the ordinary per-frame path (entry bail,
        or fewer than two matching frames queued).

        Stats reconcile exactly on every path: a K-frame window records
        K buffers of dt/K each (plus per-frame queue waits and tracer
        process spans), and an errored window re-runs its frames
        per-frame so the error policy lands on the precise frame that
        faulted.
        """
        now = time.perf_counter()
        # entry bails: state the jitted window must not bake in. Both
        # are transient — the detector stays armed and the very next
        # frame retries (swap adoption / timer fire happen per-frame).
        if elem.swap_pending():
            lstats.bail("swap")
            if tr.active:
                tr.record_loop_bail(elem.name, "swap", now)
            return False
        if elem.next_deadline() is not None:
            lstats.bail("timer")
            if tr.active:
                tr.record_loop_bail(elem.name, "timer", now)
            return False
        batch = [(pad, item, t_enq)]
        eos_msg = None
        parked = None
        while len(batch) < self._loop_window:
            m, d = ch.get_nowait()
            if m is TIMED_OUT or m is CLOSED:
                break      # channel empty/closed — run with what we have
            if tr.active:
                tr.dequeue(elem.name, d, time.perf_counter())
            p2, it2, _te2 = m
            if it2 is EOS:
                # the partial window runs first, then the EOS cascades
                # via the ordinary path (flush + async-window drain)
                eos_msg = m
                lstats.bail("eos")
                if tr.active:
                    tr.record_loop_bail(elem.name, "eos",
                                        time.perf_counter())
                detector.reset()
                break
            if p2 != pad or frame_signature(it2) != sig:
                # divergent frame: parked for per-frame processing
                # after this window; the streak restarts behind it
                parked = m
                lstats.bail("shape")
                if tr.active:
                    tr.record_loop_bail(elem.name, "shape",
                                        time.perf_counter())
                detector.reset()
                break
            batch.append(m)
        if len(batch) < 2:
            # a window of one is just the per-frame path with extra
            # steps — hand everything back
            if parked is not None:
                pending.append(parked)
            if eos_msg is not None:
                pending.append(eos_msg)
            return False
        # power-of-two round-down: every distinct K is its own jitted
        # scan bucket, and queue depth would otherwise mint one per
        # depth (measured: the open-loop serving A/B dropped 6x while
        # K∈{2..8} each compiled). {2,4,8,...} bounds the cache to
        # O(log window); the remainder runs per-frame via `pending`.
        k = 1 << (len(batch).bit_length() - 1)
        leftover = batch[k:]
        batch = batch[:k]
        t0 = time.perf_counter()
        for _, _, te in batch:
            if te:
                stats.record_wait(t0 - te)
        self._inflight[elem.name] = time.monotonic()
        try:
            emissions = elem.process_window(pad, [m[1] for m in batch])
        except Exception:
            # re-run every frame through the per-frame path so the
            # error (and its fail-fast policy) lands on the precise
            # frame that faulted — frames before it still emit. t_enq
            # zeroed so queue-wait isn't double-counted.
            lstats.bail("error")
            if tr.active:
                tr.record_loop_bail(elem.name, "error",
                                    time.perf_counter())
            detector.reset()
            rerun = [(p, it, 0.0) for p, it, _ in batch]
            rerun.extend(leftover)
            if parked is not None:
                rerun.append(parked)
            if eos_msg is not None:
                rerun.append(eos_msg)
            pending.extendleft(reversed(rerun))
            return True
        finally:
            self._inflight.pop(elem.name, None)
        t1 = time.perf_counter()
        lstats.entries += 1
        lstats.steps += k
        per = (t1 - t0) / k
        for i, m in enumerate(batch):
            stats.record(per)
            if tr.active:
                tr.record_process(elem.name, m[1], t0 + i * per,
                                  t0 + (i + 1) * per)
        if tr.active:
            tr.record_compiled_window(elem.name, k, t0, t1)
        self._consec_errors = 0
        for sp, b in emissions:
            self._emit(elem, sp, b)
            if window is not None and isinstance(b, TensorBuffer) \
                    and b.on_device:
                window.append(b.tensors)
        if window:
            while len(window) > self._max_inflight:
                device_sync(window.popleft(), forced=False)
            if tr.active:
                tr.record_inflight(elem.name, len(window),
                                   time.perf_counter())
        pending.extend(leftover)
        if parked is not None:
            pending.append(parked)
        if eos_msg is not None:
            pending.append(eos_msg)
        return True

    def _work(self, elem: Element) -> None:
        ch = self._queues[elem.name]
        n_pads = max(1, len(self.pipeline.links_to(elem)))
        eos_pads = set()
        stats = self._stats[elem.name]
        tr = self.tracer
        policy = elem.error_policy    # resolved once; immutable per run
        # async-dispatch window (DEVICE_RESIDENT elements): outputs are
        # emitted downstream UNRESOLVED — XLA's async engine pipelines
        # the dispatches — and this worker blocks only on the OLDEST
        # emitted output once more than max_inflight are live, bounding
        # HBM held by in-flight results without a per-result sync
        window = deque() if elem.DEVICE_RESIDENT else None
        # compiled steady-state loop: only fail-fast tensor_filters with
        # a window-capable backend opt in (elements/filter.py
        # window_capable); every other element pays one attribute probe
        # at thread start and nothing per frame
        loop_on = (self._compiled_loop and policy.kind == "fail"
                   and getattr(elem, "window_capable", None) is not None
                   and elem.window_capable())
        detector = SteadyStateDetector(self._loop_arm) if loop_on else None
        lstats = None
        if loop_on:
            lstats = self._loop_stats[elem.name] = LoopStats()
        # frames drained off the channel but handed back by a window
        # bail (shape divergence / error re-run / trailing EOS); always
        # consumed, in order, before the channel is touched again, and
        # never re-enter a window — ordering is preserved by construction
        pending: deque = deque()
        try:
            while not self._stop_evt.is_set():
                # deadline-aware wait: an element holding half-assembled
                # state (tensor_batch) publishes its next flush instant;
                # the channel wait is bounded by exactly that instant —
                # no fixed poll tick — so a partial batch ships on time
                # even when no further buffer ever arrives, and an idle
                # element sleeps until woken by an enqueue or teardown
                deadline = elem.next_deadline()
                if deadline is not None:
                    now = time.perf_counter()
                    if now >= deadline:
                        stats.timer_fires += 1
                        for sp, b in elem.on_timer():
                            self._emit(elem, sp, b)
                        if tr.active:
                            tr.record_timer(elem.name, now,
                                            time.perf_counter())
                        continue
                if pending:
                    # bailed-window frames: already dequeued (and
                    # traced) — just process them per-frame, in order
                    msg = pending.popleft()
                    from_pending = True
                else:
                    msg, depth = ch.get(deadline)
                    if msg is CLOSED:  # teardown wakeup (stop()/_fail())
                        return
                    if msg is TIMED_OUT:  # deadline due — fires on_timer
                        continue
                    if tr.active:
                        tr.dequeue(elem.name, depth, time.perf_counter())
                    from_pending = False
                pad, item, t_enq = msg
                if item is EOS:
                    eos_pads.add(pad)
                    if len(eos_pads) >= n_pads:
                        t0 = time.perf_counter()
                        self._inflight[elem.name] = time.monotonic()
                        try:
                            for sp, b in elem.flush():
                                self._emit(elem, sp, b)
                        finally:
                            self._inflight.pop(elem.name, None)
                        if window:
                            # drain the async window before EOS
                            # propagates: nothing downstream of the EOS
                            # sentinel is still unresolved
                            while window:
                                device_sync(window.popleft(),
                                            forced=False)
                            if tr.active:
                                tr.record_inflight(
                                    elem.name, 0, time.perf_counter())
                        if tr.active:
                            tr.record_flush(elem.name, t0,
                                            time.perf_counter())
                            tr.record_eos(elem.name, time.perf_counter())
                        self._broadcast_eos(elem)
                        return
                    continue
                # -- compiled steady-state window ----------------------
                # bail-parked frames never re-enter a window (would
                # reorder them past frames still in `pending`)
                if detector is not None and not from_pending:
                    sig = frame_signature(item)
                    if detector.observe(sig) and \
                            self._run_compiled_window(
                                elem, ch, stats, lstats, detector,
                                pending, window, tr, pad, item, t_enq,
                                sig):
                        continue
                t0 = time.perf_counter()
                if t_enq:
                    stats.record_wait(t0 - t_enq)
                if policy.kind == "fail":
                    # hot path: identical to the historic fail-fast loop
                    # plus one watchdog stamp on either side
                    self._inflight[elem.name] = time.monotonic()
                    try:
                        emissions = elem.process(pad, item)
                    finally:
                        self._inflight.pop(elem.name, None)
                else:
                    emissions = self._process_with_policy(
                        elem, stats, policy, pad, item, tr)
                    if emissions is None:
                        continue      # buffer skipped/degraded/dropped
                t1 = time.perf_counter()
                stats.record(t1 - t0)
                self._consec_errors = 0
                if tr.active:
                    tr.record_process(elem.name, item, t0, t1)
                for sp, b in emissions:
                    self._emit(elem, sp, b)
                    if window is not None and isinstance(b, TensorBuffer) \
                            and b.on_device:
                        window.append(b.tensors)
                if window:
                    while len(window) > self._max_inflight:
                        device_sync(window.popleft(), forced=False)
                    if tr.active:
                        tr.record_inflight(elem.name, len(window),
                                           time.perf_counter())
        except Exception as e:
            self._fail(elem, e)
            try:
                self._broadcast_eos(elem)
            except Exception:
                pass


def run_pipeline(pipeline: Pipeline, timeout: Optional[float] = None,
                 optimize: bool = True) -> None:
    """Negotiate (with transform fusion by default), run to EOS, tear
    down. The gst-launch behavior."""
    PipelineRunner(pipeline, optimize=optimize).run(timeout)
