"""Host-sync choke point.

Every place the runtime must *force* device work to completion (a sink
draining results, a filter in latency_mode=sync, backend warm-up) goes
through :func:`device_sync` instead of hand-rolled per-leaf
``block_until_ready`` loops.  One call site means:

- one whole-tuple ``jax.block_until_ready`` (a single runtime round-trip
  instead of a Python loop over leaves),
- the tracer can count *forced* syncs — the host-path tax the async
  dispatch work exists to remove — as the ``forced_syncs`` stat, and
- the device profiler (runtime/devprof.py) can close its per-bucket
  device-time samples exactly where device completion is forced,
  without its own sync or any change to the forced-sync accounting.

Kept free of package-internal imports (scheduler, filter, sinks and the
XLA backend all call in here) — devprof is the one exception, itself a
stdlib-only leaf — and of an import-time jax dependency.
"""

from __future__ import annotations

import threading
import time

from nnstreamer_tpu.runtime import devprof

_lock = threading.Lock()
_forced = 0


def forced_sync_count() -> int:
    """Process-wide number of forced host syncs since import."""
    return _forced


def device_sync(tensors, tracer=None, name=None, forced=True):
    """Block until every device array in ``tensors`` is resolved.

    ``tensors`` is any pytree-ish container (the usual case: a buffer's
    tensor tuple).  If nothing in it is a device array this is free and
    neither counted nor traced.  Returns ``tensors`` unchanged — device
    results resolve in place.

    ``forced=True`` marks a *semantic* sync (sink, sync latency mode,
    warm-up) and is counted + traced; ``forced=False`` marks window
    backpressure (the bounded in-flight drain), which is expected
    steady-state behavior and only surfaces via the caller's gauge.
    """
    global _forced
    leaves = tensors if isinstance(tensors, (tuple, list)) else (tensors,)
    if not any(hasattr(t, "block_until_ready") for t in leaves):
        return tensors
    import jax

    jax.block_until_ready(tuple(leaves))
    prof = devprof.get()
    if prof.enabled:
        prof.sample_sync()
    if forced:
        with _lock:
            _forced += 1
        if tracer is not None and getattr(tracer, "active", False):
            tracer.record_forced_sync(name or "?", time.monotonic())
    return tensors
