"""Length-prefixed TCP message transport.

Reference parity: the nnstreamer-edge connection layer (SURVEY.md §5.8) —
connection handle + event callback, caps-compat handshake at connect,
clean reconnect/close semantics. One transport (TCP) replaces the
reference's TCP/HYBRID/AITT/MQTT zoo; the message framing is:

  u32 type | u32 length | length bytes payload

Types: HELLO (caps string), HELLO_ACK (caps string or error), DATA
(wire frame, edge/wire.py), RESULT (wire frame), BYE, PING/PONG,
BUSY (admission rejection, JSON).

Threading model: a `MsgServer` runs an accept loop + one reader thread
per connection, dispatching to a callback; `MsgClient` owns one socket
with a reader thread. All sends are serialized per connection (lock) so
frames never interleave.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.core.log import get_logger

log = get_logger("edge.protocol")

_FRAME = struct.Struct("<II")

T_HELLO = 1
T_HELLO_ACK = 2
T_HELLO_NAK = 3
T_DATA = 4
T_RESULT = 5
T_BYE = 6
T_PING = 7
T_PONG = 8
# admission rejection: the server refused a DATA frame (bounded queue
# full / outstanding bound hit / deadline passed). Payload is JSON
# {"pts", "cause", "queue_depth", "retry_after_ms"} — enough for the
# client to back off instead of timing out blind (traffic/admission.py)
T_BUSY = 9
# mesh control plane (serving/mesh.py). REGISTER: a worker host joins
# the router, JSON ad {"name", "capacity_rps", "dims", "types",
# "out_dims", "out_types", "versions", "zone", "lease_s"}. LEASE:
# heartbeat renewal, JSON {"name", "counters"} — the router fences a
# host whose lease expires (silent-host detection, not just conn EOF).
# SWAP/SWAP_ACK: two-phase version swap broadcast, JSON
# {"phase": "prepare"|"commit"|"abort", "model", "version", "epoch"}.
T_REGISTER = 10
T_REGISTER_ACK = 11
T_LEASE = 12
T_SWAP = 13
T_SWAP_ACK = 14

#: hard cap on a single message (matches wire.MAX_FRAME_BYTES intent)
MAX_MSG = 1 << 31

#: default outbound connect timeout. The OS default (no timeout on the
#: connect() syscall) is ~2 minutes of SYN retries — a blackholed peer
#: would wedge the dialing thread for that long. A few seconds fails
#: fast into the caller's retry path instead (docs/robustness.md).
DEFAULT_CONNECT_TIMEOUT_S = 5.0


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    while n:
        try:
            b = sock.recv(min(n, 1 << 20))
        except OSError:
            return None
        if not b:
            return None
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def read_msg(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    head = _recv_exact(sock, _FRAME.size)
    if head is None:
        return None
    mtype, length = _FRAME.unpack(head)
    if length > MAX_MSG:
        raise StreamError(f"edge message of {length} bytes exceeds limit")
    payload = _recv_exact(sock, length) if length else b""
    if payload is None:
        return None
    return mtype, payload


def write_msg(sock: socket.socket, mtype: int, payload: bytes = b"",
              lock: Optional[threading.Lock] = None) -> None:
    data = _FRAME.pack(mtype, len(payload)) + payload
    if lock:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


class Connection:
    """One accepted server-side connection."""

    _next_id = 1
    _id_lock = threading.Lock()

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        # Nagle holds every small write after the first until the peer
        # ACKs — with delayed ACKs that serializes a pipelined client's
        # window to one frame per reply, defeating max_in_flight>1
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.send_lock = threading.Lock()
        with Connection._id_lock:
            self.client_id = Connection._next_id
            Connection._next_id += 1

    def send(self, mtype: int, payload: bytes = b"",
             timeout: Optional[float] = None) -> None:
        """With `timeout`, a stalled peer (full kernel send buffer)
        raises OSError instead of blocking the caller forever — servers
        replying from shared worker threads must bound their sends.

        The bound uses select-for-writability + partial send()s rather
        than socket timeouts: settimeout() would mutate state shared
        with this connection's reader thread. NOTE: a timeout may leave
        a PARTIAL frame on the wire — the stream is unrecoverable and
        the caller must close the connection.
        """
        if timeout is None:
            write_msg(self.sock, mtype, payload, self.send_lock)
            return
        import select

        data = memoryview(_FRAME.pack(mtype, len(payload)) + payload)
        deadline = time.monotonic() + timeout
        with self.send_lock:
            while data:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise OSError(f"send timed out after {timeout}s "
                                  f"({len(data)} bytes unsent)")
                try:
                    _, writable, _ = select.select([], [self.sock], [],
                                                   remain)
                except ValueError as e:
                    # fd == -1: the socket was closed concurrently —
                    # callers handle OSError, keep that contract
                    raise OSError(f"socket closed during send: {e}") \
                        from None
                if not writable:
                    continue
                # writable ⇒ send() accepts ≥1 byte and returns without
                # waiting for the full buffer
                data = data[self.sock.send(data):]

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class MsgServer:
    """Accept loop + per-connection reader threads.

    on_message(conn, mtype, payload); on_connect(conn) -> bool (False
    rejects); on_disconnect(conn).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 on_message: Callable,
                 on_connect: Optional[Callable] = None,
                 on_disconnect: Optional[Callable] = None):
        self._on_message = on_message
        self._on_connect = on_connect
        self._on_disconnect = on_disconnect
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._conns: Dict[int, Connection] = {}
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"edge-accept:{self.port}",
            daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            conn = Connection(sock, addr)
            if self._on_connect is not None and not self._on_connect(conn):
                conn.close()
                continue
            with self._lock:
                self._conns[conn.client_id] = conn
            threading.Thread(target=self._read_loop, args=(conn,),
                             name=f"edge-read:{conn.client_id}",
                             daemon=True).start()

    def _read_loop(self, conn: Connection) -> None:
        try:
            while not self._stopping.is_set():
                msg = read_msg(conn.sock)
                if msg is None or msg[0] == T_BYE:
                    break
                if msg[0] == T_PING:
                    conn.send(T_PONG)
                    continue
                self._on_message(conn, msg[0], msg[1])
        except StreamError as e:
            log.error("connection %d protocol error: %s", conn.client_id, e)
        finally:
            with self._lock:
                self._conns.pop(conn.client_id, None)
            if self._on_disconnect is not None:
                self._on_disconnect(conn)
            conn.close()

    def connection(self, client_id: int) -> Optional[Connection]:
        with self._lock:
            return self._conns.get(client_id)

    def connections(self):
        with self._lock:
            return list(self._conns.values())

    def close(self) -> None:
        self._stopping.set()
        # shutdown() first: close() alone does not wake a thread
        # blocked in accept() on Linux.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for c in self.connections():
            c.close()


class MsgClient:
    """Client connection with a reader thread + reconnect.

    on_message(mtype, payload) runs on the reader thread.
    """

    def __init__(self, host: str, port: int, *, on_message: Callable,
                 on_close: Optional[Callable] = None,
                 connect_timeout: Optional[float] = None, retries: int = 3):
        if connect_timeout is None:
            connect_timeout = DEFAULT_CONNECT_TIMEOUT_S
        self.host, self.port = host, port
        self._on_message = on_message
        self._on_close = on_close
        self.send_lock = threading.Lock()
        self._stopping = threading.Event()
        self.sock: Optional[socket.socket] = None
        last = None
        for attempt in range(retries):
            try:
                self.sock = socket.create_connection(
                    (host, port), timeout=connect_timeout)
                break
            except OSError as e:
                last = e
                time.sleep(0.2 * (attempt + 1))
        if self.sock is None:
            raise StreamError(
                f"cannot connect to edge peer {host}:{port} after "
                f"{retries} attempts: {last}")
        self.sock.settimeout(None)
        # see Connection.__init__: Nagle + delayed ACK would serialize
        # a pipelined offload window to one frame per reply
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"edge-client:{port}",
                                        daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while not self._stopping.is_set():
                msg = read_msg(self.sock)
                if msg is None or msg[0] == T_BYE:
                    break
                if msg[0] == T_PING:
                    self.send(T_PONG)
                    continue
                self._on_message(msg[0], msg[1])
        except StreamError as e:
            log.error("client protocol error: %s", e)
        finally:
            self._stopping.set()
            if self._on_close is not None:
                self._on_close()

    @property
    def alive(self) -> bool:
        return not self._stopping.is_set()

    def send(self, mtype: int, payload: bytes = b"") -> None:
        if self._stopping.is_set():
            raise StreamError(
                f"edge connection to {self.host}:{self.port} is closed")
        try:
            write_msg(self.sock, mtype, payload, self.send_lock)
        except OSError as e:
            self._stopping.set()
            raise StreamError(
                f"edge send to {self.host}:{self.port} failed: {e}") from e

    def close(self) -> None:
        if not self._stopping.is_set():
            try:
                self.send(T_BYE)
            except StreamError:
                pass
        self._stopping.set()
        try:
            self.sock.close()
        except OSError:
            pass
