"""edgesink / edgesrc — pub/sub stream bridging.

Reference parity: gst/edge/ (edge_sink.c:261-331, edge_src.c:305-338) —
publish a stream to any number of subscribers; caps carried as a string
in the connect handshake. The reference's MQTT-broker variant collapses
into the same direct TCP transport (edgesink is the broker).
"""

from __future__ import annotations

import json
import queue as _queue
import threading

from typing import Iterator, Optional, Sequence

from nnstreamer_tpu.core.errors import PipelineError, StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.edge import protocol as P
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.graph.pipeline import (
    PropDef, SinkElement, SourceElement, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("edge.pubsub")


@register_element("edgesink")
class EdgeSink(SinkElement):
    """Publisher: every connected subscriber receives every buffer.

    port=0 picks a free port (`.port` after start). Slow subscribers do
    not block the stream: sends are best-effort per connection.
    """

    WANTS_HOST = True

    ELEMENT_NAME = "edgesink"
    PROPS = {
        "host": PropDef(str, "127.0.0.1"),
        "port": PropDef(int, 0),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._server: Optional[P.MsgServer] = None
        self._spec: Optional[TensorsSpec] = None

    def negotiate(self, in_specs: Sequence[StreamSpec]):
        spec = in_specs[0]
        if isinstance(spec, TensorsSpec):
            self._spec = spec
        return []

    def start(self) -> None:
        self._server = P.MsgServer(
            self.props["host"], self.props["port"],
            on_message=self._on_message)

    def _on_message(self, conn: P.Connection, mtype: int, payload: bytes):
        if mtype == P.T_HELLO:
            dims, types, _ = (self._spec.to_strings()
                              if self._spec else ("", "", ""))
            conn.send(P.T_HELLO_ACK,
                      json.dumps({"dims": dims, "types": types}).encode())

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.port

    def render(self, buf: TensorBuffer) -> None:
        frame = encode_buffer(buf)
        for conn in self._server.connections():
            try:
                conn.send(P.T_DATA, frame)
            except OSError:
                log.info("edgesink %s: subscriber %d dropped",
                         self.name, conn.client_id)

    def stop(self) -> None:
        if self._server is not None:
            self._server.close()


@register_element("edgesrc")
class EdgeSrc(SourceElement):
    """Subscriber: connects to an edgesink and emits its stream.

    The output spec comes from the publisher's handshake (caps-in-
    handshake, edge_sink.c), so no dims= needed — but the publisher must
    be running when this pipeline negotiates.
    """

    ELEMENT_NAME = "edgesrc"
    PROPS = {
        "host": PropDef(str, "127.0.0.1"),
        "port": PropDef(int, None, "publisher port (required)"),
        "timeout": PropDef(float, 10.0, "handshake timeout, s"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client: Optional[P.MsgClient] = None
        self._frames: _queue.Queue = _queue.Queue(maxsize=64)
        self._hello: _queue.Queue = _queue.Queue()
        self._stop = threading.Event()

    def output_spec(self) -> StreamSpec:
        if not self.props["port"]:
            raise PipelineError(
                f"edgesrc {self.name}: port= of the publisher is required")
        try:
            self._client = P.MsgClient(self.props["host"],
                                       int(self.props["port"]),
                                       on_message=self._on_message,
                                       on_close=self.interrupt)
        except StreamError as e:
            raise PipelineError(
                f"edgesrc {self.name}: cannot reach publisher: {e}") from e
        self._client.send(P.T_HELLO, b"{}")
        try:
            payload = self._hello.get(timeout=self.props["timeout"])
        except _queue.Empty:
            raise PipelineError(
                f"edgesrc {self.name}: publisher did not answer the "
                f"handshake within {self.props['timeout']}s") from None
        caps = json.loads(payload.decode())
        if not caps.get("dims"):
            raise PipelineError(
                f"edgesrc {self.name}: publisher declared no caps; is its "
                f"pipeline carrying tensors?")
        return TensorsSpec.from_strings(caps["dims"], caps["types"])

    def _on_message(self, mtype: int, payload: bytes) -> None:
        if mtype == P.T_HELLO_ACK:
            self._hello.put(payload)
        elif mtype == P.T_DATA:
            try:
                buf, _ = decode_buffer(payload)
            except ValueError as e:
                log.error("edgesrc: dropping corrupt frame: %s", e)
                return
            try:
                self._frames.put(buf, timeout=1)
            except _queue.Full:
                log.warning("edgesrc %s: frame queue full, dropping",
                            self.name)

    def interrupt(self) -> None:
        self._stop.set()
        try:
            self._frames.put_nowait(None)
        except _queue.Full:
            pass

    def generate(self) -> Iterator[TensorBuffer]:
        # ends when the publisher disconnects (on_close → interrupt) or
        # the pipeline tears down; queued frames drain first
        while True:
            if self._stop.is_set() and self._frames.empty():
                return
            item = self._frames.get()
            if item is None:
                if self._stop.is_set() and self._frames.empty():
                    return
                continue
            yield item

    def stop(self) -> None:
        if self._client is not None:
            self._client.close()
        self.interrupt()
