"""Among-device AI: remote offload, pub/sub, wire transport.

Reference parity (SURVEY.md §2.5, §5.8): the `tensor_query_*` elements
(sync RPC offload with per-client routing meta), `edgesink`/`edgesrc`
(pub/sub), and the nnstreamer-edge TCP transport with its caps handshake.
The MQTT/gRPC/AITT transport zoo collapses into one TCP protocol
(edge/protocol.py) + the in-process mesh dispatcher (parallel/dispatch.py)
for on-pod scale-out — parity transport off-pod, ICI collectives on-pod.

Modules:
- wire.py     — TensorBuffer ↔ wire frame codec (MetaHeader per tensor)
- protocol.py — length-prefixed TCP message transport (client/server)
- query.py    — tensor_query_client / serversrc / serversink elements
- pubsub.py   — edgesink (publisher) / edgesrc (subscriber) elements
- broker.py   — EdgeBroker: HYBRID discovery + brokered pub/sub + clock
                alignment (MQTT/NTP analog); mqttsink/mqttsrc ride it
"""

from nnstreamer_tpu.edge.broker import BrokerClient, EdgeBroker
from nnstreamer_tpu.edge.query import (
    BatchedQueryServer, QueryServer, TensorQueryClient,
    TensorQueryServerSink, TensorQueryServerSrc)
from nnstreamer_tpu.edge.pubsub import EdgeSink, EdgeSrc
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer

__all__ = [
    "BrokerClient",
    "EdgeBroker",
    "EdgeSink",
    "EdgeSrc",
    "BatchedQueryServer",
    "QueryServer",
    "TensorQueryClient",
    "TensorQueryServerSink",
    "TensorQueryServerSrc",
    "decode_buffer",
    "encode_buffer",
]
