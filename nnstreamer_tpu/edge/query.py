"""tensor_query elements — remote inference offload (request/reply).

Reference parity (SURVEY.md §3.4): `tensor_query_client` wraps each frame,
sends it to a server pipeline, and blocks on an async queue for the
result; `tensor_query_serversrc`/`serversink` bracket the server pipeline
and share per-id state, routing answers back by the client_id that rides
the buffer meta (GstMetaQuery analog). Caps compatibility is verified at
connect (HELLO/ACK handshake carrying spec strings).

TPU-first: the server pipeline typically ends in one XLA-fused filter, so
offload cost is wire + one H2D/D2H per frame; for on-pod scale-out use
parallel/dispatch.py instead (no wire at all). This module is the
off-pod parity transport.
"""

from __future__ import annotations

import json
import queue as _queue
import threading
import time
from collections import deque

from typing import Dict, Iterator, List, Optional, Sequence

from nnstreamer_tpu.core.errors import (
    PipelineError, ServerBusyError, StreamError)
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.edge import protocol as P
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.graph.pipeline import (
    Element, Emission, PropDef, SinkElement, SourceElement, StreamSpec)
from nnstreamer_tpu.runtime.tracing import (
    NULL_TRACER, ensure_trace_ctx, get_trace_ctx, percentile, stamp_hop)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec
from nnstreamer_tpu.traffic.admission import AdmissionQueue

log = get_logger("edge.query")

_STOPPED = object()   # sentinel unblocking _take_reply at teardown

#: retry-after hint on shutdown/dispatch-failure BUSYs, where the
#: admission queue's drain-rate estimate is meaningless
_DEFAULT_SHUTDOWN_RETRY_MS = 250.0


class QueryServer:
    """Shared state of one query server id: the transport + the in/out
    specs + the frame queue serversrc drains (GstTensorQueryServer
    analog, tensor_query_server.c)."""

    _by_id: Dict[int, "QueryServer"] = {}
    _lock = threading.Lock()

    def __init__(self, sid: int):
        self.sid = sid
        self.server: Optional[P.MsgServer] = None
        self.in_spec: Optional[TensorsSpec] = None
        self.out_spec: Optional[TensorsSpec] = None
        # bounded admission (traffic/admission.py): a full queue refuses
        # the frame with a typed wire BUSY instead of the seed's silent
        # drop-after-5s-block, which collapsed goodput under overload.
        # serversrc re-knobs this from its properties at start().
        self.frames: AdmissionQueue = AdmissionQueue(max_pending=64)
        self.tracer = NULL_TRACER
        self.started = threading.Event()
        # set by serving/pool.py when a WorkerPool services this id;
        # serversrc extra_stats folds the pool's per-worker view in
        self.pool = None

    @classmethod
    def get(cls, sid: int) -> "QueryServer":
        with cls._lock:
            if sid not in cls._by_id:
                cls._by_id[sid] = cls(sid)
            return cls._by_id[sid]

    @classmethod
    def reset_all(cls) -> None:
        with cls._lock:
            for s in cls._by_id.values():
                if s.server is not None:
                    s.server.close()
            cls._by_id.clear()

    # -- transport lifecycle (owned by serversrc) --------------------------
    def start(self, host: str, port: int) -> None:
        if self.server is not None:
            return
        self.server = P.MsgServer(
            host, port,
            on_message=self._on_message,
            on_connect=self._on_connect,
        )
        self.started.set()

    def _on_connect(self, conn: P.Connection) -> bool:
        return True  # handshake happens via HELLO message

    def _on_message(self, conn: P.Connection, mtype: int, payload: bytes):
        if mtype == P.T_HELLO:
            try:
                want = json.loads(payload.decode())
                client_in = TensorsSpec.from_strings(
                    want["dims"], want["types"])
            except (ValueError, KeyError) as e:
                conn.send(P.T_HELLO_NAK, f"bad hello: {e}".encode())
                return
            if self.in_spec is not None and \
                    not self.in_spec.is_compatible(client_in):
                conn.send(P.T_HELLO_NAK, (
                    f"incompatible caps: server expects "
                    f"{self.in_spec.to_strings()[:2]}, client sends "
                    f"{want['dims']},{want['types']}").encode())
                return
            dims, types, _ = (self.out_spec.to_strings()
                              if self.out_spec else ("", "", ""))
            conn.send(P.T_HELLO_ACK,
                      json.dumps({"dims": dims, "types": types}).encode())
        elif mtype == P.T_DATA:
            try:
                buf, _ = decode_buffer(payload)
            except ValueError as e:
                log.error("server %d: dropping corrupt frame: %s",
                          self.sid, e)
                return
            buf = buf.with_meta(client_id=conn.client_id)
            dec = self.frames.offer(buf)
            # reject-oldest / deadline-drop sheds previously-ADMITTED
            # frames: each victim's client still gets a typed BUSY —
            # the conservation contract is that no request ever ends
            # neither-replied-nor-rejected
            for v in dec.victims:
                if v is not None:
                    self._send_busy(
                        v.meta.get("client_id"), v.pts,
                        dec.victim_cause or "shed",
                        dec.queue_depth, dec.retry_after_ms)
            if not dec.admitted:
                self._send_busy(conn.client_id, buf.pts, dec.cause,
                                dec.queue_depth, dec.retry_after_ms,
                                conn=conn)

    def _send_busy(self, client_id, pts, cause: str, depth: int,
                   retry_after_ms: float,
                   conn: Optional[P.Connection] = None) -> None:
        """Typed admission rejection: BUSY carrying the server's queue
        depth and a retry-after suggestion (the client surfaces it as
        ServerBusyError through its error policy)."""
        if self.tracer.active:
            self.tracer.record_shed(f"query_server_{self.sid}", cause,
                                    time.perf_counter(), pts=pts,
                                    depth=depth)
        if conn is None and self.server is not None and \
                client_id is not None:
            conn = self.server.connection(int(client_id))
        if conn is None:
            log.warning("server %d: client %s gone, BUSY (%s) for pts=%s "
                        "undeliverable", self.sid, client_id, cause, pts)
            return
        payload = json.dumps({
            "pts": pts, "cause": cause, "queue_depth": depth,
            "retry_after_ms": round(retry_after_ms, 1)}).encode()
        try:
            conn.send(P.T_BUSY, payload, timeout=5.0)
        except OSError as e:
            log.warning("server %d: BUSY to %s failed (%s); closing the "
                        "connection", self.sid, client_id, e)
            try:
                conn.close()
            except OSError:
                pass

    def send_busy(self, client_id, pts, cause: str) -> None:
        """BUSY a previously-admitted frame that will never be answered
        (dispatch failure, shutdown drain)."""
        c = self.frames.counters()
        self._send_busy(client_id, pts, cause, c["depth"],
                        _DEFAULT_SHUTDOWN_RETRY_MS)

    def reply(self, client_id: int, buf: TensorBuffer) -> None:
        # a request is "served" once its result reaches the reply path,
        # even if the client has meanwhile vanished — completion
        # accounting must balance admission accounting. The tenant
        # class stamped at admission rides the buffer meta end-to-end,
        # so per-class counters settle on the same class the offer was
        # counted under.
        cls = buf.meta.get("_tenant_class") \
            if isinstance(buf.meta, dict) else None
        self.frames.note_replied(cls=cls)
        stamp_hop(buf.meta, "reply")
        if self.tracer.active:
            ctx = get_trace_ctx(buf.meta)
            if ctx is not None:
                # server-side end of this request's timeline: the full
                # hop list (admission→worker→reply) as it leaves us
                extra = {"tenant": cls} if cls is not None else {}
                self.tracer.record_request(
                    f"query_server_{self.sid}", ctx["id"], ctx["hops"],
                    time.perf_counter(), pts=buf.pts, **extra)
        conn = self.server.connection(client_id) if self.server else None
        if conn is None:
            log.warning("server %d: client %d gone, dropping result",
                        self.sid, client_id)
            return
        try:
            # bounded send: a stalled client (full kernel buffer) must
            # not wedge the replying thread — which may be shared with
            # other clients (BatchedQueryServer's completion path)
            conn.send(P.T_RESULT, encode_buffer(buf, client_id),
                      timeout=10.0)
        except OSError as e:
            log.warning("server %d: reply to %d failed (%s); closing "
                        "the connection — a timed-out send may have "
                        "left a partial frame, the stream is "
                        "unrecoverable", self.sid, client_id, e)
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        # admitted-but-unprocessed frames are shed with a typed BUSY
        # before the transport drops: no client is left to time out
        # blind on a request the server silently discarded
        for v in self.frames.shed_remaining("shutdown"):
            if v is not None:
                self.send_busy(v.meta.get("client_id"), v.pts, "shutdown")
        if self.server is not None:
            self.server.close()
            self.server = None
        with QueryServer._lock:
            QueryServer._by_id.pop(self.sid, None)


@register_element("tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    """Server entry pad: emits frames received from clients.

    dims/types declare the accepted input (HELLO compat check). port=0
    picks a free port (read it from `.port` — loopback tests do this).
    """

    ELEMENT_NAME = "tensor_query_serversrc"
    PROPS = {
        "host": PropDef(str, "127.0.0.1"),
        "port": PropDef(int, 0),
        "id": PropDef(int, 0, "server pair id"),
        "dims": PropDef(str, None, "accepted input dims"),
        "types": PropDef(str, "float32"),
        # admission control (traffic/admission.py, docs/traffic.md):
        # a full server answers BUSY instead of buffering unboundedly
        "max_pending": PropDef(
            int, 64, "admission queue bound; a full queue sheds per "
                     "shed_policy with a typed BUSY reply"),
        "max_inflight": PropDef(
            int, 0, "bound on outstanding requests (queued + "
                    "processing); 0 = unlimited"),
        "shed_policy": PropDef(
            str, "reject-newest",
            "reject-newest | reject-oldest | deadline-drop (sheds "
            "requests whose meta deadline_ms budget has passed)"),
        # HYBRID connect type (tensor_query_common.c:35-39): advertise
        # this server under topic= at an EdgeBroker so clients find it by
        # name instead of host:port
        "broker_host": PropDef(str, "127.0.0.1"),
        "broker_port": PropDef(int, 0, "EdgeBroker port (0 = no broker)"),
        "topic": PropDef(str, "", "service name to register at the broker"),
        "advertise_host": PropDef(
            str, "", "address clients should dial (required when binding "
                     "a wildcard like 0.0.0.0)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._srv: Optional[QueryServer] = None
        self._stop = threading.Event()
        self._broker = None

    def output_spec(self) -> StreamSpec:
        if not self.props["dims"]:
            raise PipelineError(
                f"tensor_query_serversrc {self.name}: dims= is required "
                f"(declares the accepted client input)")
        return TensorsSpec.from_strings(self.props["dims"],
                                        self.props["types"])

    def start(self) -> None:
        self._srv = QueryServer.get(self.props["id"])
        self._srv.in_spec = self.out_specs[0]
        try:
            victims = self._srv.frames.configure(
                max_pending=self.props["max_pending"],
                max_inflight=self.props["max_inflight"],
                shed_policy=self.props["shed_policy"])
        except ValueError as e:
            raise PipelineError(f"{self.name}: {e}") from None
        # a policy change to deadline-drop purges already-expired
        # queued entries (admission.configure contract): each victim
        # is owed a BUSY, exactly as if an offer() had purged it
        for v in victims or ():
            if v is not None:
                self._srv.send_busy(v.meta.get("client_id"), v.pts,
                                    "deadline")
        # the runner hands the tracer down before start(): shed events
        # land on the pipeline's trace alongside everything else
        self._srv.tracer = self._tracer
        self._srv.start(self.props["host"], self.props["port"])
        if self.props["broker_port"]:
            if not self.props["topic"]:
                raise PipelineError(
                    f"{self.name}: broker registration needs topic=<name>")
            from nnstreamer_tpu.edge.broker import BrokerClient

            advertise = self.props["advertise_host"] or self.props["host"]
            if advertise in ("0.0.0.0", "::"):
                raise PipelineError(
                    f"{self.name}: binding {advertise} but registering at "
                    f"a broker — clients cannot dial a wildcard address; "
                    f"set advertise_host=<reachable address>")
            # the registration lives as long as this connection: broker
            # drops it if we crash (no stale addresses)
            self._broker = BrokerClient(self.props["broker_host"],
                                        self.props["broker_port"])
            self._broker.register(self.props["topic"], advertise, self.port)

    @property
    def port(self) -> int:
        assert self._srv is not None and self._srv.server is not None
        return self._srv.server.port

    def interrupt(self) -> None:
        self._stop.set()
        if self._srv is not None:
            # sentinels bypass admission and cannot be lost to a full
            # queue (AdmissionQueue.put_nowait is unbounded for them)
            self._srv.frames.put_nowait(None)

    def stop(self) -> None:
        if self._broker is not None:
            self._broker.close()
            self._broker = None
        if self._srv is not None:
            self._srv.stop()

    def generate(self) -> Iterator[TensorBuffer]:
        while not self._stop.is_set():
            item = self._srv.frames.get()
            if item is None:
                return
            yield item

    def admission_counters(self) -> Dict:
        """Consistent admission/shed snapshot (traffic harness reads
        this for the conservation check)."""
        srv = self._srv or QueryServer.get(self.props["id"])
        return srv.frames.counters()

    def extra_stats(self) -> Dict:
        c = self.admission_counters()
        out = {
            "admitted": c["admitted"],
            "replied": c["replied"],
            "rejected_total": sum(c["rejected"].values()),
            "shed_total": sum(c["shed"].values()),
            "admission_depth": c["depth"],
            "admission_depth_peak": c["depth_peak"],
            "admission_inflight": c["inflight"],
        }
        for cause, v in c["rejected"].items():
            out[f"rejected_{cause}"] = v
        for cause, v in c["shed"].items():
            out[f"shed_{cause}"] = v
        srv = self._srv or QueryServer.get(self.props["id"])
        if srv.pool is not None:
            out.update(srv.pool.extra_stats())
        return out


@register_element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    """Server exit pad: routes each result back to its client by the
    client_id riding buffer meta."""

    WANTS_HOST = True

    ELEMENT_NAME = "tensor_query_serversink"
    PROPS = {
        "id": PropDef(int, 0, "server pair id"),
    }

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        srv = QueryServer.get(self.props["id"])
        spec = in_specs[0]
        if isinstance(spec, TensorsSpec):
            srv.out_spec = spec
        return []

    def render(self, buf: TensorBuffer) -> None:
        client_id = buf.meta.get("client_id")
        if client_id is None:
            raise StreamError(
                f"tensor_query_serversink {self.name}: buffer has no "
                f"client_id meta — it must originate from "
                f"tensor_query_serversrc (same id) for reply routing")
        QueryServer.get(self.props["id"]).reply(int(client_id), buf)


@register_element("tensor_query_client")
class TensorQueryClient(Element):
    """Sync RPC offload: push frame to server, block (with timeout) for
    the result, emit it downstream (tensor_query_client.c:657-699).

    Backpressure: a server-side admission rejection (wire BUSY) surfaces
    as `ServerBusyError` carrying the server's queue depth and
    retry-after hint, so the element error-policy machinery finally sees
    remote overload: `error_policy=retry:N:backoff` re-offers the frame
    after the backoff, `degrade` routes it to the fallback pad, `skip`
    sheds it client-side, and the default `fail` stops the pipeline.
    With `max_in_flight=1` (the default) retry semantics are exact — the
    rejected frame IS the retried frame. With a pipelined window >1 a
    rejection may concern an *older* in-flight frame whose bytes are
    gone; that frame counts as shed and the policy's retry/backoff acts
    as send throttling (the overload response that matters), so under
    overload the emitted sequence can have gaps but never reorders.
    """

    WANTS_HOST = True

    ELEMENT_NAME = "tensor_query_client"
    PROPS = {
        "host": PropDef(str, "127.0.0.1"),
        "port": PropDef(int, None, "server port (tcp) / broker port (hybrid)"),
        "timeout": PropDef(float, 10.0, "per-frame reply timeout, s"),
        # connect_type=hybrid: host/port point at an EdgeBroker; the
        # server address is discovered by topic= (MQTT-discovery + TCP-
        # data pattern, tensor_query_common.c:39)
        "connect_type": PropDef(str, "tcp", "tcp | hybrid"),
        "topic": PropDef(str, "", "service name (hybrid)"),
        # >1 pipelines the offload: up to N frames in flight before
        # blocking, overlapping network+server latency across frames
        # (the reference blocks per frame, tensor_query_client.c:699 —
        # exactly the per-frame sync the TPU design avoids). Ordering is
        # preserved: one TCP connection, FIFO server pipeline.
        "max_in_flight": PropDef(int, 1, "1 = reference per-frame sync"),
        # Bounds the TCP dial itself (SYN + handshake), distinct from
        # timeout= which bounds per-frame replies on an established
        # connection. 0 falls back to protocol.DEFAULT_CONNECT_TIMEOUT_S;
        # without a bound a dial into a dead/filtered address would sit
        # in the OS connect retry cycle (~minutes) wedging negotiate().
        "connect_timeout": PropDef(
            float, 0.0, "TCP connect timeout, s (0 = default)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if self.props["max_in_flight"] < 1:
            raise PipelineError(
                f"{self.name}: max_in_flight must be >= 1")
        self._client: Optional[P.MsgClient] = None
        self._replies: _queue.Queue = _queue.Queue()
        self._hello: _queue.Queue = _queue.Queue()
        # (pts, t_send) of sent-but-unanswered frames, server FIFO order
        self._pending: "deque" = deque()
        # BUSY rejections consumed off the wire but not yet raised (the
        # raise is deferred to a point where no collected emissions can
        # be lost with it)
        self._busy_stash: "deque" = deque()
        # client-side goodput/rejection stats (extra_stats)
        self._sent = 0
        self._replied = 0
        self._busy = 0
        self._rtt: "deque" = deque(maxlen=2048)   # reply RTTs, seconds
        self._last_busy: Optional[dict] = None

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if not self.props["port"]:
            self.fail_negotiation("port= of the query server is required")
        host, port = self.props["host"], int(self.props["port"])
        if self.props["connect_type"] == "hybrid":
            if not self.props["topic"]:
                self.fail_negotiation(
                    "connect_type=hybrid needs topic=<service name> "
                    "(host/port address the broker)")
            from nnstreamer_tpu.edge.broker import BrokerClient

            bc = None
            try:
                bc = BrokerClient(host, port)
                host, port = bc.lookup(self.props["topic"],
                                       timeout=self.props["timeout"])
            except StreamError as e:
                self.fail_negotiation(
                    f"hybrid discovery of {self.props['topic']!r} via "
                    f"broker {self.props['host']}:{self.props['port']} "
                    f"failed: {e}")
            finally:
                if bc is not None:   # no socket/thread leak on failure
                    bc.close()
        elif self.props["connect_type"] != "tcp":
            self.fail_negotiation(
                f"connect_type must be tcp|hybrid, got "
                f"{self.props['connect_type']!r}")
        try:
            self._client = P.MsgClient(
                host, port, on_message=self._on_message,
                connect_timeout=self.props["connect_timeout"] or None)
        except StreamError as e:
            self.fail_negotiation(str(e))
        dims, types, _ = spec.to_strings()
        self._client.send(P.T_HELLO,
                          json.dumps({"dims": dims, "types": types}).encode())
        try:
            kind, payload = self._hello.get(timeout=self.props["timeout"])
        except _queue.Empty:
            self.fail_negotiation(
                f"query server {self.props['host']}:{self.props['port']} "
                f"did not answer the caps handshake within "
                f"{self.props['timeout']}s")
        if kind == P.T_HELLO_NAK:
            self.fail_negotiation(
                f"query server rejected our caps: {payload.decode()}")
        reply = json.loads(payload.decode())
        if not reply.get("dims"):
            self.fail_negotiation(
                "query server did not declare an output spec; start the "
                "server pipeline (serversrc+serversink) first")
        return [TensorsSpec.from_strings(reply["dims"], reply["types"],
                                         rate=spec.rate)]

    def _on_message(self, mtype: int, payload: bytes) -> None:
        if mtype in (P.T_HELLO_ACK, P.T_HELLO_NAK):
            self._hello.put((mtype, payload))
        elif mtype == P.T_RESULT:
            self._replies.put(("r", payload))
        elif mtype == P.T_BUSY:
            self._replies.put(("b", payload))

    def _note_busy(self, payload: bytes) -> None:
        """Consume one BUSY: the rejected frame leaves the in-flight
        window (it may not be the oldest — rejections are answered at
        admission, results only after service) and the rejection is
        stashed for the next deferred raise."""
        try:
            info = json.loads(payload.decode())
        except ValueError:
            info = {}
        pts = info.get("pts")
        removed = False
        if pts is not None:
            for i, (p, _) in enumerate(self._pending):
                if p == pts:
                    del self._pending[i]
                    removed = True
                    break
        if not removed and self._pending:
            self._pending.popleft()
        self._busy += 1
        self._last_busy = info
        self._busy_stash.append(info)

    def _raise_stashed(self) -> None:
        if not self._busy_stash:
            return
        info = self._busy_stash.popleft()
        cause = info.get("cause", "queue_full")
        depth = int(info.get("queue_depth", 0))
        retry_ms = float(info.get("retry_after_ms", 0.0))
        raise ServerBusyError(
            f"tensor_query_client {self.name}: server rejected frame "
            f"pts={info.get('pts')} at admission ({cause}; queue depth "
            f"{depth}, suggested retry after ~{retry_ms:.0f}ms). Set "
            f"error_policy=retry:N:backoff_ms | degrade | skip on this "
            f"element to absorb overload instead of failing",
            queue_depth=depth, retry_after_ms=retry_ms, cause=cause,
            pts=info.get("pts"))

    def _take_reply(self) -> Optional[Emission]:
        """Pop the oldest in-flight frame's reply (blocking, timeout).
        Returns None when the message was a BUSY rejection — the window
        shrank but nothing is emitted."""
        try:
            item = self._replies.get(timeout=self.props["timeout"])
        except _queue.Empty:
            raise StreamError(
                f"tensor_query_client {self.name}: no reply for frame "
                f"pts={self._pending[0][0]} within "
                f"{self.props['timeout']}s (server overloaded or "
                f"connection lost)") from None
        if item is _STOPPED:
            raise StreamError(
                f"tensor_query_client {self.name}: stopped with "
                f"{len(self._pending)} frame(s) still in flight")
        kind, payload = item
        if kind == "b":
            self._note_busy(payload)
            return None
        pts, t_send = self._pending.popleft()
        out, _ = decode_buffer(payload)
        out.meta.pop("client_id", None)
        stamp_hop(out.meta, "client_recv")
        if self._tracer.active:
            ctx = get_trace_ctx(out.meta)
            if ctx is not None:
                # client-side end of the timeline: includes the wire
                # round trip the server-side record cannot see
                self._tracer.record_request(
                    self.name, ctx["id"], ctx["hops"],
                    time.perf_counter(), pts=pts)
        # integrity check for the pipelined window: the reply echoes the
        # request's pts on the wire, so a server-side frame drop cannot
        # silently shift every later reply onto the wrong frame
        if out.pts is not None and pts is not None and out.pts != pts:
            raise StreamError(
                f"tensor_query_client {self.name}: reply stream out of "
                f"sync — expected pts={pts}, server answered pts="
                f"{out.pts}. A frame was dropped server-side; lower "
                f"max_in_flight or fix the server pipeline")
        self._replied += 1
        self._rtt.append(time.perf_counter() - t_send)
        return (0, out.with_tensors(out.tensors, pts=pts))

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        # a rejection consumed on a previous call raises BEFORE this
        # frame is sent: under retry the re-invoked process() sends it
        # exactly once, so no frame is ever duplicated on the wire
        self._raise_stashed()
        if self._tracer.active:
            # get-or-create: a BUSY retry re-invokes process() with the
            # SAME buffer, so the existing context (and its id) is kept
            # and this send appends a second client_send hop — the
            # retry-reuses-id invariant the regression tests pin
            ensure_trace_ctx(buf.meta)
        stamp_hop(buf.meta, "client_send", pts=buf.pts)
        self._client.send(P.T_DATA, encode_buffer(buf))
        self._pending.append((buf.pts, time.perf_counter()))
        self._sent += 1
        emissions: List[Emission] = []
        # opportunistically drain replies that already arrived, then
        # block only when the in-flight window is full (a consumed BUSY
        # shrinks the window without emitting)
        while self._pending:
            if not self._replies.empty():
                em = self._take_reply()
            elif len(self._pending) >= self.props["max_in_flight"]:
                em = self._take_reply()
            else:
                break
            if em is not None:
                emissions.append(em)
        if self._busy_stash and not emissions:
            # nothing collected, safe to raise now: with max_in_flight=1
            # this is the just-sent frame's own rejection, and the retry
            # policy re-offers it with backoff — exact retry semantics
            self._raise_stashed()
        return emissions

    def flush(self) -> List[Emission]:
        """EOS: drain every in-flight frame so nothing is dropped.
        Rejections during the drain are counted, not raised — EOS must
        deliver what CAN be delivered."""
        emissions: List[Emission] = []
        while self._pending:
            em = self._take_reply()
            if em is not None:
                emissions.append(em)
        self._busy_stash.clear()
        return emissions

    def extra_stats(self) -> Dict:
        """Client-side goodput/rejection view of the offload."""
        out = {
            "query_sent": self._sent,
            "query_replied": self._replied,
            "query_busy": self._busy,
            "query_goodput": round(self._replied / self._sent, 4)
            if self._sent else 1.0,
        }
        if self._rtt:
            vals = sorted(v * 1e3 for v in self._rtt)
            out["query_rtt_p50_ms"] = round(percentile(vals, 50), 3)
            out["query_rtt_p95_ms"] = round(percentile(vals, 95), 3)
        if self._last_busy is not None:
            out["query_retry_after_ms"] = float(
                self._last_busy.get("retry_after_ms", 0.0))
        return out

    def stop(self) -> None:
        if self._client is not None:
            self._client.close()
        # unblock a thread parked in _take_reply: teardown must not wait
        # out the full reply timeout for frames that will never answer
        self._replies.put(_STOPPED)


class BatchedQueryServer:
    """Offload serving with batch coalescing — MeshDispatcher wired into
    the query transport (the SURVEY §3.4 north star, VERDICT r2 #9).

    The element pipeline form (serversrc ! filter ! serversink) processes
    one frame per pass; this server instead feeds every arriving client
    frame straight into a `parallel.dispatch.MeshDispatcher`, which packs
    frames from ALL connected clients into dp-sharded batches (padded to
    one static bucket → a single compilation) and resolves each client's
    reply from its row of the batch. Wire format, HELLO caps handshake
    and per-client result routing are identical to the pipeline form, so
    unmodified tensor_query_client pipelines work against it.

    model: a ModelBundle, "zoo://name", or a model file path (modelio).
    pre: optional jax-traceable per-batch preprocess (e.g. uint8
    normalize) traced into the same XLA program as the model.

    One drain thread feeds the dispatcher so each client's frames enter
    batches in arrival order — the client contract is ordered replies
    (TensorQueryClient enforces the pts sequence). A frame whose
    dispatch fails is answered with a typed BUSY(dispatch_error) —
    never silence — and the failure is kept on `.error` for
    supervisors. Admission knobs (max_pending / max_inflight /
    shed_policy) mirror tensor_query_serversrc's (docs/traffic.md).
    """

    def __init__(self, model, *, sid: int = 0, host: str = "127.0.0.1",
                 port: int = 0, mesh=None, bucket: int = 8,
                 max_delay_ms: float = 2.0, pre=None,
                 in_spec: Optional[TensorsSpec] = None,
                 max_pending: int = 64, max_inflight: int = 0,
                 shed_policy: str = "reject-newest"):
        import jax

        from nnstreamer_tpu.backends.xla import XLABackend
        from nnstreamer_tpu.parallel.dispatch import MeshDispatcher
        from nnstreamer_tpu.parallel.mesh import MeshSpec, make_mesh

        bundle = XLABackend()._resolve(model)
        if mesh is None:
            n = len(jax.devices())
            dp = n if bucket % n == 0 else 1
            mesh = make_mesh(MeshSpec(dp=dp, tp=1, sp=1),
                             jax.devices()[:dp])
        params = jax.device_put(bundle.params) \
            if bundle.params is not None else None

        model_fn = bundle.fn

        def fn(p, x):
            if pre is not None:
                x = pre(x)
            out = model_fn(p, x)
            return out if isinstance(out, tuple) else (out,)

        self.dispatcher = MeshDispatcher(fn, params, mesh, bucket=bucket,
                                         max_delay_ms=max_delay_ms)
        # the dispatcher hands back per-frame rows (batch dim stripped);
        # the wire contract is the model's out_spec — restore a leading
        # batch=1 dim where the spec declares one
        self._lead1 = [t.shape and t.shape[0] == 1
                       for t in bundle.out_spec.tensors] \
            if bundle.out_spec else []
        self.qs = QueryServer.get(sid)
        # in_spec override: when `pre` changes the wire dtype (e.g.
        # uint8 camera frames normalized on-device), the HELLO contract
        # is the PRE-transform spec, not the model's
        self.qs.in_spec = in_spec if in_spec is not None \
            else bundle.in_spec
        self.qs.out_spec = bundle.out_spec
        self.qs.frames.configure(max_pending=max_pending,
                                 max_inflight=max_inflight,
                                 shed_policy=shed_policy)
        self.qs.start(host, port)
        self._stop = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        self.error: Optional[Exception] = None
        # exactly ONE drainer: a second thread could swap the order of a
        # client's consecutive frames between queue-get and submit,
        # desyncing its ordered reply stream
        self._drainers = [
            threading.Thread(target=self._drain, name="batched-query",
                             daemon=True)
        ]
        for t in self._drainers:
            t.start()

    @property
    def port(self) -> int:
        return self.qs.server.port

    def _drain(self) -> None:
        while not self._stop.is_set():
            try:
                buf = self.qs.frames.get(timeout=0.1)
            except _queue.Empty:
                continue
            cid = buf.meta.get("client_id", 0)
            pts = buf.pts
            try:
                fut = self.dispatcher.submit(buf.tensors[0])
            except StreamError as e:
                log.warning("batched query: submit failed: %s", e)
                # the frame was admitted but will never be answered:
                # account it as shed and tell the client now, instead
                # of letting its per-frame timeout expire blind
                self.qs.frames.note_failed("dispatch_error")
                self.qs.send_busy(cid, pts, "dispatch_error")
                continue

            def done(f, cid=cid, pts=pts):
                try:
                    outs = f.result()
                except Exception as e:
                    log.warning("batched query: dispatch failed for "
                                "client %d: %s", cid, e)
                    self.error = e
                    self.qs.frames.note_failed("dispatch_error")
                    self.qs.send_busy(cid, pts, "dispatch_error")
                    return
                outs = tuple(
                    o[None] if i < len(self._lead1) and self._lead1[i]
                    else o
                    for i, o in enumerate(outs))
                self.qs.reply(cid, TensorBuffer.of(*outs, pts=pts))

            fut.add_done_callback(done)

    def stats(self) -> Dict[str, int]:
        """Consistent snapshot: dispatcher counters are read under the
        dispatcher's lock (they are mutated from its completion thread)
        and admission counters under the admission queue's — callers
        never see a torn frames/batches pair mid-increment."""
        out = dict(self.dispatcher.stats())
        adm = self.qs.frames.counters()
        out.update({
            "admitted": adm["admitted"],
            "replied": adm["replied"],
            "rejected": sum(adm["rejected"].values()),
            "shed": sum(adm["shed"].values()),
            "admission_depth_peak": adm["depth_peak"],
        })
        return out

    def close(self) -> None:
        """Orderly teardown, strongest guarantee first: no request that
        a client is still waiting on may end up silently dropped.

        1. stop + JOIN the drain thread (a frame dequeued concurrently
           is still submitted — the dispatcher is not down yet);
        2. shed everything still queued with a typed BUSY(shutdown);
        3. shut the dispatcher down — it drains submitted batches (the
           done callbacks still reply: the transport is up) and fails
           any never-dispatched future with a typed StreamError;
        4. drop the transport.

        Idempotent: a supervisor drain racing a user close() is a
        no-op, not a double-join/double-shed.
        """
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        for t in self._drainers:
            t.join(timeout=5)
            if t.is_alive():
                log.warning(
                    "query server: drainer thread %s still alive after "
                    "5s join at close — wedged consumer leaked", t.name)
        for v in self.qs.frames.shed_remaining("shutdown"):
            if v is not None:
                self.qs.send_busy(v.meta.get("client_id"), v.pts,
                                  "shutdown")
        self.dispatcher.shutdown()
        self.qs.stop()
