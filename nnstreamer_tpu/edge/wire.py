"""TensorBuffer ↔ wire-frame codec.

Frame layout (little-endian):

  u32 magic 'TPUF'   u32 num_tensors   s64 pts (ns, -1 = none)
  u64 client_id      u32 meta_len      meta_len bytes of JSON meta
  per tensor: MetaHeader (tensor/meta.py) + raw payload bytes

The per-tensor MetaHeader is the same self-describing header flexible
streams use in-process (GstTensorMetaInfo analog), so any stream —
static, flexible, or sparse-encoded — serializes without negotiation
context; the receiver reconstructs shapes/dtypes from the wire alone
(the property the reference's query/edge elements get from caps strings
in their connect handshake plus per-memory headers).
"""

from __future__ import annotations

import json
import math
import struct
from typing import Optional, Tuple

import numpy as np

from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import MAX_TENSORS_PER_FRAME, TensorFormat
from nnstreamer_tpu.tensor.meta import MetaHeader

FRAME_MAGIC = 0x54505546  # 'TPUF'
_HEAD = struct.Struct("<IIqQI")  # magic, num, pts, client_id, meta_len

#: refuse to allocate absurd frames from hostile/corrupt headers
MAX_FRAME_BYTES = 1 << 31

# -- same-host shared-memory lane record framing --------------------------
# The shm ring (serving/shm.py) moves the exact same wire-frame bytes the
# pipe would have pickled, so the cross-host protocol above is untouched;
# only the *carrier* changes. Each ring record is SHM_REC header + payload.
SHM_REC_MAGIC = 0x54505553  # 'TPUS'
SHM_REC = struct.Struct("<IIQ")  # magic, payload_len, seq


def pack_shm_record(payload: bytes, seq: int) -> bytes:
    """Header for one shm ring record carrying ``payload``."""
    return SHM_REC.pack(SHM_REC_MAGIC, len(payload), seq)


def unpack_shm_record(head: bytes) -> Tuple[int, int]:
    """``(payload_len, seq)`` from a record header; raises ValueError
    on a bad magic (a torn or stale record — the reader treats that as
    a transport fault and falls back to the pipe lane)."""
    magic, length, seq = SHM_REC.unpack(head)
    if magic != SHM_REC_MAGIC:
        raise ValueError(
            f"bad shm record magic 0x{magic:08x} (want "
            f"0x{SHM_REC_MAGIC:08x})")
    return length, seq


#: recursion guard for nested meta (a trace context is depth 3:
#: ctx → hops list → hop dict; 8 leaves headroom without letting a
#: pathological self-referential meta spin the encoder)
_MAX_META_DEPTH = 8


def _jsonable(v, depth: int):
    """JSON-safe view of one meta value, recursing through dicts/lists
    (the trace context rides meta as nested dicts — dropping composites
    would silently sever every cross-process timeline). Returns the
    sentinel `_DROP` for unserializable values."""
    import base64

    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, np.ndarray):
        return {"__nd__": True, "dtype": str(v.dtype),
                "shape": list(v.shape),
                "b64": base64.b64encode(
                    np.ascontiguousarray(v).tobytes()).decode()}
    if depth >= _MAX_META_DEPTH:
        return _DROP
    if isinstance(v, dict):
        out = {}
        for k, item in v.items():
            if not isinstance(k, str):
                return _DROP
            j = _jsonable(item, depth + 1)
            if j is not _DROP:
                out[k] = j
        return out
    if isinstance(v, (list, tuple)):
        items = []
        for item in v:
            j = _jsonable(item, depth + 1)
            if j is not _DROP:
                items.append(j)
        return items
    return _DROP


_DROP = object()


def _meta_to_json(meta: dict) -> dict:
    """JSON-able meta. Arrays (decoder outputs: boxes/keypoints/class_map)
    ride as base64'd payloads, and nested dicts/lists (trace context)
    pass through recursively, so the documented meta contract survives
    the wire; unserializable values are dropped with a log line."""
    out = {}
    for k, v in meta.items():
        j = _jsonable(v, 0)
        if j is not _DROP:
            out[k] = j
        else:
            from nnstreamer_tpu.core.log import get_logger

            get_logger("edge.wire").debug(
                "meta key %r (%s) is not wire-serializable; dropped",
                k, type(v).__name__)
    return out


def _meta_from_json(meta: dict) -> dict:
    import base64

    out = {}
    for k, v in meta.items():
        if isinstance(v, dict) and v.get("__nd__"):
            out[k] = np.frombuffer(
                base64.b64decode(v["b64"]),
                np.dtype(v["dtype"])).reshape(v["shape"]).copy()
        else:
            out[k] = v
    return out


def encode_buffer(buf: TensorBuffer, client_id: int = 0) -> bytes:
    """Serialize a (host) TensorBuffer. Device buffers are synced here —
    the transport boundary is by definition a D2H point."""
    host = buf.to_host()
    metable = _meta_to_json(host.meta)
    meta_bytes = json.dumps(metable).encode() if metable else b""
    parts = [
        _HEAD.pack(FRAME_MAGIC, host.num_tensors,
                   -1 if host.pts is None else host.pts,
                   client_id, len(meta_bytes)),
        meta_bytes,
    ]
    for t in host.tensors:
        a = np.ascontiguousarray(t)
        hdr = MetaHeader(shape=tuple(a.shape) or (1,),
                         dtype=DType.from_np(a.dtype),
                         format=host.format)
        parts.append(hdr.pack())
        parts.append(a.tobytes())
    return b"".join(parts)


def peek_pts(data: bytes) -> Optional[int]:
    """pts of an encoded frame without decoding tensors/meta — the mesh
    host agent needs only the correlation id to synthesize a BUSY when
    a local forward fails. None for frames too short or with pts=-1."""
    if len(data) < _HEAD.size:
        return None
    magic, _num, pts, _cid, _mlen = _HEAD.unpack_from(data, 0)
    if magic != FRAME_MAGIC or pts < 0:
        return None
    return pts


def decode_buffer(data: bytes) -> Tuple[TensorBuffer, int]:
    """→ (buffer, client_id). Raises ValueError on corrupt frames."""
    if len(data) < _HEAD.size:
        raise ValueError(f"wire frame too small: {len(data)} bytes")
    magic, num, pts, client_id, meta_len = _HEAD.unpack_from(data, 0)
    if magic != FRAME_MAGIC:
        raise ValueError(
            f"bad wire frame magic 0x{magic:08x}; peer speaks a different "
            f"protocol (expected 0x{FRAME_MAGIC:08x})")
    if num > MAX_TENSORS_PER_FRAME:
        raise ValueError(f"corrupt frame: {num} tensors > limit")
    off = _HEAD.size
    meta = {}
    if meta_len:
        if meta_len > len(data) - off:
            raise ValueError("corrupt frame: meta overruns payload")
        meta = _meta_from_json(json.loads(data[off:off + meta_len]))
        off += meta_len
    tensors = []
    fmt = TensorFormat.STATIC
    for _ in range(num):
        hdr, used = MetaHeader.unpack(data[off:])
        off += used
        # math.prod on python ints: arbitrary precision, no silent int64
        # wraparound from a crafted header's u32 dims
        n_elems = math.prod(int(d) for d in hdr.shape)
        if n_elems > MAX_FRAME_BYTES:
            raise ValueError(
                f"corrupt frame: header claims {n_elems} elements")
        n_bytes = n_elems * hdr.dtype.itemsize
        if n_bytes > MAX_FRAME_BYTES or n_bytes > len(data) - off:
            raise ValueError(
                f"corrupt frame: tensor payload {n_bytes}B overruns frame")
        a = np.frombuffer(data[off:off + n_bytes],
                          hdr.dtype.np_dtype).reshape(hdr.shape).copy()
        off += n_bytes
        tensors.append(a)
        fmt = hdr.format
    return (TensorBuffer(tensors=tuple(tensors),
                         pts=None if pts < 0 else pts,
                         format=fmt, meta=meta),
            client_id)
