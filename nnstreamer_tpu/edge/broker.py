"""EdgeBroker — one well-known endpoint for discovery, brokered pub/sub,
and cross-host clock alignment.

Reference parity, three subsystems collapsed into one small service:

- **HYBRID discovery** (tensor_query_common.c:35-39: MQTT-for-discovery +
  TCP-for-data): services REGISTER name→host:port here; clients LOOKUP by
  name and then speak the normal direct TCP data protocol. Registrations
  are liveness-scoped — they vanish when the owning connection drops, so
  a crashed server never leaves a stale address behind.
- **MQTT-style brokered pub/sub** (gst/mqtt/, 3.4k LoC): PUBLISH fans a
  topic frame out to every SUBSCRIBE'd connection. Payloads are standard
  wire frames (edge/wire.py) so caps travel with every message.
- **NTP-style clock alignment** (ntputil.c:140, Documentation/
  synchronization-in-mqtt-elements.md): a TIME exchange returns the
  broker's clock; clients estimate their offset SNTP-style (t1 - (t0+t2)/2)
  and publishers stamp frames in *broker time*, giving all hosts one
  timeline without running an NTP daemon.

Wire framing rides edge/protocol.py (length-prefixed TCP messages).
Run standalone via `python -m nnstreamer_tpu --broker [PORT]`.
"""

from __future__ import annotations

import json
import struct
import threading
import time
from typing import Callable, Dict, Optional, Set, Tuple

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.edge import protocol as P

log = get_logger("edge.broker")

# broker message types (continuing edge/protocol.py's space)
T_REGISTER = 16      # json {name, host, port}
T_REGISTER_ACK = 17
T_REGISTER_NAK = 18  # utf8 reason
T_LOOKUP = 19        # json {name}
T_LOOKUP_ACK = 20    # json {name, host, port}
T_LOOKUP_NAK = 21    # utf8 reason
T_SUBSCRIBE = 22     # utf8 topic
T_PUBLISH = 23       # u16 topic_len | topic | u64 pub_broker_ns | frame
T_TIME = 24          # 8 opaque client bytes
T_TIME_ACK = 25      # those 8 bytes | u64 broker time_ns
T_UNREGISTER = 26    # json {name}

_PUB_HEAD = struct.Struct("<H")
_PUB_TS = struct.Struct("<Q")
_TIME_ACK = struct.Struct("<8sQ")


def pack_publish(topic: str, pub_broker_ns: int, frame: bytes) -> bytes:
    t = topic.encode()
    if len(t) > 0xFFFF:
        raise StreamError(f"topic too long ({len(t)} bytes)")
    return _PUB_HEAD.pack(len(t)) + t + _PUB_TS.pack(pub_broker_ns) + frame


def unpack_publish(payload: bytes) -> Tuple[str, int, bytes]:
    if len(payload) < _PUB_HEAD.size:
        raise StreamError("truncated publish frame")
    (tlen,) = _PUB_HEAD.unpack_from(payload, 0)
    off = _PUB_HEAD.size + tlen
    if len(payload) < off + _PUB_TS.size:
        raise StreamError("truncated publish frame")
    topic = payload[_PUB_HEAD.size:off].decode()
    (ts,) = _PUB_TS.unpack_from(payload, off)
    return topic, ts, payload[off + _PUB_TS.size:]


class EdgeBroker:
    """The broker service. Threading: MsgServer owns the sockets; all
    state mutations run on reader threads under one lock.

    With `mqtt_port` set (0 = auto-pick), a second listener speaks real
    MQTT 3.1.1 (edge/mqtt_wire.py): stock clients (paho, mosquitto_sub)
    CONNECT/SUBSCRIBE/PUBLISH against it, and topics bridge both ways
    between the MQTT domain and the edge-protocol pub/sub domain —
    reference parity with gst/mqtt's any-broker interop
    (`mqttcommon.h:43-63`) without requiring an external daemon."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 mqtt_port: Optional[int] = 0):
        self._lock = threading.Lock()
        self._registry: Dict[str, dict] = {}          # name → {host,port,owner}
        self._subs: Dict[str, Set[P.Connection]] = {}  # topic → conns
        self._server = P.MsgServer(
            host, port, on_message=self._on_message,
            on_disconnect=self._on_disconnect)
        self._mqtt = None
        if mqtt_port is not None:
            self._mqtt = _MqttListener(self, host, mqtt_port)
        log.info("edge broker on %s:%d (mqtt: %s)", host,
                 self._server.port,
                 self._mqtt.port if self._mqtt else "off")

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def mqtt_port(self) -> Optional[int]:
        return self._mqtt.port if self._mqtt else None

    # -- dispatch ----------------------------------------------------------
    def _on_message(self, conn: P.Connection, mtype: int,
                    payload: bytes) -> None:
        # a standalone broker accepts arbitrary network clients: malformed
        # payloads must NAK/log, never kill the reader thread
        try:
            self._dispatch(conn, mtype, payload)
        except StreamError as e:
            log.warning("broker: bad %d message from conn %d: %s",
                        mtype, conn.client_id, e)
        except (ValueError, KeyError, TypeError, AttributeError,
                UnicodeDecodeError) as e:
            log.warning("broker: malformed %d payload from conn %d: %s",
                        mtype, conn.client_id, e)

    def _dispatch(self, conn: P.Connection, mtype: int,
                  payload: bytes) -> None:
        if mtype == T_TIME:
            conn.send(T_TIME_ACK,
                      _TIME_ACK.pack(payload[:8].ljust(8, b"\0"),
                                     time.time_ns()))
        elif mtype == T_REGISTER:
            self._register(conn, payload)
        elif mtype == T_UNREGISTER:
            name = json.loads(payload.decode()).get("name", "")
            with self._lock:
                ent = self._registry.get(name)
                if ent and ent["owner"] == conn.client_id:
                    del self._registry[name]
        elif mtype == T_LOOKUP:
            name = json.loads(payload.decode()).get("name", "")
            with self._lock:
                ent = self._registry.get(name)
            if ent is None:
                conn.send(T_LOOKUP_NAK,
                          f"no service registered as {name!r}".encode())
            else:
                conn.send(T_LOOKUP_ACK, json.dumps(
                    {"name": name, "host": ent["host"],
                     "port": ent["port"]}).encode())
        elif mtype == T_SUBSCRIBE:
            topic = payload.decode()
            with self._lock:
                self._subs.setdefault(topic, set()).add(conn)
        elif mtype == T_PUBLISH:
            topic, _, frame = unpack_publish(payload)
            with self._lock:
                targets = list(self._subs.get(topic, ()))
            for sub in targets:
                if sub.client_id == conn.client_id:
                    continue   # no self-echo
                try:
                    sub.send(T_PUBLISH, payload)
                except OSError:
                    pass   # reader thread will reap it
            # bridge into the MQTT domain (payload = the bare frame)
            if self._mqtt is not None:
                self._mqtt.fanout(topic, frame, exclude=None)
        else:
            log.warning("broker: unknown message type %d", mtype)

    def _register(self, conn: P.Connection, payload: bytes) -> None:
        try:
            ent = json.loads(payload.decode())
            name, host, port = ent["name"], ent["host"], int(ent["port"])
        except (ValueError, KeyError, TypeError) as e:
            # TypeError covers non-dict JSON / non-castable port: the
            # client must get an immediate NAK, not a 10s RPC timeout
            conn.send(T_REGISTER_NAK, f"bad registration: {e}".encode())
            return
        with self._lock:
            cur = self._registry.get(name)
            if cur is not None and cur["owner"] != conn.client_id:
                conn.send(T_REGISTER_NAK,
                          f"{name!r} already registered by another "
                          f"connection".encode())
                return
            self._registry[name] = dict(host=host, port=port,
                                        owner=conn.client_id)
        conn.send(T_REGISTER_ACK)

    def _on_disconnect(self, conn: P.Connection) -> None:
        with self._lock:
            dead = [n for n, e in self._registry.items()
                    if e["owner"] == conn.client_id]
            for n in dead:
                del self._registry[n]
            for subs in self._subs.values():
                subs.discard(conn)
        if dead:
            log.info("broker: dropped registrations %s (owner left)", dead)

    def services(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return {n: (e["host"], e["port"])
                    for n, e in self._registry.items()}

    def _publish_from_mqtt(self, topic: str, frame: bytes) -> None:
        """Bridge an MQTT-side PUBLISH into edge-protocol subscribers."""
        payload = pack_publish(topic, time.time_ns(), frame)
        with self._lock:
            targets = list(self._subs.get(topic, ()))
        for sub in targets:
            try:
                sub.send(T_PUBLISH, payload)
            except OSError:
                pass

    def close(self) -> None:
        if self._mqtt is not None:
            self._mqtt.close()
        self._server.close()


class _MqttListener:
    """Minimal MQTT 3.1.1 broker listener bridged to the EdgeBroker's
    topic space. QoS 0/1 (QoS 1 acks immediately: at-most-once delivery
    to subscribers, like the reference's default sink QoS), wildcard
    filters (+/#), keepalive via PINGREQ/PINGRESP."""

    def __init__(self, broker: "EdgeBroker", host: str, port: int):
        import socket as _socket

        from nnstreamer_tpu.edge import mqtt_wire as M

        self._M = M
        self._broker = broker
        self._lock = threading.Lock()
        self._conns: Dict[int, dict] = {}    # id → {sock, filters, lock}
        self._next_id = 0
        self._closing = False
        self._srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="mqtt-accept", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        import queue as _q

        while not self._closing:
            try:
                sock, addr = self._srv.accept()
            except OSError:
                return
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                # outbound frames go through a bounded queue + writer
                # thread so a stalled subscriber can NEVER block the
                # publishing thread (which may be an edge-protocol
                # reader via the topic bridge); overflow drops frames —
                # QoS 0 delivery semantics
                self._conns[cid] = dict(sock=sock, filters=[],
                                        outq=_q.Queue(maxsize=256),
                                        client_id="")
            threading.Thread(target=self._reader, args=(cid, sock),
                             name=f"mqtt-conn-{cid}", daemon=True).start()
            threading.Thread(target=self._writer, args=(cid, sock),
                             name=f"mqtt-send-{cid}", daemon=True).start()

    def _writer(self, cid: int, sock) -> None:
        import queue as _q

        with self._lock:
            ent = self._conns.get(cid)
        if ent is None:
            return
        outq = ent["outq"]
        try:
            while True:
                data = outq.get()
                if data is None:
                    return
                sock.sendall(data)
        except OSError:
            pass

    def _send(self, cid: int, data: bytes) -> None:
        import queue as _q

        with self._lock:
            ent = self._conns.get(cid)
        if ent is None:
            return
        try:
            ent["outq"].put_nowait(data)
        except _q.Full:
            log.warning("mqtt conn %d: send queue full, dropping frame",
                        cid)

    def _reader(self, cid: int, sock) -> None:
        M = self._M
        split = M.PacketSplitter()
        connected = False
        try:
            while True:
                data = sock.recv(1 << 16)
                if not data:
                    return
                for p in split.feed(data):
                    if p.ptype == M.CONNECT:
                        client_id, _ka, _clean = M.parse_connect(p)
                        with self._lock:
                            if cid in self._conns:
                                self._conns[cid]["client_id"] = client_id
                        self._send(cid, M.encode_connack(False,
                                                         M.CONNACK_ACCEPTED))
                        connected = True
                    elif not connected:
                        log.warning("mqtt: packet %d before CONNECT",
                                    p.ptype)
                        return
                    elif p.ptype == M.SUBSCRIBE:
                        pid, topics = M.parse_subscribe(p)
                        with self._lock:
                            ent = self._conns.get(cid)
                            if ent is not None:
                                ent["filters"].extend(
                                    t for t, _q in topics)
                        self._send(cid, M.encode_suback(
                            pid, [min(q, 1) for _t, q in topics]))
                    elif p.ptype == M.UNSUBSCRIBE:
                        pid, topics = M.parse_unsubscribe(p)
                        with self._lock:
                            ent = self._conns.get(cid)
                            if ent is not None:
                                ent["filters"] = [
                                    f for f in ent["filters"]
                                    if f not in topics]
                        self._send(cid, M.encode_unsuback(pid))
                    elif p.ptype == M.PUBLISH:
                        M.parse_publish(p)
                        if p.qos == 1:
                            self._send(cid, M.encode_puback(p.packet_id))
                        # MQTT 3.1.1 has no no-local option: a client
                        # subscribed to its own publish topic gets the
                        # echo, exactly like a stock broker
                        self.fanout(p.topic, p.payload, exclude=None)
                        self._broker._publish_from_mqtt(p.topic, p.payload)
                    elif p.ptype == M.PINGREQ:
                        self._send(cid, M.encode_pingresp())
                    elif p.ptype == M.PUBACK:
                        pass                      # QoS1 publisher ack
                    elif p.ptype == M.DISCONNECT:
                        return
                    else:
                        log.warning("mqtt: unsupported packet type %d",
                                    p.ptype)
        except (StreamError, OSError, UnicodeDecodeError, struct.error,
                IndexError, ValueError) as e:
            # truncated/garbage packets from an open network port must
            # log one line, never kill the thread with a traceback
            log.warning("mqtt conn %d: %s: %s", cid, type(e).__name__, e)
        finally:
            with self._lock:
                ent = self._conns.pop(cid, None)
            if ent is not None:
                try:
                    ent["outq"].put_nowait(None)   # stop the writer
                except Exception:
                    pass
            try:
                sock.close()
            except OSError:
                pass

    def fanout(self, topic: str, payload: bytes,
               exclude: Optional[int]) -> None:
        M = self._M
        with self._lock:
            targets = [(cid, ent) for cid, ent in self._conns.items()
                       if cid != exclude
                       and any(M.topic_matches(f, topic)
                               for f in ent["filters"])]
        if not targets:
            return
        pkt = M.encode_publish(topic, payload, qos=0)
        for cid, _ent in targets:
            self._send(cid, pkt)

    def close(self) -> None:
        import socket as _socket

        self._closing = True
        # shutdown() before close(): close() alone does not wake a
        # thread blocked in accept()/recv() on Linux.
        for s in (self._srv,):
            for op in (lambda: s.shutdown(_socket.SHUT_RDWR), s.close):
                try:
                    op()
                except OSError:
                    pass
        with self._lock:
            ents = list(self._conns.values())
            self._conns.clear()
        for e in ents:
            # Wake the writer thread: the reader's finally-block sentinel
            # is skipped once the entry is popped, so enqueue it here.
            try:
                e["outq"].put_nowait(None)
            except Exception:
                pass
            for op in (lambda s=e["sock"]: s.shutdown(_socket.SHUT_RDWR),
                       e["sock"].close):
                try:
                    op()
                except OSError:
                    pass


class BrokerClient:
    """Client handle: register/lookup services, pub/sub topics, and an
    SNTP-style clock-offset estimate against the broker."""

    def __init__(self, host: str, port: int, *,
                 connect_timeout: float = 10.0):
        self._replies: Dict[int, bytes] = {}
        self._reply_lock = threading.Lock()
        self._reply_evt = threading.Condition(self._reply_lock)
        self._sub_cb: Dict[str, Callable[[int, bytes], None]] = {}
        self._client = P.MsgClient(host, port, on_message=self._on_message,
                                   connect_timeout=connect_timeout)
        self._offset_ns: Optional[int] = None

    # -- plumbing ----------------------------------------------------------
    def _on_message(self, mtype: int, payload: bytes) -> None:
        if mtype == T_PUBLISH:
            try:
                topic, pub_ns, frame = unpack_publish(payload)
            except StreamError as e:
                log.error("broker client: %s", e)
                return
            cb = self._sub_cb.get(topic)
            if cb is not None:
                cb(pub_ns, frame)
            return
        with self._reply_evt:
            self._replies[mtype] = payload
            self._reply_evt.notify_all()

    def _rpc(self, send_type: int, payload: bytes, ok: int, nak: int,
             timeout: float, what: str) -> bytes:
        with self._reply_evt:
            self._replies.pop(ok, None)
            self._replies.pop(nak, None)
        self._client.send(send_type, payload)
        deadline = time.monotonic() + timeout
        with self._reply_evt:
            while ok not in self._replies and nak not in self._replies:
                remain = deadline - time.monotonic()
                if remain <= 0 or not self._reply_evt.wait(remain):
                    raise StreamError(
                        f"broker {what} timed out after {timeout}s")
            if nak in self._replies:
                raise StreamError(
                    f"broker {what} refused: "
                    f"{self._replies.pop(nak).decode()}")
            return self._replies.pop(ok)

    # -- discovery ---------------------------------------------------------
    def register(self, name: str, host: str, port: int,
                 timeout: float = 10.0) -> None:
        self._rpc(T_REGISTER,
                  json.dumps({"name": name, "host": host,
                              "port": port}).encode(),
                  T_REGISTER_ACK, T_REGISTER_NAK, timeout,
                  f"registration of {name!r}")

    def unregister(self, name: str) -> None:
        self._client.send(T_UNREGISTER, json.dumps({"name": name}).encode())

    def lookup(self, name: str, timeout: float = 10.0) -> Tuple[str, int]:
        got = self._rpc(T_LOOKUP, json.dumps({"name": name}).encode(),
                        T_LOOKUP_ACK, T_LOOKUP_NAK, timeout,
                        f"lookup of {name!r}")
        ent = json.loads(got.decode())
        return ent["host"], int(ent["port"])

    # -- clock (NTP analog) ------------------------------------------------
    def clock_offset_ns(self, samples: int = 5,
                        timeout: float = 5.0) -> int:
        """Estimate broker_clock - local_clock in ns (SNTP midpoint:
        offset ≈ t1 - (t0+t2)/2 per sample, median over samples).
        Cached; publishers use it to stamp frames in broker time."""
        offs = []
        for i in range(samples):
            tag = struct.pack("<Q", i)
            t0 = time.time_ns()
            got = self._rpc(T_TIME, tag, T_TIME_ACK, -1, timeout,
                            "time exchange")
            t2 = time.time_ns()
            _, t1 = _TIME_ACK.unpack(got)
            offs.append(t1 - (t0 + t2) // 2)
        offs.sort()
        self._offset_ns = offs[len(offs) // 2]
        return self._offset_ns

    def broker_now_ns(self) -> int:
        if self._offset_ns is None:
            self.clock_offset_ns()
        return time.time_ns() + self._offset_ns

    # -- pub/sub -----------------------------------------------------------
    def subscribe(self, topic: str,
                  callback: Callable[[int, bytes], None]) -> None:
        """callback(pub_broker_ns, wire_frame) runs on the reader thread."""
        self._sub_cb[topic] = callback
        self._client.send(T_SUBSCRIBE, topic.encode())

    def publish(self, topic: str, frame: bytes,
                pub_broker_ns: Optional[int] = None) -> None:
        ts = self.broker_now_ns() if pub_broker_ns is None else pub_broker_ns
        self._client.send(T_PUBLISH, pack_publish(topic, ts, frame))

    @property
    def alive(self) -> bool:
        return self._client.alive

    def close(self) -> None:
        self._client.close()
