"""MQTT 3.1.1 wire framing (encode/decode + incremental splitter).

Reference parity: gst/mqtt speaks real MQTT through the paho client
(`mqttcommon.h`, `mqttsink.c`, `mqttsrc.c`), so any stock broker —
mosquitto, EMQX, a cloud endpoint — can carry its tensor streams.
Round-2 VERDICT missing #4: our mqttsink/src spoke only the private
EdgeBroker protocol. This module implements the MQTT 3.1.1 control
packets the tensor path needs (CONNECT/CONNACK, PUBLISH with QoS 0/1 +
PUBACK, SUBSCRIBE/SUBACK, UNSUBSCRIBE/UNSUBACK, PINGREQ/PINGRESP,
DISCONNECT) from the OASIS spec — no external MQTT library.

The payloads carried over PUBLISH are this framework's standard wire
frames (edge/wire.py), so caps/meta/PTS travel exactly as on every
other transport.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple

from nnstreamer_tpu.core.errors import StreamError

# control packet types (high nibble of the fixed-header byte)
CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14

#: CONNACK return codes (3.1.1 §3.2.2.3)
CONNACK_ACCEPTED = 0

_U16 = struct.Struct(">H")


def _encode_remaining(n: int) -> bytes:
    """Remaining-length varint (§2.2.3): 7 bits per byte, MSB=continue."""
    if n < 0 or n > 268_435_455:
        raise StreamError(f"MQTT remaining length {n} out of range")
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def decode_remaining(buf: bytes, pos: int) -> Optional[Tuple[int, int]]:
    """→ (value, bytes_consumed) or None if more bytes are needed."""
    mult, value = 1, 0
    for i in range(4):
        if pos + i >= len(buf):
            return None
        b = buf[pos + i]
        value += (b & 0x7F) * mult
        if not b & 0x80:
            return value, i + 1
        mult *= 128
    raise StreamError("malformed MQTT remaining length (>4 bytes)")


def _mqtt_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise StreamError(f"MQTT string too long ({len(b)} bytes)")
    return _U16.pack(len(b)) + b


def _read_str(payload: bytes, pos: int) -> Tuple[str, int]:
    if pos + 2 > len(payload):
        raise StreamError("truncated MQTT string")
    (n,) = _U16.unpack_from(payload, pos)
    end = pos + 2 + n
    if end > len(payload):
        raise StreamError("truncated MQTT string")
    return payload[pos + 2:end].decode("utf-8"), end


def _packet(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _encode_remaining(len(body)) \
        + body


# -- encoders ---------------------------------------------------------------

def encode_connect(client_id: str, keepalive: int = 60,
                   clean_session: bool = True,
                   username: Optional[str] = None,
                   password: Optional[bytes] = None) -> bytes:
    flags = (0x02 if clean_session else 0)
    if username is not None:
        flags |= 0x80
    if password is not None:
        flags |= 0x40
    body = (_mqtt_str("MQTT") + bytes([4])      # protocol level 4 = 3.1.1
            + bytes([flags]) + _U16.pack(keepalive)
            + _mqtt_str(client_id))
    if username is not None:
        body += _mqtt_str(username)
    if password is not None:
        body += _U16.pack(len(password)) + password
    return _packet(CONNECT, 0, body)


def encode_connack(session_present: bool = False, rc: int = 0) -> bytes:
    return _packet(CONNACK, 0, bytes([1 if session_present else 0, rc]))


def encode_publish(topic: str, payload: bytes, qos: int = 0,
                   packet_id: int = 0, retain: bool = False,
                   dup: bool = False) -> bytes:
    if qos not in (0, 1):
        raise StreamError(f"QoS {qos} not supported (0/1 only)")
    flags = (0x08 if dup else 0) | (qos << 1) | (0x01 if retain else 0)
    body = _mqtt_str(topic)
    if qos:
        body += _U16.pack(packet_id)
    return _packet(PUBLISH, flags, body + payload)


def encode_puback(packet_id: int) -> bytes:
    return _packet(PUBACK, 0, _U16.pack(packet_id))


def encode_subscribe(packet_id: int,
                     topics: List[Tuple[str, int]]) -> bytes:
    body = _U16.pack(packet_id)
    for topic, qos in topics:
        body += _mqtt_str(topic) + bytes([qos])
    return _packet(SUBSCRIBE, 0x02, body)       # §3.8.1 reserved flags


def encode_suback(packet_id: int, rcs: List[int]) -> bytes:
    return _packet(SUBACK, 0, _U16.pack(packet_id) + bytes(rcs))


def encode_unsubscribe(packet_id: int, topics: List[str]) -> bytes:
    body = _U16.pack(packet_id)
    for t in topics:
        body += _mqtt_str(t)
    return _packet(UNSUBSCRIBE, 0x02, body)


def encode_unsuback(packet_id: int) -> bytes:
    return _packet(UNSUBACK, 0, _U16.pack(packet_id))


def encode_pingreq() -> bytes:
    return _packet(PINGREQ, 0, b"")


def encode_pingresp() -> bytes:
    return _packet(PINGRESP, 0, b"")


def encode_disconnect() -> bytes:
    return _packet(DISCONNECT, 0, b"")


# -- decoded packet views ---------------------------------------------------

@dataclass
class Packet:
    ptype: int
    flags: int
    body: bytes

    # PUBLISH fields (filled by parse_publish)
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    packet_id: int = 0


def parse_connect(p: Packet) -> Tuple[str, int, bool]:
    """→ (client_id, keepalive, clean_session); validates magic/level."""
    proto, pos = _read_str(p.body, 0)
    if proto not in ("MQTT", "MQIsdp"):
        raise StreamError(f"not an MQTT CONNECT (protocol {proto!r})")
    level = p.body[pos]
    flags = p.body[pos + 1]
    (keepalive,) = _U16.unpack_from(p.body, pos + 2)
    client_id, _ = _read_str(p.body, pos + 4)
    if level not in (3, 4):
        raise StreamError(f"unsupported MQTT protocol level {level}")
    return client_id, keepalive, bool(flags & 0x02)


def parse_publish(p: Packet) -> Packet:
    p.qos = (p.flags >> 1) & 0x03
    if p.qos > 1:
        raise StreamError("QoS 2 PUBLISH not supported")
    topic, pos = _read_str(p.body, 0)
    if p.qos:
        (p.packet_id,) = _U16.unpack_from(p.body, pos)
        pos += 2
    p.topic = topic
    p.payload = p.body[pos:]
    return p


def parse_subscribe(p: Packet) -> Tuple[int, List[Tuple[str, int]]]:
    (pid,) = _U16.unpack_from(p.body, 0)
    pos = 2
    topics: List[Tuple[str, int]] = []
    while pos < len(p.body):
        t, pos = _read_str(p.body, pos)
        topics.append((t, p.body[pos]))
        pos += 1
    if not topics:
        raise StreamError("SUBSCRIBE with no topics")
    return pid, topics


def parse_unsubscribe(p: Packet) -> Tuple[int, List[str]]:
    (pid,) = _U16.unpack_from(p.body, 0)
    pos = 2
    topics: List[str] = []
    while pos < len(p.body):
        t, pos = _read_str(p.body, pos)
        topics.append(t)
    return pid, topics


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT topic filter match (§4.7): '+' one level, '#' trailing rest."""
    pp = pattern.split("/")
    tt = topic.split("/")
    for i, seg in enumerate(pp):
        if seg == "#":
            return True
        if i >= len(tt):
            return False
        if seg != "+" and seg != tt[i]:
            return False
    return len(pp) == len(tt)


class PacketSplitter:
    """Incremental byte-stream → packet splitter (reader-thread use)."""

    def __init__(self, max_packet: int = 1 << 28):
        self._buf = bytearray()
        self._max = max_packet

    def feed(self, data: bytes) -> List[Packet]:
        self._buf.extend(data)
        out: List[Packet] = []
        while True:
            if len(self._buf) < 2:
                return out
            head = self._buf[0]
            rem = decode_remaining(self._buf, 1)
            if rem is None:
                return out
            length, nlen = rem
            if length > self._max:
                raise StreamError(
                    f"MQTT packet of {length} bytes exceeds cap")
            total = 1 + nlen + length
            if len(self._buf) < total:
                return out
            body = bytes(self._buf[1 + nlen:total])
            del self._buf[:total]
            out.append(Packet(ptype=head >> 4, flags=head & 0x0F,
                              body=body))


class MqttClient:
    """Small MQTT 3.1.1 client (CONNECT, SUBSCRIBE, PUBLISH QoS 0/1,
    keepalive pings) over one TCP socket — what mqttsink/mqttsrc use in
    protocol=mqtt mode against any stock broker."""

    def __init__(self, host: str, port: int, client_id: str = "",
                 keepalive: int = 30, connect_timeout: float = 10.0):
        import os
        import socket
        import threading

        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._evt = threading.Condition()
        self._connack: Optional[int] = None
        self._sub_acks: set = set()
        self._pub_acks: set = set()
        self._subs: List[Tuple[str, object]] = []   # (filter, callback)
        self._next_pid = 1
        self._alive = True
        self._keepalive = keepalive
        cid = client_id or f"nns-tpu-{os.getpid()}-{id(self) & 0xFFFF}"
        self._reader = threading.Thread(
            target=self._read_loop, name="mqtt-client-reader", daemon=True)
        self._reader.start()
        with self._wlock:
            self._sock.sendall(encode_connect(cid, keepalive=keepalive))
        with self._evt:
            deadline = _now() + connect_timeout
            while self._connack is None and self._alive:
                if not self._evt.wait(max(deadline - _now(), 0.001)):
                    break
                if _now() > deadline:
                    break
        if self._connack != CONNACK_ACCEPTED:
            self.close()
            raise StreamError(
                f"MQTT broker {host}:{port} refused connection "
                f"(CONNACK rc={self._connack})")
        self._pinger = threading.Thread(
            target=self._ping_loop, name="mqtt-client-ping", daemon=True)
        self._pinger.start()

    @property
    def alive(self) -> bool:
        return self._alive

    def _read_loop(self) -> None:
        import logging

        split = PacketSplitter()
        try:
            while True:
                data = self._sock.recv(1 << 16)
                if not data:
                    break
                for p in split.feed(data):
                    self._handle(p)
        except (OSError, StreamError, UnicodeDecodeError, struct.error,
                IndexError, ValueError) as e:
            # one corrupt broker frame must not tear down the process
            # with a thread traceback; the connection dies cleanly
            logging.getLogger("nnstreamer_tpu.edge.mqtt").warning(
                "mqtt client reader: %s: %s", type(e).__name__, e)
        finally:
            self._alive = False
            with self._evt:
                self._evt.notify_all()

    def _handle(self, p: Packet) -> None:
        if p.ptype == CONNACK:
            with self._evt:
                self._connack = p.body[1] if len(p.body) > 1 else 0xFF
                self._evt.notify_all()
        elif p.ptype == SUBACK:
            (pid,) = _U16.unpack_from(p.body, 0)
            with self._evt:
                self._sub_acks.add(pid)
                self._evt.notify_all()
        elif p.ptype == PUBACK:
            (pid,) = _U16.unpack_from(p.body, 0)
            with self._evt:
                self._pub_acks.add(pid)
                self._evt.notify_all()
        elif p.ptype == PUBLISH:
            parse_publish(p)
            if p.qos == 1:
                self._send(encode_puback(p.packet_id))
            for filt, cb in list(self._subs):
                if topic_matches(filt, p.topic):
                    cb(p.topic, p.payload)
        elif p.ptype in (PINGRESP, UNSUBACK):
            pass

    def _send(self, data: bytes) -> None:
        try:
            with self._wlock:
                self._sock.sendall(data)
        except OSError:
            self._alive = False

    def _ping_loop(self) -> None:
        import time as _t

        interval = max(self._keepalive / 2.0, 1.0)
        while self._alive:
            _t.sleep(interval)
            if self._alive:
                self._send(encode_pingreq())

    def _pid(self) -> int:
        with self._evt:
            pid = self._next_pid
            self._next_pid = pid % 0xFFFF + 1
            return pid

    def _wait(self, acks: set, pid: int, timeout: float, what: str):
        deadline = _now() + timeout
        with self._evt:
            while pid not in acks:
                if not self._alive:
                    raise StreamError(f"MQTT connection lost during {what}")
                remain = deadline - _now()
                if remain <= 0 or not self._evt.wait(remain):
                    raise StreamError(f"MQTT {what} timed out")
            acks.discard(pid)

    def subscribe(self, topic_filter: str, callback,
                  qos: int = 0, timeout: float = 10.0) -> None:
        """callback(topic, payload) runs on the reader thread."""
        self._subs.append((topic_filter, callback))
        pid = self._pid()
        self._send(encode_subscribe(pid, [(topic_filter, qos)]))
        self._wait(self._sub_acks, pid, timeout, "SUBSCRIBE")

    def publish(self, topic: str, payload: bytes, qos: int = 0,
                timeout: float = 10.0) -> None:
        if qos == 0:
            self._send(encode_publish(topic, payload, qos=0))
            if not self._alive:
                raise StreamError("MQTT connection lost during PUBLISH")
            return
        pid = self._pid()
        self._send(encode_publish(topic, payload, qos=1, packet_id=pid))
        self._wait(self._pub_acks, pid, timeout, "PUBLISH(qos1)")

    def close(self) -> None:
        if self._alive:
            self._send(encode_disconnect())
        self._alive = False
        try:
            self._sock.close()
        except OSError:
            pass


def _now() -> float:
    import time

    return time.monotonic()
