"""Framework exception taxonomy.

The reference's UX rule — long, actionable error strings that tell the
user exactly which property to fix (tensor_filter.c:558-628) — is a
contract here: every raise should name the element, the property, and a
suggested fix where known.
"""

from __future__ import annotations


class NNStreamerTPUError(Exception):
    """Base class for all framework errors."""


class ConfigError(NNStreamerTPUError):
    """Bad configuration file / env var / property value."""


class NegotiationError(NNStreamerTPUError):
    """Static shape/dtype negotiation failed between two linked elements.

    Equivalent of a GStreamer caps-negotiation failure, raised at pipeline
    build time — never in the steady-state loop.
    """


class PipelineError(NNStreamerTPUError):
    """Malformed pipeline description or graph structure."""


class BackendError(NNStreamerTPUError):
    """A filter backend (XLA / custom / pallas) failed to open or invoke."""


class StreamError(NNStreamerTPUError):
    """Runtime dataflow failure (the GST_FLOW_ERROR analog)."""
