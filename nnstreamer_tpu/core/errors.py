"""Framework exception taxonomy.

The reference's UX rule — long, actionable error strings that tell the
user exactly which property to fix (tensor_filter.c:558-628) — is a
contract here: every raise should name the element, the property, and a
suggested fix where known.
"""

from __future__ import annotations

from dataclasses import dataclass


def _rebuild_error(cls, args, state):
    """Pickle reconstructor (see NNStreamerTPUError.__reduce__):
    rebuilds without calling the subclass __init__, then restores args
    and instance state verbatim."""
    exc = cls.__new__(cls)
    Exception.__init__(exc, *args)
    exc.__dict__.update(state)
    return exc


class NNStreamerTPUError(Exception):
    """Base class for all framework errors.

    Every framework error is pickle-round-trip safe: errors cross
    process boundaries in the supervised worker pool (serving/pool.py
    ships them back over a multiprocessing pipe). Subclasses with
    non-default ``__init__`` signatures (`SegmentStageError`,
    `ServerBusyError`) would break naive pickling — which re-invokes
    ``cls(*args)`` — so the base class reduces to a reconstructor that
    bypasses ``__init__`` and restores ``args`` + ``__dict__`` exactly
    (tests/test_faults.py parametrizes the round trip over every
    public error class)."""

    def __reduce__(self):
        return (_rebuild_error,
                (type(self), self.args, dict(self.__dict__)))


class ConfigError(NNStreamerTPUError):
    """Bad configuration file / env var / property value."""


class NegotiationError(NNStreamerTPUError):
    """Static shape/dtype negotiation failed between two linked elements.

    Equivalent of a GStreamer caps-negotiation failure, raised at pipeline
    build time — never in the steady-state loop.
    """


class PipelineError(NNStreamerTPUError):
    """Malformed pipeline description or graph structure."""


class BackendError(NNStreamerTPUError):
    """A filter backend (XLA / custom / pallas) failed to open or invoke."""


class SegmentStageError(BackendError):
    """A member stage of a composed device segment failed (trace or
    host-fallback invoke). Carries the *member element's* name so the
    owning head filter can attribute the failure to the element the
    user wrote, not the surviving head."""

    def __init__(self, member: str, exc: BaseException):
        super().__init__(f"segment stage {member!r} failed: {exc}")
        self.member = member


class StreamError(NNStreamerTPUError):
    """Runtime dataflow failure (the GST_FLOW_ERROR analog)."""


class ServerBusyError(StreamError):
    """A remote query server refused a frame at admission (wire `BUSY`
    reply): its bounded queue was full, its outstanding-request bound
    was hit, or the frame's deadline had already passed. Carries the
    server's view of the overload so callers — and the element
    error-policy machinery — can back off intelligently:
    `retry:N:backoff` on the client re-offers after the backoff,
    `degrade` routes the frame to the fallback pad, `skip` sheds it
    locally."""

    def __init__(self, msg: str, *, queue_depth: int = 0,
                 retry_after_ms: float = 0.0, cause: str = "queue_full",
                 pts=None):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.retry_after_ms = retry_after_ms
        self.cause = cause
        self.pts = pts


class FaultInjected(StreamError):
    """Raised by the `tensor_fault` element's `mode=raise` injection —
    a distinct type so tests and policies can tell injected chaos from
    organic failures."""


class WatchdogStall(StreamError):
    """An element exceeded its stall budget (process() never returned)
    or a queue stayed at capacity beyond its budget, and the watchdog
    was configured to escalate (`watchdog_action="fail"`)."""


class CircuitOpenError(BackendError):
    """The filter's circuit breaker is open: the backend failed K
    consecutive invokes and is cooling down, so invokes are being
    short-circuited without touching the backend. Under
    `error-policy=degrade` the input buffer is served on the fallback
    pad instead; under `skip` it is dropped and counted."""


#: `error-policy` property grammar (per-element, enforced by the
#: scheduler's worker loop):
#:   fail                  — any process() exception stops the pipeline
#:                           (the default; today's fail-fast contract)
#:   skip                  — drop the offending input buffer, count it
#:   retry:N[:backoff_ms]  — re-invoke process() up to N times with
#:                           exponential backoff (backoff_ms, 2x per
#:                           attempt); exhausted retries fall back to
#:                           skip semantics
#:   degrade               — route the *input* buffer to the element's
#:                           fallback src pad (auto-added as its last
#:                           src pad; must be linked, e.g. to a cheaper
#:                           model branch or a sink)
@dataclass(frozen=True)
class ErrorPolicy:
    """Parsed per-element error policy (see grammar above)."""

    kind: str = "fail"            # fail | skip | retry | degrade
    retries: int = 0              # retry budget per buffer (kind=retry)
    backoff_ms: float = 10.0      # first retry delay, doubles per retry

    @staticmethod
    def parse(s: "str | ErrorPolicy") -> "ErrorPolicy":
        if isinstance(s, ErrorPolicy):
            return s
        text = str(s).strip().lower()
        if text in ("fail", "skip", "degrade"):
            return ErrorPolicy(kind=text)
        if text.startswith("retry"):
            parts = text.split(":")
            if len(parts) in (2, 3) and parts[0] == "retry":
                try:
                    retries = int(parts[1])
                    backoff = float(parts[2]) if len(parts) == 3 else 10.0
                except ValueError:
                    pass
                else:
                    if retries >= 1 and backoff >= 0:
                        return ErrorPolicy(kind="retry", retries=retries,
                                           backoff_ms=backoff)
        raise ValueError(
            f"bad error-policy {s!r}; expected one of fail | skip | "
            f"retry:N[:backoff_ms] | degrade (e.g. retry:3:50)"
        )

    def __str__(self):
        if self.kind == "retry":
            return f"retry:{self.retries}:{self.backoff_ms:g}"
        return self.kind


#: shared default — the fail-fast contract every element starts with
FAIL_FAST = ErrorPolicy()
