"""Logging (reference: nnstreamer_log.c/h ml_logi/w/e/d + stacktrace).

Thin layer over python logging with one framework-wide logger tree
(``nnstreamer_tpu.*``) and a fatal-path helper that attaches a formatted
stack trace the way ml_loge_stacktrace does (nnstreamer_log.h:95-107).
"""

from __future__ import annotations

import logging
import os
import traceback

_ROOT = "nnstreamer_tpu"


def get_logger(name: str = "") -> logging.Logger:
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def log_error_with_trace(logger: logging.Logger, msg: str, *args) -> None:
    """Error + current stack (the ml_loge_stacktrace analog)."""
    stack = "".join(traceback.format_stack()[:-1])
    logger.error(msg + "\nstack:\n%s", *args, stack)


def _init_from_env() -> None:
    """NNSTREAMER_TPU_LOG=debug|info|warning|error sets the tree level."""
    level = os.environ.get("NNSTREAMER_TPU_LOG", "").upper()
    if level and hasattr(logging, level):
        logging.basicConfig()
        get_logger().setLevel(getattr(logging, level))


_init_from_env()
