"""Core plumbing services — config, registry, logging, errors (reference L2).

Reference parity: gst/nnstreamer/nnstreamer_conf.c (ini+env config),
nnstreamer_subplugin.c (name→vtable registries), nnstreamer_log.c.
"""

from nnstreamer_tpu.core.errors import (
    BackendError,
    ConfigError,
    NegotiationError,
    PipelineError,
    StreamError,
)
from nnstreamer_tpu.core.config import Config, get_config
from nnstreamer_tpu.core.registry import PluginKind, registry

__all__ = [
    "BackendError",
    "ConfigError",
    "NegotiationError",
    "PipelineError",
    "StreamError",
    "Config",
    "get_config",
    "PluginKind",
    "registry",
]
