"""Deterministic synthetic raster frames for tests and benchmarks.

The reference checks in small generators that synthesize test rasters
(`tests/gen24bBMP.py` in nnstreamer) rather than binary fixtures; this
is the same idea for classifier inputs. Frames are structured —
per-channel gradient, flat color, one saturated block, mild noise — so
a classifier's logits are peaked and argmax is stable under ±1
quantized-step numeric skew; pure noise would give near-uniform logits
whose argmax flips on rounding-mode differences and misreads them as
model error. Arithmetic is int16 + clip (uint8 += wraps modulo 256 and
would punch near-black holes into the saturated block).
"""

from __future__ import annotations

import numpy as np


def synthetic_frames(n: int, seed: int = 42, size: int = 224,
                     block: int = 64) -> np.ndarray:
    """(n, size, size, 3) uint8 structured frames, deterministic in
    `seed`. Block origins are bounded so blocks are never truncated."""
    rng = np.random.default_rng(seed)
    x = np.zeros((n, size, size, 3), np.int16)
    x[..., 0] = np.linspace(0, 255, size, dtype=np.int16)[None, None, :]
    hi = max(size - block + 1, 1)
    for i in range(n):
        x[i, :, :, 1] = rng.integers(0, 256)
        bx, by = rng.integers(0, hi, 2)
        x[i, by:by + block, bx:bx + block, 2] = 255
    noise = rng.integers(0, 30, x.shape)
    return np.clip(x + noise, 0, 255).astype(np.uint8)
