"""Layered configuration (reference: gst/nnstreamer/nnstreamer_conf.c).

Priority, highest first (nnsconf_loadconf:342-480 semantics):

1. Environment: ``NNSTREAMER_TPU_<GROUP>_<KEY>`` (e.g.
   ``NNSTREAMER_TPU_FILTER_DEFAULT_BACKEND=xla``), plus
   ``NNSTREAMER_TPU_PLUGINS`` as an extra plugin search path list.
2. Ini file: path from ``NNSTREAMER_TPU_CONF`` env, else
   ``~/.config/nnstreamer_tpu.ini``, else ``/etc/nnstreamer_tpu.ini``.
3. Built-in defaults.

Unlike the reference there is no dlopen .so scan: subplugins are python
modules. ``[common] plugin_paths`` lists directories whose ``*.py`` files
are imported on demand; importing a plugin module registers it (the
constructor-self-registration analog, nnstreamer_subplugin.c:111-131).
"""

from __future__ import annotations

import configparser
import os
import threading
from pathlib import Path
from typing import Dict, List, Optional

from nnstreamer_tpu.core.errors import ConfigError
from nnstreamer_tpu.core.log import get_logger

log = get_logger("config")

ENV_PREFIX = "NNSTREAMER_TPU_"
CONF_ENV = "NNSTREAMER_TPU_CONF"

_DEFAULTS: Dict[str, Dict[str, str]] = {
    "common": {
        "plugin_paths": "",
        "enable_envvar": "1",
    },
    "filter": {
        # backend auto-detect priority per model extension
        # (nnstreamer.ini.in framework_priority_* analog)
        "priority_stablehlo": "xla",
        "priority_msgpack": "xla",
        "priority_py": "custom",
        "default_backend": "xla",
    },
    "runtime": {
        "queue_capacity": "4",       # per-link buffer queue depth
        "drop_on_overrun": "0",      # leaky-queue behavior
        # scheduler-level chain fusion: run linear chains of cheap
        # single-in/single-out elements in one worker thread (direct
        # call-through, no channel hop per element)
        "chain_fusion": "1",
        # donate freshly-staged input buffers to bucketed XLA invokes
        # (HBM churn reduction; ignored on CPU where XLA aliases host
        # memory anyway)
        "donate_inputs": "1",
        # graph-level device segments: lower maximal linear
        # transform → filter [→ transform → filter]* [→ decoder(device)]
        # runs into ONE bucketed jit (graph/optimize.py fuse_segments)
        # so tensors stay in HBM with one dispatch per segment
        "device_segments": "1",
        # bounded async-dispatch window: max unresolved device results a
        # DEVICE_RESIDENT element may have in flight before the worker
        # blocks on the oldest (caps HBM held by live buffers); 0 = sync
        # after every dispatch
        "max_inflight": "8",
    },
    "serving": {
        # persistent XLA compile cache + bucket manifest for store://
        # models (serving/compile_cache.py); opt-in
        "compile_cache": "0",
        "compile_cache_dir": "~/.cache/nnstreamer_tpu/xla",
    },
}


class Config:
    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._ini: Dict[str, Dict[str, str]] = {}
        self._path = None
        candidates = (
            [path]
            if path
            else [
                os.environ.get(CONF_ENV),
                os.path.expanduser("~/.config/nnstreamer_tpu.ini"),
                "/etc/nnstreamer_tpu.ini",
            ]
        )
        for cand in candidates:
            if cand and Path(cand).is_file():
                self._load_ini(cand)
                self._path = cand
                break

    def _load_ini(self, path: str) -> None:
        parser = configparser.ConfigParser()
        try:
            parser.read(path)
        except configparser.Error as e:
            raise ConfigError(f"failed to parse config file {path}: {e}") from e
        for section in parser.sections():
            self._ini.setdefault(section.lower(), {}).update(
                {k.lower(): v for k, v in parser.items(section)}
            )
        log.debug("loaded config %s", path)

    # -- lookup ------------------------------------------------------------
    def get(self, group: str, key: str, default: Optional[str] = None) -> Optional[str]:
        """env > ini > built-in > `default`
        (nnsconf_get_custom_value_string:557 analog)."""
        group, key = group.lower(), key.lower()
        if self._envvar_enabled():
            env = os.environ.get(f"{ENV_PREFIX}{group.upper()}_{key.upper()}")
            if env is not None:
                return env
        if group in self._ini and key in self._ini[group]:
            return self._ini[group][key]
        return _DEFAULTS.get(group, {}).get(key, default)

    def get_bool(self, group: str, key: str, default: bool = False) -> bool:
        v = self.get(group, key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    def get_int(self, group: str, key: str, default: int = 0) -> int:
        v = self.get(group, key)
        if v is None:
            return default
        try:
            return int(v)
        except ValueError:
            raise ConfigError(
                f"config [{group}] {key}={v!r} is not an integer"
            ) from None

    def get_float(self, group: str, key: str, default: float = 0.0) -> float:
        v = self.get(group, key)
        if v is None:
            return default
        try:
            return float(v)
        except ValueError:
            raise ConfigError(
                f"config [{group}] {key}={v!r} is not a number"
            ) from None

    def plugin_paths(self) -> List[Path]:
        """Directories scanned for plugin modules (env paths first)."""
        paths: List[Path] = []
        env = os.environ.get(f"{ENV_PREFIX}PLUGINS", "")
        ini = self.get("common", "plugin_paths") or ""
        for chunk in (env, ini):
            for p in chunk.split(os.pathsep):
                if p.strip():
                    paths.append(Path(p.strip()).expanduser())
        return paths

    def _envvar_enabled(self) -> bool:
        # Note: consults ini/defaults directly to avoid recursion.
        v = self._ini.get("common", {}).get(
            "enable_envvar", _DEFAULTS["common"]["enable_envvar"]
        )
        return v.strip().lower() in ("1", "true", "yes", "on")

    def dump(self) -> str:
        """Human-readable effective config (nnsconf_dump:628 analog)."""
        lines = [f"# config file: {self._path or '(none)'}"]
        groups = sorted(set(_DEFAULTS) | set(self._ini))
        for g in groups:
            lines.append(f"[{g}]")
            keys = sorted(set(_DEFAULTS.get(g, {})) | set(self._ini.get(g, {})))
            for k in keys:
                lines.append(f"{k} = {self.get(g, k)}")
        return "\n".join(lines)


_global: Optional[Config] = None
_global_lock = threading.Lock()


def get_config() -> Config:
    global _global
    with _global_lock:
        if _global is None:
            _global = Config()
        return _global


def reset_config(path: Optional[str] = None) -> Config:
    """Replace the global config (tests / explicit re-load)."""
    global _global
    with _global_lock:
        _global = Config(path)
        return _global
