"""Subplugin registries (reference: gst/nnstreamer/nnstreamer_subplugin.c).

One name→object table per plugin kind — the reference's per-type
GHashTable (register_subplugin/get_subplugin, nnstreamer_subplugin.h:61-92)
— with python-module loading in place of dlopen: a miss triggers a scan of
the config's plugin paths for ``<name>.py`` / any module that registers
the name at import (constructor-self-registration analog).

Kinds follow the reference set {FILTER, DECODER, CONVERTER, TRAINER}
(nnstreamer_subplugin.h) plus ELEMENT for pipeline-element classes used by
the DSL parser.
"""

from __future__ import annotations

import enum
import importlib
import importlib.util
import sys
import threading
from typing import Any, Callable, Dict, List, Optional

from nnstreamer_tpu.core.config import get_config
from nnstreamer_tpu.core.errors import ConfigError, PipelineError
from nnstreamer_tpu.core.log import get_logger

log = get_logger("registry")


class PluginKind(enum.Enum):
    ELEMENT = "element"
    FILTER = "filter"        # model-execution backends
    DECODER = "decoder"      # tensor→media decoders
    CONVERTER = "converter"  # media→tensor converters
    TRAINER = "trainer"


class Registry:
    def __init__(self):
        self._lock = threading.RLock()
        self._tables: Dict[PluginKind, Dict[str, Any]] = {
            k: {} for k in PluginKind
        }
        self._scanned = False

    def register(self, kind: PluginKind, name: str, obj: Any = None):
        """Register `obj` under (kind, name); usable as a decorator.

        Re-registration of the same name replaces the entry (the reference
        refuses duplicates; replacement is friendlier for notebook reload).
        """
        if obj is None:
            def deco(o):
                self.register(kind, name, o)
                return o
            return deco
        with self._lock:
            if name in self._tables[kind]:
                log.warning("replacing %s plugin %r", kind.value, name)
            self._tables[kind][name] = obj
        return obj

    def unregister(self, kind: PluginKind, name: str) -> bool:
        with self._lock:
            return self._tables[kind].pop(name, None) is not None

    def get(self, kind: PluginKind, name: str) -> Any:
        with self._lock:
            obj = self._tables[kind].get(name)
        if obj is not None:
            return obj
        # lazy path scan (the g_module_open-on-demand analog)
        self._scan_plugin_paths()
        with self._lock:
            obj = self._tables[kind].get(name)
        if obj is None:
            raise PipelineError(
                f"no {kind.value} plugin named {name!r}; registered "
                f"{kind.value}s: {sorted(self._tables[kind]) or '(none)'}. "
                f"Register one with registry.register(PluginKind."
                f"{kind.name}, {name!r}, obj) or add its module directory "
                f"to [common] plugin_paths / $NNSTREAMER_TPU_PLUGINS."
            )
        return obj

    def find(self, kind: PluginKind, name: str) -> Optional[Any]:
        with self._lock:
            return self._tables[kind].get(name)

    def names(self, kind: PluginKind) -> List[str]:
        with self._lock:
            return sorted(self._tables[kind])

    # -- module scanning ---------------------------------------------------
    def _scan_plugin_paths(self) -> None:
        with self._lock:
            if self._scanned:
                return
            self._scanned = True
        for path in get_config().plugin_paths():
            if not path.is_dir():
                log.warning("plugin path %s does not exist", path)
                continue
            for mod_file in sorted(path.glob("*.py")):
                self.load_module(str(mod_file))

    def load_module(self, path: str) -> None:
        """Import a plugin module by file path; importing registers it."""
        mod_name = f"nnstreamer_tpu_plugin_{abs(hash(path)):x}"
        if mod_name in sys.modules:
            return
        spec = importlib.util.spec_from_file_location(mod_name, path)
        if spec is None or spec.loader is None:
            raise ConfigError(f"cannot load plugin module {path}")
        module = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = module
        try:
            spec.loader.exec_module(module)
            log.info("loaded plugin module %s", path)
        except Exception:
            del sys.modules[mod_name]
            raise

    def rescan(self) -> None:
        with self._lock:
            self._scanned = False
        self._scan_plugin_paths()


#: process-wide registry (the reference's static per-type tables)
registry = Registry()


def register_element(name: str) -> Callable:
    """Class decorator: `@register_element("tensor_mux")`."""
    return registry.register(PluginKind.ELEMENT, name)
