"""tensor_src_grpc / tensor_sink_grpc — streaming tensors over real gRPC.

Reference parity: ext/nnstreamer/tensor_source/tensor_src_grpc.c +
tensor_sink/tensor_sink_grpc.c over the shared engine
ext/nnstreamer/extra/nnstreamer_grpc_common.cc. Same contract:

- service nnstreamer.protobuf.TensorService (interop/tensors.proto),
  SendTensors (client-streaming) / RecvTensors (server-streaming);
- every element can run as gRPC *server* or *client* (`server` prop,
  tensor_src_grpc.c:148-160), so all four pairings work:
    sink(server) ← src(client) pull, sink(client) → src(server) push;
- frames are self-describing Tensors messages, so an external process
  with any gRPC stack + the schema can feed or tap a pipeline.

No generated stubs: method handlers and multicallables are registered
by path (grpc generic-handler API), the schema module provides the
serializers. PTS is not part of the interop schema; buffers arrive
without timestamps and downstream elements treat them as live frames.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Iterator, Optional

from google.protobuf import empty_pb2

from nnstreamer_tpu.core.errors import PipelineError, StreamError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.interop import tensors_pb2 as pb
from nnstreamer_tpu.interop.protobuf_codec import buffer_to_msg, msg_to_buffer
from nnstreamer_tpu.graph.pipeline import (
    PropDef, SinkElement, SourceElement, StreamSpec, prop_bool)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("interop.grpc")

_SERVICE = "nnstreamer.protobuf.TensorService"
_SEND = f"/{_SERVICE}/SendTensors"
_RECV = f"/{_SERVICE}/RecvTensors"
_EOS = object()


def _grpc():
    import grpc  # deferred: keep module import cheap for non-gRPC pipelines

    return grpc


def _generic_handler(send_behavior=None, recv_behavior=None):
    grpc = _grpc()
    rpcs = {}
    if send_behavior is not None:
        rpcs["SendTensors"] = grpc.stream_unary_rpc_method_handler(
            send_behavior,
            request_deserializer=pb.Tensors.FromString,
            response_serializer=empty_pb2.Empty.SerializeToString)
    if recv_behavior is not None:
        rpcs["RecvTensors"] = grpc.unary_stream_rpc_method_handler(
            recv_behavior,
            request_deserializer=empty_pb2.Empty.FromString,
            response_serializer=pb.Tensors.SerializeToString)
    return grpc.method_handlers_generic_handler(_SERVICE, rpcs)


def _start_server(handler, host: str, port: int):
    """→ (server, bound_port). port=0 picks a free port."""
    grpc = _grpc()
    from concurrent import futures

    # no SO_REUSEPORT: a port collision must fail loudly, not silently
    # split traffic between two servers
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=8),
                         options=(("grpc.so_reuseport", 0),))
    server.add_generic_rpc_handlers((handler,))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise PipelineError(f"cannot bind gRPC server on {host}:{port}")
    server.start()
    return server, bound


@register_element("tensor_sink_grpc")
class TensorSinkGrpc(SinkElement):
    """Pipeline egress over gRPC.

    server=true: host RecvTensors; every connected external client gets
    the stream (fan-out, per-client bounded queue — a slow client drops
    its own frames, never stalls the pipeline).
    server=false: connect out and SendTensors the stream.
    """

    ELEMENT_NAME = "tensor_sink_grpc"
    WANTS_HOST = True
    PROPS = {
        "host": PropDef(str, "127.0.0.1"),
        "port": PropDef(int, None, "listen/connect port (0 = pick free)"),
        "server": PropDef(prop_bool, True, "host the service vs connect out"),
        "queue_size": PropDef(int, 64, "per-client buffer before dropping"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if self.props["port"] is None:
            raise PipelineError(f"{self.name}: port= is required")
        self._server = None
        self._clients: set = set()
        self._clients_lock = threading.Lock()
        self._sendq: Optional[_queue.Queue] = None
        self._sender: Optional[threading.Thread] = None
        self._send_err: Optional[BaseException] = None
        self.bound_port: Optional[int] = None
        self._rate = None

    def negotiate(self, in_specs):
        self._rate = getattr(in_specs[0], "rate", None)
        return super().negotiate(in_specs)

    # -- server mode -------------------------------------------------------
    def _recv_tensors(self, request, context):
        q: _queue.Queue = _queue.Queue(maxsize=self.props["queue_size"])
        with self._clients_lock:
            self._clients.add(q)
        try:
            while True:
                item = q.get()
                if item is _EOS:
                    return
                yield item
        finally:
            with self._clients_lock:
                self._clients.discard(q)

    # -- client mode -------------------------------------------------------
    def _send_loop(self):
        grpc = _grpc()
        chan = grpc.insecure_channel(f"{self.props['host']}:{self.props['port']}")
        send = chan.stream_unary(
            _SEND,
            request_serializer=pb.Tensors.SerializeToString,
            response_deserializer=empty_pb2.Empty.FromString)

        def frames():
            while True:
                item = self._sendq.get()
                if item is _EOS:
                    return
                yield item

        try:
            # wait_for_ready: don't fail fast if our peer pipeline is
            # still binding its server (startup ordering is unsynchronized)
            send(frames(), wait_for_ready=True)
        except BaseException as e:  # surfaced on the next render()
            self._send_err = e
        finally:
            chan.close()

    # -- element lifecycle ---------------------------------------------------
    def start(self) -> None:
        if self.props["server"]:
            self._server, self.bound_port = _start_server(
                _generic_handler(recv_behavior=self._recv_tensors),
                self.props["host"], self.props["port"])
            log.info("%s: serving RecvTensors on :%d", self.name, self.bound_port)
        else:
            self._sendq = _queue.Queue(maxsize=self.props["queue_size"])
            self._sender = threading.Thread(
                target=self._send_loop, name=f"{self.name}-send", daemon=True)
            self._sender.start()

    def render(self, buf: TensorBuffer) -> None:
        msg = buffer_to_msg(buf, rate=self._rate)
        if self.props["server"]:
            with self._clients_lock:
                clients = list(self._clients)
            for q in clients:
                try:
                    q.put_nowait(msg)
                except _queue.Full:
                    pass  # that client lags; drop its frame, not the stream
        else:
            if self._send_err is not None:
                raise StreamError(
                    f"{self.name}: gRPC send stream failed: {self._send_err}")
            self._sendq.put(msg)

    @staticmethod
    def _signal_eos(q: _queue.Queue) -> None:
        """Non-blocking EOS: on a full queue (stalled client), drop one
        frame to make room — never hang teardown on a slow reader."""
        try:
            q.put_nowait(_EOS)
        except _queue.Full:
            try:
                q.get_nowait()
                q.put_nowait(_EOS)
            except (_queue.Empty, _queue.Full):
                pass

    def stop(self) -> None:
        if self._server is not None:
            with self._clients_lock:
                clients = list(self._clients)
            for q in clients:
                self._signal_eos(q)
            self._server.stop(grace=0.5)
            self._server = None
        if self._sender is not None:
            self._signal_eos(self._sendq)
            self._sender.join(timeout=5)
            if self._sender.is_alive():
                log.warning(
                    "tensor_sink_grpc %s: sender thread %s still alive "
                    "after 5s join at stop — wedged gRPC stream leaked",
                    self.name, self._sender.name)
            self._sender = None


@register_element("tensor_src_grpc")
class TensorSrcGrpc(SourceElement):
    """Pipeline ingress over gRPC.

    server=true: host SendTensors; external clients stream frames in.
    server=false: connect out and pull via RecvTensors.
    Output spec comes from dims=/types= or is sniffed from frame 1
    (ipc_src convention).
    """

    ELEMENT_NAME = "tensor_src_grpc"
    PROPS = {
        "host": PropDef(str, "127.0.0.1"),
        "port": PropDef(int, None, "listen/connect port (0 = pick free)"),
        "server": PropDef(prop_bool, True),
        "dims": PropDef(str, "", "expected dims (else sniffed from frame 1)"),
        "types": PropDef(str, "float32"),
        "sniff_timeout": PropDef(float, 10.0, "first-frame wait, s"),
        "queue_size": PropDef(int, 64),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if self.props["port"] is None:
            raise PipelineError(f"{self.name}: port= is required")
        self._q: _queue.Queue = _queue.Queue(maxsize=self.props["queue_size"])
        self._stop = threading.Event()
        self._server = None
        self._puller: Optional[threading.Thread] = None
        self._pull_err: Optional[BaseException] = None
        self._sniffed: Optional[TensorBuffer] = None
        self.bound_port: Optional[int] = None

    # -- server mode ---------------------------------------------------------
    def _enqueue(self, msg) -> bool:
        """Bounded put that keeps observing _stop: a stopped pipeline no
        longer drains _q, and a blocking put would park a non-daemon gRPC
        executor thread forever (hanging interpreter exit)."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _send_tensors(self, request_iterator, context):
        for msg in request_iterator:
            if not self._enqueue(msg):
                break
        return empty_pb2.Empty()

    # -- client mode ---------------------------------------------------------
    def _pull_loop(self):
        grpc = _grpc()
        chan = grpc.insecure_channel(f"{self.props['host']}:{self.props['port']}")
        recv = chan.unary_stream(
            _RECV,
            request_serializer=empty_pb2.Empty.SerializeToString,
            response_deserializer=pb.Tensors.FromString)
        try:
            for msg in recv(empty_pb2.Empty(), wait_for_ready=True):
                if not self._enqueue(msg):
                    break
        except BaseException as e:
            if not self._stop.is_set():
                self._pull_err = e
        finally:
            chan.close()
            # clean end-of-stream with a live consumer: deliver every
            # queued frame (stop-aware bounded put); only if the pipeline
            # already stopped fall back to the frame-dropping variant
            if not self._enqueue(_EOS):
                TensorSinkGrpc._signal_eos(self._q)

    def _ensure_running(self):
        if self.props["server"]:
            if self._server is None:
                self._server, self.bound_port = _start_server(
                    _generic_handler(send_behavior=self._send_tensors),
                    self.props["host"], self.props["port"])
                log.info("%s: serving SendTensors on :%d",
                         self.name, self.bound_port)
        elif self._puller is None:
            self._puller = threading.Thread(
                target=self._pull_loop, name=f"{self.name}-pull", daemon=True)
            self._puller.start()

    def _next_msg(self, timeout: float):
        try:
            return self._q.get(timeout=timeout)
        except _queue.Empty:
            return None

    def output_spec(self) -> StreamSpec:
        if self.props["dims"]:
            return TensorsSpec.from_strings(self.props["dims"],
                                            self.props["types"])
        self._ensure_running()
        msg = self._next_msg(self.props["sniff_timeout"])
        if msg is None or msg is _EOS:
            raise PipelineError(
                f"{self.name}: no frame arrived within "
                f"{self.props['sniff_timeout']}s to sniff the stream type; "
                f"declare dims=/types= to negotiate without sniffing")
        self._sniffed = msg_to_buffer(msg)
        return self._sniffed.spec()

    def generate(self) -> Iterator[TensorBuffer]:
        self._ensure_running()
        if self._sniffed is not None:
            yield self._sniffed
            self._sniffed = None
        while not self._stop.is_set():
            msg = self._next_msg(0.1)
            if msg is _EOS:
                if self._pull_err is not None:
                    raise StreamError(
                        f"{self.name}: gRPC receive stream failed: "
                        f"{self._pull_err}")
                return
            if msg is None:
                continue
            yield msg_to_buffer(msg)

    def interrupt(self) -> None:
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
