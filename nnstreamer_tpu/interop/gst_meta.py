"""GstTensorMetaInfo v1 header codec — the reference's self-describing
per-tensor wire header, for interop payloads.

Reference: tensor_typedef.h:268-296 (struct), nnstreamer_plugin_api_util_impl.c
:1130-1145 (version macros, v1 header size 128), :1288-1330 (update/parse).
Layout, little-endian u32, zero-padded to 128 bytes:

  [0]      version   0xDE000000 | major<<12 | minor   (v1 = 0xDE001000)
  [1]      type      tensor dtype enum (== our DType values 0..9)
  [2..17]  dimension innermost-first, zero-terminated (rank = #nonzero prefix)
  [18]     format    static=0 / flexible=1 / sparse=2
  [19]     media     media type enum (tensor=0)
  [20]     nnz       sparse non-zero count (union GstSparseTensorInfo)

This is distinct from tensor/meta.py (our own richer TPUT header used on
in-framework flexible streams): interop codecs speak the reference layout
so an unmodified nnstreamer can parse flexible frames we produce.
"""

from __future__ import annotations

import struct
from typing import Tuple

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat

HEADER_SIZE = 128
META_RANK_LIMIT = 16
VERSION_V1 = 0xDE000000 | (1 << 12)
_MASK_VALID = 0xDE000000

#: dtypes expressible in the interop enums (values 0..9 match our DType).
WIRE_DTYPES = frozenset(range(10))
PAD_RANK = 4  # NNS_TENSOR_RANK_LIMIT in the reference wire convention


def check_wire_dtype(dt: DType) -> None:
    if int(dt) not in WIRE_DTYPES:
        raise StreamError(
            f"dtype {dt.type_name} has no interop encoding (the reference "
            f"enum stops at uint64); insert "
            f"`tensor_transform mode=typecast option=float32` upstream"
        )


def wire_dims(shape) -> list:
    """numpy shape → innermost-first dims padded with 1 to rank 4
    (gst_tensor_parse_dimension pads with 1,
    nnstreamer_plugin_api_util_impl.c:911-912)."""
    dims = [int(d) for d in reversed(tuple(shape))]
    while len(dims) < PAD_RANK:
        dims.append(1)
    return dims


def shape_from_wire(dims) -> tuple:
    """Inverse of wire_dims: strip the trailing pad-1s, reverse. Rank is
    not on the wire, so trailing 1-dims are canonicalized away; exact
    shapes travel in the GstTensorMetaInfo header on FLEXIBLE streams."""
    ds = [int(d) for d in dims]
    while len(ds) > 1 and ds[-1] == 1:
        ds.pop()
    return tuple(reversed(ds))


def payload_to_array(raw: bytes, dims, dtype: DType, fmt: TensorFormat,
                     label: str):
    """Decode one interop tensor payload → numpy array. Shared by all
    three codecs; every corruption mode (bad header, size mismatch,
    truncated buffer) surfaces as StreamError — the codec contract."""
    import math

    import numpy as np

    try:
        if fmt != TensorFormat.STATIC and len(raw) >= HEADER_SIZE:
            shape, hdt, _, _, _, off = parse_gst_meta(raw)
            return np.frombuffer(raw, hdt.np_dtype, offset=off,
                                 count=math.prod(shape)
                                 ).reshape(shape).copy()
        shape = shape_from_wire(dims)
        n = math.prod(shape) if shape else 1
        if n * dtype.itemsize != len(raw):
            raise StreamError(
                f"{label}: {len(raw)} payload bytes != {n} elements of "
                f"{dtype.type_name} from dims {list(dims)}")
        return np.frombuffer(raw, dtype.np_dtype).reshape(shape).copy()
    except StreamError:
        raise
    except (ValueError, TypeError) as e:   # truncated/corrupt buffers
        raise StreamError(f"{label}: corrupt tensor payload: {e}") from None


def pack_gst_meta(shape: Tuple[int, ...], dtype: DType,
                  fmt: TensorFormat = TensorFormat.FLEXIBLE,
                  media: int = 0, nnz: int = 0) -> bytes:
    """numpy-order shape → 128-byte GstTensorMetaInfo v1 header."""
    dims = [int(d) for d in reversed(shape)] or [1]
    if len(dims) > META_RANK_LIMIT:
        raise StreamError(
            f"rank {len(dims)} exceeds the interop header limit "
            f"{META_RANK_LIMIT} (NNS_TENSOR_META_RANK_LIMIT)"
        )
    if any(d <= 0 for d in dims):
        # zero terminates the dim list in this layout, so zero-sized
        # tensors cannot travel in reference-flexible frames
        raise StreamError(
            f"zero/negative dim in shape {shape} not representable in a "
            f"GstTensorMetaInfo header (dims are zero-terminated)"
        )
    dims += [0] * (META_RANK_LIMIT - len(dims))
    head = struct.pack("<21I", VERSION_V1, int(dtype), *dims,
                       int(fmt), int(media), int(nnz))
    return head + b"\x00" * (HEADER_SIZE - len(head))


def parse_gst_meta(data: bytes):
    """Parse header from the front of data →
    (shape numpy-order, DType, TensorFormat, media, nnz, header_size)."""
    if len(data) < HEADER_SIZE:
        raise StreamError(
            f"buffer too small for GstTensorMetaInfo header: {len(data)} "
            f"< {HEADER_SIZE}"
        )
    vals = struct.unpack_from("<21I", data, 0)
    version = vals[0]
    # mask the FULL tag byte: (v & 0xDE000000) == 0xDE000000 would also
    # accept 0xFF/0xFE/0xDF tags (bit-superset false positives)
    if (version & 0xFF000000) != _MASK_VALID:
        raise StreamError(
            f"bad GstTensorMetaInfo version 0x{version:08x}; not a "
            f"reference-flexible tensor payload"
        )
    dtype = DType(vals[1])
    dims = []
    for d in vals[2:2 + META_RANK_LIMIT]:
        if d == 0:
            break
        dims.append(int(d))
    if not dims:
        raise StreamError("corrupt GstTensorMetaInfo: empty dimension list")
    fmt = TensorFormat(vals[18])
    return tuple(reversed(dims)), dtype, fmt, vals[19], vals[20], HEADER_SIZE
