"""FlatBuffers tensor-stream codec — the schema'd binary interop format.

Reference parity: tensordec-flatbuf.cc + tensor_converter_flatbuf.cc over
the nnstreamer.fbs schema (ext/nnstreamer/include/nnstreamer.fbs):

    table Tensor  { name:string; type:int=NNS_END; dimension:[uint];
                    data:[ubyte]; }
    struct frame_rate { rate_n:int; rate_d:int; }
    table Tensors { num_tensor:int; fr:frame_rate; tensor:[Tensor];
                    format:int=0; }  // root_type Tensors

No flatc on the build host, so tables are built/read with the raw
flatbuffers Builder/Table API; the vtable slot layout (slot i → voffset
4+2i) *is* the schema contract, matching what flatc would generate, so
frames interop with any consumer compiled from nnstreamer.fbs. Same
dim/payload conventions as the protobuf codec (innermost-first rank-4
1-padded dims; GstTensorMetaInfo-prefixed FLEXIBLE payloads).
"""

from __future__ import annotations


import flatbuffers
import numpy as np
from flatbuffers import number_types as NT
from flatbuffers.table import Table

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.interop._codec_base import register_codec_pair
from nnstreamer_tpu.interop.gst_meta import (
    check_wire_dtype,
    pack_gst_meta,
    payload_to_array,
    wire_dims,
)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat

_NNS_END = 10   # schema default for Tensor.type


def encode_flatbuf(buf: TensorBuffer, rate=None) -> bytes:
    """TensorBuffer → flatbuffers frame (nnstreamer.fbs layout)."""
    b = flatbuffers.Builder(1024)
    non_static = buf.format != TensorFormat.STATIC
    frac = rate if isinstance(rate, tuple) else (rate or 0, 1)

    tensor_offs = []
    for i, t in enumerate(buf.tensors):
        arr = np.ascontiguousarray(np.asarray(t))
        dt = DType.from_np(arr.dtype)
        check_wire_dtype(dt)
        raw = arr.tobytes()
        if non_static:
            raw = pack_gst_meta(arr.shape, dt, buf.format) + raw
        name_off = b.CreateString(
            str(buf.meta.get("tensor_names", {}).get(i, "")))
        data_off = b.CreateByteVector(raw)
        dims = wire_dims(arr.shape)
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependUint32(d)
        dim_off = b.EndVector()
        # table Tensor: slots name=0, type=1, dimension=2, data=3
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name_off, 0)
        b.PrependInt32Slot(1, int(dt), _NNS_END)
        b.PrependUOffsetTRelativeSlot(2, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data_off, 0)
        tensor_offs.append(b.EndObject())

    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    vec_off = b.EndVector()

    # table Tensors: num_tensor=0, fr=1 (inline struct), tensor=2, format=3
    b.StartObject(4)
    b.PrependInt32Slot(0, buf.num_tensors, 0)
    b.Prep(4, 8)                      # struct frame_rate {int;int}
    b.PrependInt32(int(frac[1]))      # rate_d (last field first)
    b.PrependInt32(int(frac[0]))      # rate_n
    b.PrependStructSlot(1, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(2, vec_off, 0)
    b.PrependInt32Slot(3, int(buf.format), 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def _slot(tab: Table, slot: int) -> int:
    return tab.Offset(4 + 2 * slot)


def decode_flatbuf(frame: bytes) -> TensorBuffer:
    """flatbuffers frame → TensorBuffer (host numpy)."""
    buf = bytearray(frame)
    try:
        root_pos = flatbuffers.encode.Get(flatbuffers.packer.uoffset, buf, 0)
        tab = Table(buf, root_pos)
        o = _slot(tab, 0)
        num = tab.Get(NT.Int32Flags, o + tab.Pos) if o else 0
        o = _slot(tab, 3)
        fmt = TensorFormat(tab.Get(NT.Int32Flags, o + tab.Pos) if o else 0)
        vo = _slot(tab, 2)
        n_vec = tab.VectorLen(vo) if vo else 0
    except (Exception,) as e:
        raise StreamError(f"corrupt flatbuf tensor frame: {e}") from None
    if num != n_vec:
        raise StreamError(
            f"flatbuf frame: num_tensor={num} but tensor vector has "
            f"{n_vec} entries")
    arrays, names = [], {}
    for j in range(n_vec):
        try:
            x = tab.Vector(vo) + j * 4
            ttab = Table(buf, tab.Indirect(x))
            so = _slot(ttab, 0)
            name = (ttab.String(so + ttab.Pos).decode()
                    if so else "")
            to = _slot(ttab, 1)
            dt = DType(ttab.Get(NT.Int32Flags, to + ttab.Pos)
                       if to else _NNS_END)
            do = _slot(ttab, 2)
            dims = []
            if do:
                for k in range(ttab.VectorLen(do)):
                    dims.append(ttab.Get(
                        NT.Uint32Flags, ttab.Vector(do) + k * 4))
            bo = _slot(ttab, 3)
            if not bo:
                raise StreamError("tensor entry without data")
            dstart = ttab.Vector(bo)
            raw = bytes(buf[dstart:dstart + ttab.VectorLen(bo)])
        except StreamError:
            raise
        except Exception as e:
            raise StreamError(
                f"corrupt flatbuf tensor frame at tensor {j}: {e}"
            ) from None
        arr = payload_to_array(raw, dims, dt, fmt,
                               f"flatbuf tensor {j}")
        arrays.append(arr)
        if name:
            names[j] = name
    meta = {"tensor_names": names} if names else {}
    return TensorBuffer(tensors=tuple(arrays), format=fmt, meta=meta)


FlatbufEncode, FlatbufDecode = register_codec_pair(
    "flatbuf", encode_flatbuf, decode_flatbuf)
