"""Dependency-free FlexBuffers reader (decode only).

FlexBuffers is flatbuffers' schema-less sibling; TFLite custom-op
options and the reference's flexbuf tensor frames use it.  The repo's
other wire formats already have from-scratch readers (protowire for
protobuf, modelio/tflite.py's flatbuffer walker); this closes the gap so
custom-op ingestion (modelio/tflite.py) and flexbuf frame decode work
without the external ``flatbuffers`` package installed.

Format (public spec, mirrors flexbuffers.h semantics):
  buffer = [...values...][root value: root_w bytes][packed type][root_w]
  packed_type = (type << 2) | log2(child byte width)
  offset types store a uint at the value position; target address is
  ``value_pos - offset`` (offsets point backwards).
  vector: length at addr-w, elements at addr (w bytes each), 1 packed
  type byte per element after the elements.
  map: vector of values + keys-vector pointer at addr-3w (own byte
  width at addr-2w); keys vector is a typed vector of KEYs.
  string/blob: length at addr-w, bytes at addr.  key: NUL-terminated.

Tested byte-for-byte against the stock ``flatbuffers.flexbuffers``
builder in tests/test_interop.py.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List

# type enum (flexbuffers.h FBT_*)
_NULL, _INT, _UINT, _FLOAT, _KEY, _STRING = 0, 1, 2, 3, 4, 5
_INDIRECT_INT, _INDIRECT_UINT, _INDIRECT_FLOAT = 6, 7, 8
_MAP, _VECTOR = 9, 10
_VECTOR_INT, _VECTOR_UINT, _VECTOR_FLOAT, _VECTOR_KEY = 11, 12, 13, 14
_VECTOR_STRING_DEPR = 15
_VECTOR_INT2, _VECTOR_FLOAT4 = 16, 24   # fixed typed vectors span 16..24
_BLOB, _BOOL, _VECTOR_BOOL = 25, 26, 36

_UINT_FMT = {1: "<B", 2: "<H", 4: "<I", 8: "<Q"}
_INT_FMT = {1: "<b", 2: "<h", 4: "<i", 8: "<q"}
_FLT_FMT = {2: "<e", 4: "<f", 8: "<d"}


class FlexDecodeError(ValueError):
    pass


def _scalar(fmts, data: bytes, off: int, w: int):
    # Bounds-check every dereference: corrupt offsets must raise, not
    # read from the buffer tail via Python negative indexing.
    if off < 0 or off + w > len(data):
        raise FlexDecodeError(f"offset {off} (+{w}) out of bounds")
    try:
        return struct.unpack_from(fmts[w], data, off)[0]
    except (KeyError, struct.error) as e:
        raise FlexDecodeError(f"bad scalar at {off}: {e}") from None


def _u(data: bytes, off: int, w: int) -> int:
    return _scalar(_UINT_FMT, data, off, w)


def _i(data: bytes, off: int, w: int) -> int:
    return _scalar(_INT_FMT, data, off, w)


def _f(data: bytes, off: int, w: int) -> float:
    return _scalar(_FLT_FMT, data, off, w)


def _indirect(data: bytes, off: int, parent_w: int) -> int:
    addr = off - _u(data, off, parent_w)
    if addr < 0:
        raise FlexDecodeError(f"backward offset at {off} underflows")
    return addr


def _key(data: bytes, addr: int) -> str:
    if addr < 0 or addr >= len(data):
        raise FlexDecodeError(f"key offset {addr} out of bounds")
    end = data.find(b"\x00", addr)
    if end < 0:
        raise FlexDecodeError(f"unterminated key at {addr}")
    try:
        return data[addr:end].decode("utf-8")
    except UnicodeDecodeError as e:
        raise FlexDecodeError(f"key at {addr} is not utf-8: {e}") \
            from None


def _types_start(data: bytes, addr: int, w: int, n: int) -> int:
    """Start of a vector/map's trailing per-element type bytes, bounds
    checked so truncated buffers raise instead of IndexError."""
    start = addr + n * w
    if start + n > len(data):
        raise FlexDecodeError(
            f"vector type bytes at {start} (+{n}) exceed buffer")
    return start


def _typed_vector(data: bytes, addr: int, w: int, elem_type: int,
                  length: int) -> List[Any]:
    out: List[Any] = []
    for idx in range(length):
        pos = addr + idx * w
        if elem_type == _INT:
            out.append(_i(data, pos, w))
        elif elem_type == _UINT:
            out.append(_u(data, pos, w))
        elif elem_type == _FLOAT:
            out.append(_f(data, pos, w))
        elif elem_type == _BOOL:
            out.append(bool(_u(data, pos, w)))
        elif elem_type == _KEY:
            out.append(_key(data, _indirect(data, pos, w)))
        else:
            raise FlexDecodeError(f"typed vector of type {elem_type}")
    return out


def _ref(data: bytes, off: int, parent_w: int, packed: int) -> Any:
    t, child_w = packed >> 2, 1 << (packed & 3)
    if t == _NULL:
        return None
    if t == _INT:
        return _i(data, off, parent_w)
    if t in (_UINT, _BOOL):
        v = _u(data, off, parent_w)
        return bool(v) if t == _BOOL else v
    if t == _FLOAT:
        return _f(data, off, parent_w)
    # everything below is an offset type
    addr = _indirect(data, off, parent_w)
    if t == _KEY:
        return _key(data, addr)
    if t in (_STRING, _BLOB):
        n = _u(data, addr - child_w, child_w)
        if addr + n > len(data):
            raise FlexDecodeError(
                f"{'string' if t == _STRING else 'blob'} length {n} at "
                f"{addr} exceeds buffer")
        raw = data[addr:addr + n]
        if t == _BLOB:
            return bytes(raw)
        try:
            return raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise FlexDecodeError(
                f"string at {addr} is not utf-8: {e}") from None
    if t == _INDIRECT_INT:
        return _i(data, addr, child_w)
    if t == _INDIRECT_UINT:
        return _u(data, addr, child_w)
    if t == _INDIRECT_FLOAT:
        return _f(data, addr, child_w)
    if t == _MAP:
        n = _u(data, addr - child_w, child_w)
        keys_w = _u(data, addr - 2 * child_w, child_w)
        keys_addr = _indirect(data, addr - 3 * child_w, child_w)
        keys = _typed_vector(data, keys_addr, keys_w, _KEY, n)
        types_at = _types_start(data, addr, child_w, n)
        out: Dict[str, Any] = {}
        for idx in range(n):
            out[keys[idx]] = _ref(data, addr + idx * child_w, child_w,
                                  data[types_at + idx])
        return out
    if t == _VECTOR:
        n = _u(data, addr - child_w, child_w)
        types_at = _types_start(data, addr, child_w, n)
        return [_ref(data, addr + idx * child_w, child_w,
                     data[types_at + idx]) for idx in range(n)]
    if _VECTOR_INT <= t <= _VECTOR_STRING_DEPR or t == _VECTOR_BOOL:
        n = _u(data, addr - child_w, child_w)
        elem = _BOOL if t == _VECTOR_BOOL else (
            _KEY if t >= _VECTOR_KEY else t - _VECTOR_INT + _INT)
        return _typed_vector(data, addr, child_w, elem, n)
    if _VECTOR_INT2 <= t <= _VECTOR_FLOAT4:
        n = (t - _VECTOR_INT2) // 3 + 2
        elem = (t - _VECTOR_INT2) % 3 + _INT
        return _typed_vector(data, addr, child_w, elem, n)
    raise FlexDecodeError(f"unsupported flexbuffer type {t}")


def flexbuf_loads(data: bytes) -> Any:
    """Decode a whole FlexBuffers buffer to plain Python values."""
    if len(data) < 3:
        raise FlexDecodeError("flexbuffer too short")
    root_w = data[-1]
    if root_w not in _UINT_FMT:
        raise FlexDecodeError(f"bad root byte width {root_w}")
    if len(data) < 2 + root_w:
        raise FlexDecodeError("flexbuffer shorter than its root value")
    packed = data[-2]
    return _ref(data, len(data) - 2 - root_w, root_w, packed)
