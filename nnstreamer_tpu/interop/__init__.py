"""Interop serialization + RPC: published formats external processes speak.

The private wire codec (edge/wire.py) is for nnstreamer_tpu↔nnstreamer_tpu
links; this package covers the reference's schema'd interop surface
(SURVEY.md §2.4 flatbuf/flexbuf/protobuf codec pairs, §2.5 gRPC):

- protobuf_codec — nnstreamer.protobuf.Tensors frames (tensors.proto)
- flatbuf_codec  — nnstreamer.fbs flatbuffers frames (raw Builder/Table,
                   no flatc needed)
- flexbuf_codec  — schema-less flexbuffers map frames
- gst_meta       — GstTensorMetaInfo v1 header for flexible payloads
- grpc_elements  — tensor_src_grpc / tensor_sink_grpc over real gRPC

Importing the codec modules registers decoder modes "protobuf"/
"flexbuf"/"flatbuf"
and converter subplugins of the same names.
"""

from nnstreamer_tpu.interop import tensors_pb2  # noqa: F401
