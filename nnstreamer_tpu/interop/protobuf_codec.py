"""Protobuf tensor-stream codec — schema'd interop serialization.

Reference parity: tensordec-protobuf.cc + tensor_converter_protobuf.cc
(both thin wrappers over ext/nnstreamer/extra/nnstreamer_protobuf.cc).
Unlike edge/wire.py (our private schema-free codec), this format is a
published contract: any external process holding interop/tensors.proto —
including an unmodified nnstreamer with its protobuf subplugins — can
produce and consume these frames.

Pipeline usage mirrors the reference:

    ... ! tensor_decoder mode=protobuf ! <byte transport> !
    tensor_converter mode=custom:protobuf ! ...

Format semantics (from nnstreamer_protobuf.cc:60-200):
  - dimension[] is innermost-first (reverse numpy shape), padded with 1
    to rank 4 (gst_tensor_parse_dimension pads with 1,
    nnstreamer_plugin_api_util_impl.c:911-912). Rank is not on the wire,
    so decode canonicalizes by stripping trailing 1-dims.
  - FLEXIBLE/SPARSE: each data blob is prefixed with a GstTensorMetaInfo
    v1 header (interop/gst_meta.py — the reference's own layout), which
    *does* preserve exact rank/shape; the padded dims are advisory.
  - float16/bfloat16 have no slot in the 10-value enum; encoding them
    raises with a typecast hint rather than shipping wrong bytes.
"""

from __future__ import annotations


import numpy as np

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.interop import tensors_pb2 as pb
from nnstreamer_tpu.interop._codec_base import register_codec_pair
from nnstreamer_tpu.interop.gst_meta import (
    check_wire_dtype,
    pack_gst_meta,
    payload_to_array,
    wire_dims,
)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat

def buffer_to_msg(buf: TensorBuffer, rate=None) -> "pb.Tensors":
    """TensorBuffer → nnstreamer.protobuf.Tensors message."""
    msg = pb.Tensors()
    msg.num_tensor = buf.num_tensors
    msg.format = int(buf.format)
    if rate is not None and rate:
        frac = rate if isinstance(rate, tuple) else (rate, 1)
        msg.fr.rate_n, msg.fr.rate_d = int(frac[0]), int(frac[1])
    non_static = buf.format != TensorFormat.STATIC
    for i, t in enumerate(buf.tensors):
        arr = np.ascontiguousarray(np.asarray(t))
        dt = DType.from_np(arr.dtype)
        check_wire_dtype(dt)
        entry = msg.tensor.add()
        entry.name = str(buf.meta.get("tensor_names", {}).get(i, ""))
        entry.type = int(dt)
        entry.dimension.extend(wire_dims(arr.shape))
        raw = arr.tobytes()
        if non_static:
            # flexible/sparse payloads carry a GstTensorMetaInfo header
            # so exact shape survives the rank-4 dims
            # (nnstreamer_protobuf.cc:80, is_flexible branch)
            raw = pack_gst_meta(arr.shape, dt, buf.format) + raw
        entry.data = raw
    return msg


def encode_protobuf(buf: TensorBuffer, rate=None) -> bytes:
    """TensorBuffer → serialized Tensors frame."""
    return buffer_to_msg(buf, rate).SerializeToString()


def decode_protobuf(frame: bytes) -> TensorBuffer:
    """Serialized Tensors frame → TensorBuffer (host numpy)."""
    msg = pb.Tensors()
    try:
        msg.ParseFromString(bytes(frame))
    except Exception as e:
        raise StreamError(f"corrupt protobuf tensor frame: {e}") from None
    return msg_to_buffer(msg)


def msg_to_buffer(msg: "pb.Tensors") -> TensorBuffer:
    """Tensors message → TensorBuffer (host numpy)."""
    fmt = TensorFormat(msg.format)
    arrays, names = [], {}
    for i, entry in enumerate(msg.tensor):
        dt = DType(entry.type)
        raw = entry.data
        arr = payload_to_array(raw, entry.dimension, dt, fmt,
                               f"protobuf tensor #{i}")
        arrays.append(arr)
        if entry.name:
            names[i] = entry.name
    meta = {"tensor_names": names} if names else {}
    return TensorBuffer(tensors=tuple(arrays), format=fmt, meta=meta)


ProtobufEncode, ProtobufDecode = register_codec_pair(
    "protobuf", encode_protobuf, decode_protobuf)
