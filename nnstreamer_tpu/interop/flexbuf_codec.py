"""FlexBuffers tensor-stream codec — schema-less interop serialization.

Reference parity: tensordec-flexbuf.cc + tensor_converter_flexbuf.cc.
FlexBuffers is flatbuffers' schema-less sibling; the layout here is the
reference's documented map (tensordec-flexbuf.cc:26-41,:139-168):

    Map {
      "num_tensors": UInt,
      "rate_n": Int, "rate_d": Int, "format": Int,
      "tensor_#i": Vector [ name:String, type:Int,
                            dimension:TypedVector<UInt> (rank-4, 1-padded),
                            data:Blob ],
    }

Any process with a flexbuffers library reads these frames without our
code or a schema file; an unmodified nnstreamer flexbuf converter parses
them directly. FLEXIBLE/SPARSE data blobs are GstTensorMetaInfo-prefixed
exactly like the reference (is_flexible branch, tensordec-flexbuf.cc:147).
"""

from __future__ import annotations


import numpy as np

from nnstreamer_tpu.core.errors import StreamError
from nnstreamer_tpu.interop.flexbuf_read import flexbuf_loads
from nnstreamer_tpu.interop._codec_base import register_codec_pair
from nnstreamer_tpu.interop.gst_meta import (
    check_wire_dtype,
    pack_gst_meta,
    payload_to_array,
    wire_dims,
)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorFormat


def encode_flexbuf(buf: TensorBuffer, rate=None) -> bytes:
    """TensorBuffer → flexbuffers frame (reference map layout).

    Encoding needs the flexbuffers *builder*; only the stock
    ``flatbuffers`` package provides one (decode is dependency-free via
    interop/flexbuf_read.py)."""
    from flatbuffers import flexbuffers

    fbb = flexbuffers.Builder()
    non_static = buf.format != TensorFormat.STATIC
    frac = (rate if isinstance(rate, tuple) else (rate or 0, 1))
    with fbb.Map():
        fbb.Key("num_tensors")
        fbb.UInt(buf.num_tensors)
        fbb.Key("rate_n")
        fbb.Int(int(frac[0]))
        fbb.Key("rate_d")
        fbb.Int(int(frac[1]))
        fbb.Key("format")
        fbb.Int(int(buf.format))
        for i, t in enumerate(buf.tensors):
            arr = np.ascontiguousarray(np.asarray(t))
            dt = DType.from_np(arr.dtype)
            check_wire_dtype(dt)
            raw = arr.tobytes()
            if non_static:
                raw = pack_gst_meta(arr.shape, dt, buf.format) + raw
            fbb.Key(f"tensor_{i}")
            with fbb.Vector():
                fbb.String(str(buf.meta.get("tensor_names", {}).get(i, "")))
                fbb.Int(int(dt))
                fbb.TypedVectorFromElements(wire_dims(arr.shape))
                fbb.Blob(raw)
    return bytes(fbb.Finish())


def decode_flexbuf(frame: bytes) -> TensorBuffer:
    """flexbuffers frame → TensorBuffer (host numpy)."""
    try:
        root = flexbuf_loads(frame)
        if not isinstance(root, dict):
            raise ValueError("frame root is not a map")
        num = int(root["num_tensors"])
        fmt = (TensorFormat(int(root["format"])) if "format" in root
               else TensorFormat.STATIC)  # older frames omit the key
    except Exception as e:
        raise StreamError(f"corrupt flexbuf tensor frame: {e}") from None
    arrays, names = [], {}
    for i in range(num):
        try:
            name, ty, dims, raw = root[f"tensor_{i}"]
            dt = DType(int(ty))
        except Exception as e:
            raise StreamError(
                f"corrupt flexbuf tensor frame at tensor_{i}: {e}"
            ) from None
        arr = payload_to_array(raw, dims, dt, fmt,
                               f"flexbuf tensor_{i}")
        arrays.append(arr)
        if name:
            names[i] = name
    meta = {"tensor_names": names} if names else {}
    return TensorBuffer(tensors=tuple(arrays), format=fmt, meta=meta)


FlexbufEncode, FlexbufDecode = register_codec_pair(
    "flexbuf", encode_flexbuf, decode_flexbuf)
