"""Shared decoder/converter subplugin wrapper for the interop codecs.

All three schema'd codecs (protobuf/flatbuf/flexbuf) expose the same
pipeline surface: `tensor_decoder mode=<name>` serializes tensors to
frame bytes, `tensor_converter mode=custom:<name>` parses them back as a
FLEXIBLE stream. One factory instead of three verbatim class pairs.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.interop.gst_meta import check_wire_dtype
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec


def register_codec_pair(name: str, encode_fn, decode_fn):
    """Register tensors→bytes decoder + bytes→tensors converter under
    `name`. encode_fn(buf, rate=...) -> bytes; decode_fn(bytes) ->
    TensorBuffer. Returns the two classes.

    The element imports live here, not at module top: a codec module may
    be the FIRST thing imported in a process, and elements/__init__
    re-imports the codecs — a top-level import of elements.converter
    from this module would make that cycle unresolvable."""
    from nnstreamer_tpu.elements.converter import (
        ConverterSubplugin, register_converter)
    from nnstreamer_tpu.elements.decoder import (
        DecoderSubplugin, register_decoder)
    from nnstreamer_tpu.graph.media import MediaSpec, OctetSpec

    class Encode(DecoderSubplugin):
        def negotiate(self, in_spec: TensorsSpec) -> OctetSpec:
            for ti in in_spec.tensors:
                check_wire_dtype(ti.dtype)
            self._rate = in_spec.rate
            return OctetSpec(rate=in_spec.rate)

        def decode(self, buf: TensorBuffer) -> TensorBuffer:
            frame = encode_fn(buf, rate=getattr(self, "_rate", None))
            return buf.with_tensors(
                (np.frombuffer(frame, np.uint8).copy(),))

    class Decode(ConverterSubplugin):
        def negotiate(self, in_spec: MediaSpec) -> TensorsSpec:
            # FLEXIBLE: every frame is self-describing; shapes may vary
            return TensorsSpec(tensors=(), format=TensorFormat.FLEXIBLE,
                               rate=in_spec.rate)

        def convert(self, buf: TensorBuffer) -> TensorBuffer:
            data = np.ascontiguousarray(np.asarray(buf.tensors[0])).tobytes()
            out = decode_fn(data)
            if buf.pts is not None:
                out = out.with_tensors(out.tensors, pts=buf.pts)
            return out

    Encode.__name__ = f"{name.capitalize()}Encode"
    Decode.__name__ = f"{name.capitalize()}Decode"
    Encode.__doc__ = f"tensors → {name} frame bytes."
    Decode.__doc__ = f"{name} frame bytes → tensors (FLEXIBLE stream)."
    register_decoder(name)(Encode)
    register_converter(name)(Decode)
    return Encode, Decode
