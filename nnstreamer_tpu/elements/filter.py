"""tensor_filter — the model-execution element.

Reference parity: gst/nnstreamer/tensor_filter/tensor_filter.c +
tensor_filter_common.c (§3.1/§3.2 call stacks): backend open at start,
model-info-driven negotiation (load_tensor_info / setInputDimension for
adaptive models), input/output-combination subset routing, per-invoke
latency/throughput stats (:354-460), invoke error propagation.

TPU-first differences:
- One backend family (xla/custom/pallas) instead of 20 vendor subplugins;
  `framework=` defaults from config ([filter] default_backend) with
  extension-based auto-detect parity (detect_framework:1208 analog).
- **Fusion**: `set_fusion()` receives the elementwise programs of
  adjacent tensor_transform elements removed by the graph optimizer
  (graph/optimize.py); an accepting backend compiles them into the model
  computation. Refusing backends get them applied host-side here, so
  correctness never depends on fusion.
- Invoke is non-blocking: device outputs flow downstream as jax.Arrays
  (see backends/xla.py). Latency stats therefore measure *dispatch* by
  default; `latency-mode=sync` blocks for true per-frame latency.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import replace
from typing import Callable, List, Optional, Sequence, Tuple

from nnstreamer_tpu.backends.base import CircuitBreaker, FilterBackend, get_backend
from nnstreamer_tpu.core.config import get_config
from nnstreamer_tpu.core.errors import (
    BackendError, CircuitOpenError, PipelineError, SegmentStageError)
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("filter")


def _parse_combination(s: str) -> Optional[List[int]]:
    if not s:
        return None
    try:
        return [int(x) for x in s.split(",")]
    except ValueError:
        raise PipelineError(
            f"bad combination list {s!r}; expected comma-separated tensor "
            f"indices like '0,2'"
        ) from None


@register_element("tensor_filter")
class TensorFilter(Element):
    ELEMENT_NAME = "tensor_filter"
    # a tensor_batch upstream is the whole point of micro-batching: the
    # filter runs ONE batched invoke per coalesced buffer (backend
    # invoke_batched, bucket-compiled) and forwards batched outputs with
    # the dyn_batch meta intact for tensor_unbatch downstream
    ACCEPTS_DYN_BATCH = True
    # never chain-fused: the filter's dedicated worker thread is what
    # lets its device dispatch overlap upstream conversion (the async-
    # dispatch property the scheduler exists to provide)
    CHAIN_FUSABLE = False
    # invoke is non-blocking — outputs leave as unresolved jax arrays,
    # so the scheduler may pipeline dispatches behind a bounded window
    # instead of syncing per buffer ([runtime] max_inflight)
    DEVICE_RESIDENT = True
    PROPS = {
        "framework": PropDef(str, "", "backend name (xla|custom|pallas|…)"),
        "model": PropDef(lambda s: s, None, "model reference (backend-specific)"),
        "custom": PropDef(str, "", "opaque backend option string"),
        "accelerator": PropDef(str, "", "device selector, e.g. tpu:0"),
        # multi-chip data-parallel serving (serving/placement.py): N
        # per-device replicas of the model behind per-chip bounded
        # queues with least-outstanding routing. Replica i lives on
        # device i; bit-parity with devices=0 (each replica IS the
        # single-device path, placed elsewhere). Declined softly (with
        # a log + stat, single-device behavior preserved) for segment
        # heads, explicit accelerator= pins, and shared model keys.
        "devices": PropDef(
            int, 0, "data-parallel replicas, one per device (0=off)"),
        # tensor-parallel serving (serving/sharding.py): one mesh-
        # sharded backend whose projections are head-sharded over N
        # chips, leased as ONE shard group (a fenced member fences the
        # group). With devices=M too, M//N such groups serve data-
        # parallel behind the same ReplicaSet front door. Bit-parity
        # with shards=1 by the canonical-blocking construction.
        "shards": PropDef(
            int, 0, "tensor-parallel shards per group: one mesh-sharded "
                    "backend across N chips (0=off; 2/4/8)"),
        "input": PropDef(str, "", "override input dims (dim string list)"),
        "inputtype": PropDef(str, "", "override input types"),
        "output": PropDef(str, "", "override output dims"),
        "outputtype": PropDef(str, "", "override output types"),
        # graph tensor binding for multi-node model files (reference
        # tensorflow filter props, tensor_filter_tensorflow.cc): which
        # graph nodes are the I/O ("," separates multiple names)
        "inputname": PropDef(str, "", "model input node name(s)"),
        "outputname": PropDef(str, "", "model output node name(s)"),
        "input_combination": PropDef(str, "", "sink-tensor subset, e.g. 0,2"),
        "output_combination": PropDef(str, "",
                                      "i<n>=input passthrough / o<n>=output picks"),
        "latency_mode": PropDef(str, "async", "async|sync stats timing"),
        "is_updatable": PropDef(lambda s: str(s).lower() in ("1", "true"), False),
        "invoke_dynamic": PropDef(
            lambda s: str(s).lower() in ("1", "true"), False,
            "accept FLEXIBLE input (per-buffer shapes, bucketed recompile)"),
        "shared_tensor_filter_key": PropDef(
            str, "", "share one device model across filters with this key"),
        # store:// serving (docs/serving.md): canary splits are routed
        # per-invoke by a deterministic seeded RNG so a run is exactly
        # reproducible; the seed is per-filter
        "canary_seed": PropDef(
            int, 0, "seed for store:// canary routing (deterministic)"),
        # circuit breaker around backend invokes (docs/robustness.md):
        # after `breaker_threshold` consecutive invoke failures the
        # circuit opens and invokes short-circuit with CircuitOpenError
        # for `breaker_cooldown_ms`, then one half-open probe decides
        # recovery. 0 disables (default). Pair with error-policy=skip or
        # degrade so the short-circuits don't fail the pipeline.
        "breaker_threshold": PropDef(
            int, 0, "consecutive invoke failures to open the circuit (0=off)"),
        "breaker_cooldown_ms": PropDef(
            float, 1000.0, "open-circuit cooldown before the probe invoke"),
        # scheduler bypass (runtime/compiled_loop.py): when the stream
        # reaches steady state the scheduler may sweep queued frames
        # into ONE jitted K-step scan (backend invoke_window) instead
        # of K per-frame dispatches. Per-element opt-out; the global
        # [runtime] compiled_loop knob gates the whole mechanism.
        "compiled_loop": PropDef(
            lambda s: str(s).lower() in ("1", "true"), True,
            "allow the scheduler's compiled steady-state window"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.backend: Optional[FilterBackend] = None
        self._pre: Optional[Callable] = None   # fused pre chain
        self._post: Optional[Callable] = None
        self._pre_programs: list = []
        self._post_programs: list = []
        self._fused_in_backend = False
        self._fused_decoder = None             # device-decode subplugin
        self._in_combination = _parse_combination(self.props["input_combination"])
        self._out_combination = self._parse_out_combination(
            self.props["output_combination"]
        )
        self._breaker: Optional[CircuitBreaker] = None
        self._lat_window = deque(maxlen=10)   # last-10 window, ref :443-455
        self._invoke_count = 0
        self._t_start = None
        self._flexible = False
        self._dyn_batched = 0                 # dyn_batch of the input stream
        self._batch_keepdims: List[bool] = []
        # device segment (graph/optimize.py fuse_segments): downstream
        # filters absorbed into this head as (mid_programs, element)
        self._members: List[Tuple[list, "TensorFilter"]] = []
        # negotiated per-member host-fallback stages:
        # (mid chain_fn | None, member, member batch keepdims)
        self._member_stages: List[Tuple[Optional[Callable],
                                        "TensorFilter", List[bool]]] = []
        self._segment_in_backend = False
        self._forced_syncs = 0                # host syncs this element forced
        # data-parallel replica set (devices=N, serving/placement.py):
        # when live, invokes route through it instead of self.backend
        # (which stays the negotiation/spec source of truth and never
        # runs a frame)
        self.replicas = None
        self._replica_decline = ""

    # -- combination parsing ----------------------------------------------
    @staticmethod
    def _parse_out_combination(s: str) -> Optional[List[Tuple[str, int]]]:
        """'i0,o1' → [('i',0),('o',1)] — pass input 0 through + output 1
        (reference output-combination, tensor_filter.c:820-877)."""
        if not s:
            return None
        out = []
        for part in s.split(","):
            part = part.strip()
            if len(part) < 2 or part[0] not in "io" or not part[1:].isdigit():
                raise PipelineError(
                    f"bad output-combination entry {part!r}; entries are "
                    f"i<idx> (pass input) or o<idx> (model output)"
                )
            out.append((part[0], int(part[1:])))
        return out

    # -- fusion (called by graph/optimize.py) ------------------------------
    def set_fusion(self, pre_programs, post_programs) -> None:
        """Absorb removed transform elements' compiled programs."""
        from nnstreamer_tpu.graph.optimize import chain_fn

        self._pre_programs = pre_programs or []
        self._post_programs = post_programs or []
        self._pre = chain_fn(self._pre_programs)
        self._post = chain_fn(self._post_programs)

    def set_decoder_fusion(self, sub) -> None:
        """Absorb a downstream `tensor_decoder device=true` subplugin:
        its device_decode traces into the same XLA program as the model
        (+ any post transforms), so model output, postprocess, and result
        land in ONE dispatch — raw outputs never leave the chip."""
        self._fused_decoder = sub
        base_post = self._post

        def post(tensors, aux=None):
            if base_post is not None:
                tensors = base_post(tensors)
            out = sub.device_decode(tuple(tensors), aux)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        self._post = post

    def absorb_member(self, mid_programs, member: "TensorFilter") -> None:
        """Absorb a downstream tensor_filter (plus the transform chain
        connecting it) removed by `graph/optimize.fuse_segments`. The
        member's model traces into this head's jit at negotiation time
        (`XLABackend.compose_segment`); a declining backend gets the
        member invoked host-side per buffer instead."""
        self._members.append((list(mid_programs or []), member))

    def segment_name(self) -> str:
        """head+member1+member2… — the trace/report identity of the
        composed segment (empty string when no members)."""
        if not self._members:
            return ""
        return "+".join([self.name] + [m.name for _, m in self._members])

    def _host_decoder_aux(self):
        """Device-resident aux for the host-side fused-decoder fallback,
        uploaded once and cached."""
        if not hasattr(self, "_host_aux_cache"):
            aux = getattr(self._post, "aux_params", None)
            if aux is not None:
                import jax

                aux = jax.device_put(aux)
            self._host_aux_cache = aux
        return self._host_aux_cache

    #: reference framework names → our backends, so reference pipeline
    #: strings run verbatim (`framework=snpe model=add2_float.dlc`,
    #: `framework=deepview-rt model=....rtm`, runTest.sh recipes). The
    #: vendor zoo collapses into the xla backend's modelio ingestion
    #: (PARITY §2.3); scripted filters map onto their analogs.
    _FRAMEWORK_ALIASES = {
        "tensorflow-lite": "xla", "tensorflow1-lite": "xla",
        "tensorflow2-lite": "xla", "tensorflow": "xla",
        "pytorch": "xla", "caffe2": "xla", "snpe": "xla",
        "deepview-rt": "xla", "tensorrt": "xla", "armnn": "xla",
        "custom-easy": "custom",
    }

    # -- negotiation / backend open ---------------------------------------
    def _framework_name(self) -> str:
        fw = self.props["framework"]
        if fw:
            return self._FRAMEWORK_ALIASES.get(fw, fw)
        model = self.props["model"]
        cfg = get_config()
        if isinstance(model, str):
            ext = model.rsplit(".", 1)[-1].lower() if "." in model else ""
            if ext:
                by_ext = cfg.get("filter", f"priority_{ext}")
                if by_ext:
                    return by_ext.split(",")[0]
                from nnstreamer_tpu.modelio import MODEL_EXTENSIONS

                if ext in MODEL_EXTENSIONS:
                    return MODEL_EXTENSIONS[ext]
            if model.startswith(("zoo://", "store://")):
                return "xla"
        if callable(model) or type(model).__name__ == "ModelBundle":
            return "xla"
        return cfg.get("filter", "default_backend") or "xla"

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        from nnstreamer_tpu.tensor.info import TensorFormat

        spec = self.expect_tensors(in_specs[0])
        self._dyn_batched = spec.dyn_batch
        if spec.dyn_batch:
            if self._in_combination is not None or \
                    self._out_combination is not None:
                self.fail_negotiation(
                    "input-/output-combination cannot apply to a "
                    "micro-batched stream (batched buffers carry one "
                    "variable batch axis, not fixed per-frame tensor "
                    "slots); place tensor_batch after the combination or "
                    "drop the combination properties")
            # negotiation currency stays PER-FRAME: the model and every
            # override/check below see the frame spec; dyn_batch is
            # re-attached to the output spec at the end
            spec = replace(spec, dyn_batch=0)
            self._batch_keepdims = [
                len(t.shape) >= 1 and t.shape[0] == 1 for t in spec.tensors]
        fw = self._framework_name()
        try:
            self.backend = get_backend(fw)
        except PipelineError as e:
            self.fail_negotiation(str(e))
        props = dict(self.props)
        try:
            self.backend.open(props)
        except BackendError as e:
            self.fail_negotiation(f"backend {fw!r} failed to open model: {e}")

        if self._pre is not None or self._post is not None:
            self._fused_in_backend = self.backend.fuse(self._pre, self._post)

        if spec.format == TensorFormat.FLEXIBLE:
            if self._in_combination is not None or \
                    self._out_combination is not None:
                self.fail_negotiation(
                    "input-/output-combination cannot apply to a FLEXIBLE "
                    "stream (per-buffer region count, no fixed tensor "
                    "indices); remove the combination properties or make "
                    "the stream static with tensor_resize")
            if not self.props["invoke_dynamic"]:
                self.fail_negotiation(
                    "input stream is FLEXIBLE (per-buffer shapes, e.g. from "
                    "tensor_crop) but invoke-dynamic is off. Either set "
                    "invoke_dynamic=true (shape-bucketed recompile; model "
                    "must accept the per-buffer shapes — use "
                    "custom=dynamic_spatial=true for shape-polymorphic "
                    "models) or insert `tensor_resize size=H:W` to make the "
                    "stream static")
            self._flexible = True
            if int(self.props["devices"] or 0) > 0:
                self._replica_decline = (
                    "FLEXIBLE stream (per-buffer shapes route through one "
                    "backend's bucket cache)")
                log.warning("tensor_filter %s: devices=%d declined: %s",
                            self.name, self.props["devices"],
                            self._replica_decline)
            # per-region output shapes are only known per buffer
            model_out = self.backend.get_model_info()[1]
            out_tensors = model_out.tensors if model_out is not None else ()
            return [TensorsSpec(tensors=out_tensors,
                                format=TensorFormat.FLEXIBLE,
                                rate=spec.rate)]

        from nnstreamer_tpu.graph.optimize import transfer_spec

        model_in = self._override_spec(
            self.props["input"], self.props["inputtype"],
            self.backend.get_model_info()[0],
        )
        model_out = self._override_spec(
            self.props["output"], self.props["outputtype"],
            self.backend.get_model_info()[1],
        )

        fed = spec if self._in_combination is None else self._subset_spec(spec)
        # what the model itself sees after any fused pre-chain
        model_sees = transfer_spec(self._pre_programs, fed)
        if model_in is not None and not model_in.is_compatible(model_sees):
            self.fail_negotiation(
                f"model expects input {model_in} but receives {model_sees}"
                + (f" (= {fed} after fused pre-transforms)"
                   if self._pre_programs else "")
                + ". Fix the upstream pipeline (converter/transform dims) or "
                  "override with input=/inputtype= properties"
            )
        need_set_input = model_out is None
        if need_set_input:
            try:
                model_out = self.backend.set_input_info(model_sees)
            except BackendError as e:
                self.fail_negotiation(str(e))
        # device segment: chain member negotiation — each member sees the
        # previous stage's output spec after its connecting transform
        # chain, reusing every member-side validation (model-info checks,
        # overrides, backend open) exactly as if it were still in the
        # graph. The currency stays per-frame (dyn_batch stripped above).
        if self._members:
            model_out = self._negotiate_members(model_out, spec.rate)
        # fused post-chain spec transfer
        model_out = transfer_spec(self._post_programs, model_out)
        if self._fused_decoder is not None:
            if self._out_combination is not None:
                self.fail_negotiation(
                    "output-combination cannot combine with a fused device "
                    "decoder (the decoder consumes the whole output set)")
            try:
                model_out = self._fused_decoder.device_negotiate(model_out)
            except (ValueError, PipelineError) as e:
                self.fail_negotiation(
                    f"fused device decoder rejected model output "
                    f"{model_out}: {e}")
            # decode constants (e.g. anchors) exist only after
            # device_negotiate; hand them to the backend as jit-argument
            # aux now (re-fuse: compile happens lazily at first invoke)
            self._post.aux_params = self._fused_decoder.device_aux()
            if self._fused_in_backend:
                self._fused_in_backend = self.backend.fuse(
                    self._pre, self._post)
        # devices=N: replicate the fully-negotiated backend config on N
        # explicitly-placed sibling backends (AFTER decoder re-fuse, so
        # every replica serves the final fused program)
        self._maybe_setup_replicas(fw, need_set_input, model_sees)
        out = model_out.with_rate(spec.rate)
        if self._out_combination is not None:
            infos = []
            for kind, idx in self._out_combination:
                pool = spec.tensors if kind == "i" else out.tensors
                if idx >= len(pool):
                    self.fail_negotiation(
                        f"output-combination {kind}{idx} out of range "
                        f"({'input' if kind == 'i' else 'output'} has "
                        f"{len(pool)} tensors)"
                    )
                infos.append(pool[idx])
            out = replace(out, tensors=tuple(infos))
        if self._dyn_batched:
            out = replace(out, dyn_batch=self._dyn_batched)
        return [out]

    def _maybe_setup_replicas(self, fw: str, need_set_input: bool,
                              model_sees) -> None:
        """Stand up the devices=N replica set (serving/placement.py).

        Config parity is by replay: each replica backend gets the same
        open props (plus its device pin), the same fused pre/post, and
        the same set_input_info call the head backend got — so every
        chip serves the head's exact program. Unsupported combinations
        decline SOFTLY (log + `replica_decline` stat, single-device
        behavior preserved): replication must never change what a
        pipeline computes, only where."""
        n = int(self.props["devices"] or 0)
        shards = int(self.props["shards"] or 0)
        if n <= 0 and shards <= 0:
            return
        decline = ""
        if self._members:
            decline = ("segment head (members absorbed); replicate the "
                       "unfused filters or use segment placement instead")
        elif self.props["accelerator"]:
            decline = (f"accelerator={self.props['accelerator']!r} pins "
                       f"one device explicitly")
        elif self.props["shared_tensor_filter_key"]:
            decline = "shared-tensor-filter-key holds one device-resident model"
        elif "@" in str(self.props["model"] or "") and \
                ":" in str(self.props["model"]).rpartition("@")[2]:
            decline = "store canary split routes per-backend (seeded RNG)"
        if not decline and shards > 0:
            # sharded groups serve the raw model bundle as one SPMD
            # program (shapes re-infer per input signature) — a host-
            # side pre/post chain or explicit I/O overrides have no
            # per-shard replay story
            if self._pre is not None or self._post is not None:
                decline = ("custom-ops pre/post chain (sharded groups "
                           "serve the raw model bundle)")
            elif any(self.props[k] for k in
                     ("input", "inputtype", "output", "outputtype",
                      "inputname", "outputname")):
                decline = "explicit I/O override props are single-backend"
            elif fw not in ("", "xla"):
                decline = f"framework {fw!r} (sharding is mesh/XLA-only)"
        if decline:
            what = f"devices={n}" if shards <= 0 else f"shards={shards}"
            self._replica_decline = decline
            log.warning("tensor_filter %s: %s declined: %s",
                        self.name, what, decline)
            return
        if shards > 0:
            from nnstreamer_tpu.serving.sharding import (
                SUPPORTED_SHARDS, ShardedReplicaSet)

            if shards not in SUPPORTED_SHARDS:
                self.fail_negotiation(
                    f"shards must be one of {SUPPORTED_SHARDS} "
                    f"(canonical 8-block serving layout), got {shards}")
            groups = max(1, n // shards) if n > 0 else 1
            try:
                self.replicas = ShardedReplicaSet.open_sharded(
                    self.props["model"], shards=shards, groups=groups,
                    name=self.name, tracer=self._tracer)
            except BackendError as e:
                self.fail_negotiation(f"shards={shards}: {e}")
            return
        from nnstreamer_tpu.serving.placement import ReplicaSet

        pre, post = self._pre, self._post
        fused = self._fused_in_backend

        def configure(b):
            if pre is not None or post is not None:
                if bool(b.fuse(pre, post)) != fused:
                    raise BackendError(
                        "replica backend disagreed with the head about "
                        "pre/post fusion — placement would change results")
            if need_set_input:
                b.set_input_info(model_sees)

        try:
            self.replicas = ReplicaSet.open(
                fw, dict(self.props), n, configure=configure,
                name=self.name)
        except BackendError as e:
            self.fail_negotiation(f"devices={n}: {e}")

    def _negotiate_members(self, model_out: TensorsSpec, rate) -> TensorsSpec:
        """Chain member negotiation through the segment, then offer the
        backend the composed trace. Returns the last member's output
        spec (the segment's spec currency for the post chain/decoder)."""
        from nnstreamer_tpu.graph.optimize import chain_fn, transfer_spec

        self._member_stages = []
        cur = model_out
        for mids, m in self._members:
            cur = transfer_spec(mids, cur)
            keep = [len(t.shape) >= 1 and t.shape[0] == 1
                    for t in cur.tensors]
            [cur] = m.negotiate([cur.with_rate(rate)])
            self._member_stages.append((chain_fn(mids), m, keep))
        compose = getattr(self.backend, "compose_segment", None)
        self._segment_in_backend = bool(compose is not None and compose(
            [(fn, m.backend, m.name) for fn, m, _ in self._member_stages]))
        if not self._segment_in_backend:
            log.info(
                "segment %s: backend declined composition; member invokes "
                "run host-side (results identical)", self.segment_name())
        return cur

    def _apply_segment_host(self, outputs, n=None, keepdims=None):
        """Declined-composition fallback: run each member's connecting
        chain + model invoke host-side, in dataflow order. One dispatch
        per member instead of one per segment, but bit-identical."""
        for fn, m, keep in self._member_stages:
            if fn is not None:
                outputs = fn(outputs)
            try:
                if n is None:
                    outputs = m.backend.invoke(outputs)
                else:
                    outputs = m.backend.invoke_batched(outputs, n, keep)
            except Exception as e:
                m.backend.invoke_failures += 1
                raise SegmentStageError(m.name, e) from e
        return outputs

    def _subset_spec(self, spec: TensorsSpec) -> TensorsSpec:
        idxs = self._in_combination
        if any(i >= spec.num_tensors for i in idxs):
            self.fail_negotiation(
                f"input-combination {idxs} out of range for {spec.num_tensors}"
                f"-tensor input"
            )
        return replace(spec, tensors=tuple(spec.tensors[i] for i in idxs))

    @staticmethod
    def _override_spec(dims: str, types: str, fallback) -> Optional[TensorsSpec]:
        if dims:
            return TensorsSpec.from_strings(dims, types or "float32")
        return fallback

    def start(self) -> None:
        self._t_start = time.monotonic()
        # tests may pre-install a breaker with an injected clock; only
        # build one here if the props ask for it and none exists yet
        if self._breaker is None and self.props["breaker_threshold"] > 0:
            self._breaker = CircuitBreaker(
                self.props["breaker_threshold"],
                self.props["breaker_cooldown_ms"] / 1e3)
        if self.backend is not None:
            # hand the runner's tracer down so backend compile/invoke
            # spans land on this element's trace track — for a composed
            # segment the track carries the joined member names, so
            # report() shows the segment instead of vanished elements
            self.backend.tracer = self._tracer
            self.backend.trace_name = self.segment_name() or self.name
            # store-bound backends replay their persistent bucket
            # manifest here — start() runs before any buffer flows, so
            # a restarted process compiles its working set off the hot
            # path (warm against the on-disk XLA cache)
            if self.replicas is None:
                self.backend.warm_start()
        if self.replicas is not None:
            # replica mode: the N placed backends serve every frame;
            # the head backend stays cold (spec source only), so warm
            # the replicas instead
            self.replicas.warm_start(self._tracer, self.name)
        for _, m, _ in self._member_stages:
            if m.backend is not None:
                m.backend.tracer = self._tracer
                m.backend.trace_name = m.name   # swaps keep member identity
                if not self._segment_in_backend:
                    # host fallback invokes the member backend directly —
                    # warm its manifest like any standalone filter;
                    # composed members only feed params/fns into the
                    # head's jit, so their own buckets never compile
                    m.backend.warm_start()

    def stop(self) -> None:
        if self.replicas is not None:
            self.replicas.close()
        if self.backend is not None:
            self.backend.close()
        for _, m in self._members:
            if m.backend is not None:
                m.backend.close()

    def extra_stats(self) -> dict:
        """Backend compile/cache counters merged into this element's
        stats() row (absent for backends that don't track them)."""
        out = {}
        for k in ("compile_count", "cache_hits", "cache_misses",
                  "invoke_failures", "staging_transfers",
                  "staging_elided", "donated_invokes",
                  "window_invokes", "window_frames",
                  "window_compile_count"):
            v = getattr(self.backend, k, None)
            if v is not None:
                out["backend_" + k] = v
        # observed micro-batch occupancy histogram ({n: invokes}) —
        # the autotuner's bucket-refinement sensor
        hist = getattr(self.backend, "batch_size_hist", None)
        if hist:
            out["backend_batch_size_hist"] = dict(hist)
        # store:// serving: per-version invoke/error/p95 counters +
        # epoch adoptions, under backend_ keys so report()'s backend
        # section renders the canary comparison without extra tooling
        vstats = getattr(self.backend, "version_stats", None)
        if vstats is not None:
            for ver, d in vstats().items():
                for k, v in d.items():
                    out[f"backend_v{ver}_{k}"] = v
        swaps = getattr(self.backend, "swap_count", 0)
        if swaps:
            out["backend_swaps"] = swaps
        if self._breaker is not None:
            for k, v in self._breaker.stats().items():
                out["breaker_" + k] = v
        if self._members:
            out["segment"] = self.segment_name()
            out["segment_size"] = 1 + len(self._members)
            out["segment_composed"] = int(self._segment_in_backend)
            mswaps = sum(getattr(m.backend, "swap_count", 0) or 0
                         for _, m in self._members)
            if mswaps:
                out["backend_swaps"] = out.get("backend_swaps", 0) + mswaps
        if self._forced_syncs:
            out["forced_syncs"] = self._forced_syncs
        if self.replicas is not None:
            rst = self.replicas.stats()
            out["replica_devices"] = rst["devices"]
            out["replica_live"] = rst["live"]
            out["replica_invokes"] = sum(
                r["invokes"] for r in rst["replicas"])
            out["replica_errors"] = sum(
                r["errors"] for r in rst["replicas"])
            out["replica_reoffers"] = rst["reoffers"]
            out["replica_fences"] = rst["fences"]
            # per-chip rows ride along for the metrics plane
            out["replicas"] = rst["replicas"]
            if "group_size" in rst:       # sharded groups
                out["shards"] = rst["group_size"]
                out["shard_groups"] = rst["devices"]
                if "leases" in rst:
                    out["leases"] = rst["leases"]
        if self._replica_decline:
            out["replica_decline"] = self._replica_decline
        return out

    def _invoke_guarded(self, invoke, *args):
        """Run one backend invoke through the circuit breaker (when
        configured). A `guard()` short-circuit raises CircuitOpenError
        *without* touching the backend and without counting as a new
        failure; the owning element's error policy decides what the
        short-circuit means (skip/degrade/fail)."""
        br = self._breaker
        if br is None:
            return invoke(*args)
        br.guard(self.name)
        try:
            out = invoke(*args)
        except Exception:
            br.record_failure()
            raise
        br.record_success()
        return out

    def _invoke_segment(self, inputs):
        """One segment invoke: a composed backend runs every member
        inside the head's jit (one dispatch); otherwise members run
        host-side after the head. Guarded as ONE unit by the breaker —
        the head's policy/breaker governs the whole segment."""
        if self.replicas is not None:
            return self.replicas.invoke(inputs)
        outputs = self.backend.invoke(inputs)
        if self._member_stages and not self._segment_in_backend:
            outputs = self._apply_segment_host(outputs)
        return outputs

    def _invoke_segment_batched(self, inputs, n, keepdims):
        if self.replicas is not None:
            return self.replicas.invoke_batched(inputs, n, keepdims)
        outputs = self.backend.invoke_batched(inputs, n, keepdims)
        if self._member_stages and not self._segment_in_backend:
            outputs = self._apply_segment_host(outputs, n, keepdims)
        return outputs

    def _sync_outputs(self, outputs):
        """latency-mode=sync: one whole-tuple forced host sync per
        buffer (runtime/sync.py — counted by the tracer and surfaced as
        the `forced_syncs` stat)."""
        from nnstreamer_tpu.runtime.sync import device_sync

        device_sync(tuple(outputs), self._tracer, self.name)
        self._forced_syncs += 1
        return outputs

    # -- hot loop (reference §3.2) -----------------------------------------
    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        if self._flexible:
            return self._process_flexible(buf)
        if self._dyn_batched:
            return self._process_batched(buf)
        inputs = buf.tensors
        if self._in_combination is not None:
            inputs = tuple(inputs[i] for i in self._in_combination)
        t0 = time.perf_counter()
        if self._pre is not None and not self._fused_in_backend:
            inputs = self._pre(inputs)
        try:
            outputs = self._invoke_guarded(self._invoke_segment, inputs)
        except CircuitOpenError:
            raise   # keep the type — error policies never retry these
        except SegmentStageError as e:
            self.backend.invoke_failures += 1
            raise BackendError(
                f"tensor_filter {self.name}: segment member {e.member!r} "
                f"failed on frame pts={buf.pts}: {e}"
            ) from e
        except Exception as e:
            self.backend.invoke_failures += 1
            raise BackendError(
                f"tensor_filter {self.name}: invoke failed on frame "
                f"pts={buf.pts}: {e}"
            ) from e
        if self._post is not None and not self._fused_in_backend:
            # forward decode-aux (device_put once) so a declined-fusion
            # backend doesn't re-upload constants (anchors) every frame
            outputs = self._post(outputs) if self._fused_decoder is None \
                else self._post(outputs, self._host_decoder_aux())
        if self.props["latency_mode"] == "sync":
            outputs = tuple(self._sync_outputs(outputs))
        dt = time.perf_counter() - t0
        self._lat_window.append(dt)
        self._invoke_count += 1
        if self._out_combination is not None:
            sel = []
            for kind, idx in self._out_combination:
                sel.append(buf.tensors[idx] if kind == "i" else outputs[idx])
            outputs = tuple(sel)
        return [(0, buf.with_tensors(outputs))]

    def _process_batched(self, buf: TensorBuffer) -> List[Emission]:
        """Micro-batched buffer (tensor_batch upstream): one batched
        invoke over the coalesced frames; outputs stay batched and keep
        the dyn_batch meta so tensor_unbatch can split them. Fused
        host-side pre/post chains are elementwise, hence batch-
        polymorphic — they apply to the batched arrays directly."""
        db = buf.meta.get("dyn_batch")
        if db is None:
            raise BackendError(
                f"tensor_filter {self.name}: micro-batched stream buffer "
                f"has no dyn_batch meta (upstream element dropped it?)")
        n = int(db["n"])
        inputs = buf.tensors
        t0 = time.perf_counter()
        if self._pre is not None and not self._fused_in_backend:
            inputs = self._pre(inputs)
        try:
            outputs = self._invoke_guarded(
                self._invoke_segment_batched, inputs, n,
                self._batch_keepdims)
        except CircuitOpenError:
            raise
        except SegmentStageError as e:
            self.backend.invoke_failures += 1
            raise BackendError(
                f"tensor_filter {self.name}: segment member {e.member!r} "
                f"failed on buffer pts={buf.pts} occupancy={n}: {e}"
            ) from e
        except Exception as e:
            self.backend.invoke_failures += 1
            raise BackendError(
                f"tensor_filter {self.name}: batched invoke failed on "
                f"buffer pts={buf.pts} occupancy={n}: {e}"
            ) from e
        if self._post is not None and not self._fused_in_backend:
            outputs = self._post(outputs) if self._fused_decoder is None \
                else self._post(outputs, self._host_decoder_aux())
        if self.props["latency_mode"] == "sync":
            outputs = tuple(self._sync_outputs(outputs))
        self._lat_window.append(time.perf_counter() - t0)
        self._invoke_count += n   # throughput prop counts FRAMES
        return [(0, buf.with_tensors(outputs))]

    def _process_flexible(self, buf: TensorBuffer) -> List[Emission]:
        """FLEXIBLE buffer = N variable-shape regions; one model output per
        region (invoke-dynamic). Host-side fused chains apply per region."""
        regions = list(buf.tensors)
        if self._pre is not None and not self._fused_in_backend:
            regions = [self._pre((r,))[0] for r in regions]
        t0 = time.perf_counter()
        try:
            outputs = list(self._invoke_guarded(
                self.backend.invoke_flexible, regions))
        except CircuitOpenError:
            raise
        except Exception as e:
            self.backend.invoke_failures += 1
            raise BackendError(
                f"tensor_filter {self.name}: flexible invoke failed on "
                f"frame pts={buf.pts} with region shapes "
                f"{[tuple(np_shape(r)) for r in regions]}: {e}"
            ) from e
        if self._post is not None and not self._fused_in_backend:
            outputs = [self._post((o,))[0] for o in outputs]
        if self.props["latency_mode"] == "sync":
            outputs = list(self._sync_outputs(tuple(outputs)))
        self._lat_window.append(time.perf_counter() - t0)
        self._invoke_count += 1
        return [(0, buf.with_tensors(tuple(outputs)))]

    # -- compiled steady-state window (scheduler bypass) --------------------
    def window_capable(self) -> bool:
        """Whether this element may serve frames through the compiled
        multi-step window. The exclusions are exactly the paths whose
        per-frame behavior is NOT a pure function of one fixed-shape
        invoke: flexible/batched shapes (their own bucketing), replica
        routing (per-frame placement decisions), an armed breaker
        (per-frame failure accounting), sync latency mode (the point is
        per-frame sync), and host-fallback segment members (host Python
        per frame regardless)."""
        return (bool(self.props["compiled_loop"])
                and self.backend is not None
                and hasattr(self.backend, "invoke_window")
                and not self._flexible
                and not self._dyn_batched
                and self.replicas is None
                and self._breaker is None
                and self.props["latency_mode"] != "sync"
                and not (self._member_stages
                         and not self._segment_in_backend))

    def swap_pending(self) -> bool:
        """A store epoch flip this backend has not adopted yet — the
        scheduler bails the window (cause "swap") so adoption happens
        at an ordinary per-frame invoke boundary."""
        fn = getattr(self.backend, "swap_pending", None)
        return bool(fn()) if fn is not None else False

    def process_window(self, pad: int,
                       bufs: List[TensorBuffer]) -> List[Emission]:
        """K same-signature frames through ONE compiled scan dispatch
        (backend invoke_window). Host-side combination/pre/post stages
        apply per frame exactly as `process()` would — outputs are
        bit-identical to K per-frame calls; only the dispatch count
        changes. Raises leave ALL K frames unconsumed semantically: the
        scheduler re-runs them through the per-frame path so the error
        lands on the precise frame that faulted."""
        frames = []
        for buf in bufs:
            inputs = buf.tensors
            if self._in_combination is not None:
                inputs = tuple(inputs[i] for i in self._in_combination)
            if self._pre is not None and not self._fused_in_backend:
                inputs = self._pre(inputs)
            frames.append(tuple(inputs))
        t0 = time.perf_counter()
        outs = self.backend.invoke_window(frames)
        per = (time.perf_counter() - t0) / len(bufs)
        emissions: List[Emission] = []
        for buf, outputs in zip(bufs, outs):
            if self._post is not None and not self._fused_in_backend:
                outputs = self._post(outputs) \
                    if self._fused_decoder is None \
                    else self._post(outputs, self._host_decoder_aux())
            self._lat_window.append(per)
            self._invoke_count += 1
            if self._out_combination is not None:
                sel = []
                for kind, idx in self._out_combination:
                    sel.append(buf.tensors[idx] if kind == "i"
                               else outputs[idx])
                outputs = tuple(sel)
            emissions.append((0, buf.with_tensors(tuple(outputs))))
        return emissions

    # -- stats (reference latency/throughput props) ------------------------
    @property
    def latency_us(self) -> float:
        """avg invoke latency, µs, last-10 window (prop `latency`)."""
        if not self._lat_window:
            return 0.0
        return 1e6 * sum(self._lat_window) / len(self._lat_window)

    @property
    def throughput(self) -> float:
        """invokes/sec since start (prop `throughput`)."""
        if not self._invoke_count or self._t_start is None:
            return 0.0
        dt = time.monotonic() - self._t_start
        return self._invoke_count / dt if dt > 0 else 0.0

    def reload_model(self, model) -> None:
        """Hot swap (is-updatable + model property update analog)."""
        if not self.props["is_updatable"]:
            raise PipelineError(
                f"tensor_filter {self.name} is not reloadable; construct it "
                f"with is-updatable=true to allow hot model swaps"
            )
        self.backend.reload(model)


def _block(x):
    """Compat shim — every host sync routes through runtime/sync.py so
    forced syncs are counted in one place."""
    from nnstreamer_tpu.runtime.sync import device_sync

    device_sync((x,))
    return x


def np_shape(x):
    import numpy as np

    return np.asarray(x).shape if not hasattr(x, "shape") else x.shape
