"""Pipeline elements (reference L3: gst/nnstreamer/elements/).

Importing this package registers every built-in element with the ELEMENT
registry — the plugin_init analog (registerer/nnstreamer.c:91-119).
"""

from nnstreamer_tpu.elements import (  # noqa: F401
    aggregator,
    batch,
    control,
    converter,
    debug,
    decoder,
    fault,
    filter as filter_element,
    iio,
    ipc,
    llm,
    mqtt,
    repo,
    routing,
    sinks,
    sources,
    sparse_elements,
    transform,
    wire_codec,
)
from nnstreamer_tpu.trainer import element as _trainer_element  # noqa: F401
# schema'd interop codecs register decoder/converter subplugins
# "protobuf", "flexbuf" and "flatbuf" (SURVEY.md §2.4 codec pairs); grpc_elements
# registers tensor_src_grpc / tensor_sink_grpc (§2.5). Soft dependency:
# a stripped install without protobuf/flatbuffers/grpcio still gets the
# full non-interop element set (the reference gates the same subplugins
# behind meson feature flags).
try:
    from nnstreamer_tpu.interop import (  # noqa: F401
        flatbuf_codec,
        flexbuf_codec,
        grpc_elements,
        protobuf_codec,
    )
except ImportError as _interop_err:  # pragma: no cover - env without deps
    from nnstreamer_tpu.core.log import get_logger as _get_logger

    _get_logger("elements").info(
        "interop codecs unavailable (%s); protobuf/flexbuf/grpc elements "
        "not registered", _interop_err)

from nnstreamer_tpu.elements.aggregator import TensorAggregator
from nnstreamer_tpu.elements.batch import TensorBatch, TensorUnbatch
from nnstreamer_tpu.elements.control import (
    TensorCrop, TensorIf, TensorRate, register_if_condition)
from nnstreamer_tpu.elements.converter import TensorConverter, register_converter
from nnstreamer_tpu.elements.debug import TensorDebug
from nnstreamer_tpu.elements.decoder import TensorDecoder, register_decoder
from nnstreamer_tpu.elements.fault import TensorFault
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.ipc import IpcSink, IpcSrc
from nnstreamer_tpu.elements.llm import TensorLLM
from nnstreamer_tpu.elements.repo import REPO, TensorRepoSink, TensorRepoSrc
from nnstreamer_tpu.elements.routing import (
    Join, Queue, Tee, TensorDemux, TensorMerge, TensorMux, TensorSplit)
import nnstreamer_tpu.elements.script_codec  # noqa: F401 (registers
                                             # the python3 decoder)
from nnstreamer_tpu.elements.sinks import FakeSink, FileSink, TensorSink
from nnstreamer_tpu.elements.sources import AppSrc, TensorSrc, VideoTestSrc
from nnstreamer_tpu.elements.sparse_elements import (
    TensorSparseDec, TensorSparseEnc)
from nnstreamer_tpu.elements.transform import TensorTransform, TransformProgram

__all__ = [
    "AppSrc",
    "FakeSink",
    "FileSink",
    "IpcSink",
    "IpcSrc",
    "Join",
    "Queue",
    "REPO",
    "Tee",
    "TensorAggregator",
    "TensorBatch",
    "TensorConverter",
    "TensorCrop",
    "TensorDebug",
    "TensorDecoder",
    "TensorDemux",
    "TensorFault",
    "TensorFilter",
    "TensorIf",
    "TensorLLM",
    "TensorMerge",
    "TensorMux",
    "TensorRate",
    "TensorRepoSink",
    "TensorRepoSrc",
    "TensorSink",
    "TensorSparseDec",
    "TensorSparseEnc",
    "TensorSplit",
    "TensorSrc",
    "TensorTransform",
    "TensorUnbatch",
    "TransformProgram",
    "VideoTestSrc",
    "register_converter",
    "register_decoder",
    "register_if_condition",
]
