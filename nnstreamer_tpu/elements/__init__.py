"""Pipeline elements (reference L3: gst/nnstreamer/elements/).

Importing this package registers every built-in element with the ELEMENT
registry — the plugin_init analog (registerer/nnstreamer.c:91-119).
"""

from nnstreamer_tpu.elements import (  # noqa: F401
    converter,
    decoder,
    filter as filter_element,
    sinks,
    sources,
    transform,
)

from nnstreamer_tpu.elements.converter import TensorConverter, register_converter
from nnstreamer_tpu.elements.decoder import TensorDecoder, register_decoder
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sinks import FakeSink, TensorSink
from nnstreamer_tpu.elements.sources import AppSrc, TensorSrc, VideoTestSrc
from nnstreamer_tpu.elements.transform import TensorTransform, TransformProgram

__all__ = [
    "TensorConverter",
    "TensorDecoder",
    "TensorFilter",
    "TensorSink",
    "FakeSink",
    "AppSrc",
    "TensorSrc",
    "VideoTestSrc",
    "TensorTransform",
    "TransformProgram",
    "register_converter",
    "register_decoder",
]
