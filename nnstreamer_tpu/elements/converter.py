"""tensor_converter — media→tensor ingress.

Reference parity: gst/nnstreamer/elements/gsttensor_converter.c (2418 LoC):
per-media branches video(:1046)/audio(:1110)/text(:1114)/octet(:1144),
frames-per-tensor accumulation via GstAdapter (:971), and converter
subplugin dispatch for arbitrary media (:1237-1239).

TPU notes: incoming video frames are contiguous numpy arrays, so the
reference's stride-4 row-padding fixups for RGB don't apply. With
frames_per_tensor>1, frames batch along a leading axis — which is exactly
the batch dim the MXU wants; the batching adapter is the accumulation
point that turns a stream into MXU-shaped work.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import PluginKind, register_element, registry
from nnstreamer_tpu.graph.media import AudioSpec, MediaSpec, OctetSpec, TextSpec, VideoSpec
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec


@register_element("tensor_converter")
class TensorConverter(Element):
    ELEMENT_NAME = "tensor_converter"
    PROPS = {
        "frames_per_tensor": PropDef(int, 1, "batch N media frames per tensor"),
        "input_dim": PropDef(str, "", "required for octet/text input"),
        "input_type": PropDef(str, "", "required for octet input"),
        "mode": PropDef(str, "", "custom converter: custom:<name> or "
                                 "custom-script:<script.py>"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._pending: List[TensorBuffer] = []
        self._audio_backlog: Optional[np.ndarray] = None
        self._subplugin = None

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = in_specs[0]
        n = self.props["frames_per_tensor"]
        if n < 1:
            self.fail_negotiation(f"frames-per-tensor must be >= 1, got {n}")
        mode = self.props["mode"]
        if mode:
            kind, _, sub = mode.partition(":")
            if kind == "custom-script" and sub:
                # reference python3 converter scripts, run unmodified
                # (tensor_converter_python3.cc contract)
                from nnstreamer_tpu.elements.script_codec import (
                    make_script_converter)

                self._subplugin = make_script_converter(sub)
                return [self._subplugin.negotiate(spec)]
            if kind != "custom" or not sub:
                self.fail_negotiation(
                    f"mode must be custom:<subplugin name> or "
                    f"custom-script:<script.py>, got {mode!r}"
                )
            self._subplugin = registry.get(PluginKind.CONVERTER, sub)()
            return [self._subplugin.negotiate(spec)]
        if isinstance(spec, VideoSpec):
            h, w, c = spec.frame_shape
            if not (h and w):
                self.fail_negotiation(
                    "video input needs fixed width/height before conversion"
                )
            out = TensorsSpec.of(
                TensorInfo((n, h, w, c), DType.UINT8),
                rate=spec.rate / n if spec.rate and n > 1 else spec.rate,
            )
            return [out]
        if isinstance(spec, AudioSpec):
            out = TensorsSpec.of(
                TensorInfo((n, spec.channels), DType.from_name(spec.dtype_name)),
                rate=spec.rate,
            )
            return [out]
        if isinstance(spec, TextSpec):
            if not self.props["input_dim"]:
                self.fail_negotiation(
                    "text input requires input-dim=<N> (fixed byte width per "
                    "frame, reference gsttensor_converter text branch)"
                )
            ti = TensorInfo.from_dim_string(self.props["input_dim"], "uint8")
            return [TensorsSpec.of(ti, rate=spec.rate)]
        if isinstance(spec, OctetSpec):
            if not (self.props["input_dim"] and self.props["input_type"]):
                self.fail_negotiation(
                    "octet input requires input-dim= and input-type= "
                    "(self-describing raw bytes)"
                )
            ti = TensorInfo.from_dim_string(
                self.props["input_dim"], self.props["input_type"]
            )
            return [TensorsSpec.of(ti, rate=spec.rate)]
        if isinstance(spec, TensorsSpec):
            return [spec]  # tensor passthrough (reference allows this)
        self.fail_negotiation(
            f"no conversion for input stream {spec}; use mode=custom:<name> "
            f"with a registered converter subplugin"
        )

    # -- dataflow ----------------------------------------------------------
    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        if self._subplugin is not None:
            return [(0, self._subplugin.convert(buf))]
        spec = self.in_specs[0]
        n = self.props["frames_per_tensor"]
        if isinstance(spec, VideoSpec):
            frame = np.asarray(buf.tensors[0])
            if frame.shape != spec.frame_shape:
                raise PipelineError(
                    f"tensor_converter {self.name}: video frame shape "
                    f"{frame.shape} != negotiated {spec.frame_shape}"
                )
            batched = frame[None, ...]
            if n == 1:
                return [(0, buf.with_tensors((batched,)))]
            # frames-per-tensor accumulation (GstAdapter analog :971)
            self._pending.append(buf.with_tensors((batched,)))
            if len(self._pending) < n:
                return []
            chunk = self._pending[:n]
            self._pending = self._pending[n:]
            stacked = np.concatenate([b.tensors[0] for b in chunk], axis=0)
            return [(0, chunk[0].with_tensors((stacked,)))]
        if isinstance(spec, AudioSpec):
            # sample adapter: arbitrary-length chunks in, fixed
            # (frames_per_tensor, channels) tensors out (GstAdapter analog)
            arr = np.asarray(buf.tensors[0])
            if arr.ndim == 1:
                arr = arr[:, None]
            if arr.shape[1] != spec.channels:
                raise PipelineError(
                    f"tensor_converter {self.name}: audio chunk has "
                    f"{arr.shape[1]} channels, negotiated {spec.channels}"
                )
            arr = arr.astype(DType.from_name(spec.dtype_name).np_dtype,
                             copy=False)
            self._audio_backlog = (
                arr if self._audio_backlog is None
                else np.concatenate([self._audio_backlog, arr], axis=0)
            )
            out: List[Emission] = []
            while self._audio_backlog.shape[0] >= n:
                chunk, self._audio_backlog = (
                    self._audio_backlog[:n], self._audio_backlog[n:]
                )
                out.append((0, buf.with_tensors((chunk,))))
            return out
        if isinstance(spec, TextSpec):
            out_info: TensorInfo = self.out_specs[0].tensors[0]
            raw = buf.meta.get("text", "")
            data = raw.encode("utf-8") if isinstance(raw, str) else bytes(raw)
            fixed = np.zeros(out_info.num_elements, np.uint8)
            clipped = data[: out_info.num_elements]
            fixed[: len(clipped)] = np.frombuffer(clipped, np.uint8)
            return [(0, buf.with_tensors((fixed.reshape(out_info.shape),)))]
        if isinstance(spec, OctetSpec):
            out_info = self.out_specs[0].tensors[0]
            raw = np.asarray(buf.tensors[0], np.uint8).tobytes()
            if len(raw) != out_info.nbytes:
                raise PipelineError(
                    f"tensor_converter {self.name}: octet frame of {len(raw)} "
                    f"bytes != declared input-dim size {out_info.nbytes}"
                )
            arr = np.frombuffer(raw, out_info.dtype.np_dtype).reshape(out_info.shape)
            return [(0, buf.with_tensors((arr,)))]
        return [(0, buf)]  # tensor passthrough

    def flush(self) -> List[Emission]:
        # incomplete batch at EOS is dropped (reference adapter behavior)
        self._pending = []
        self._audio_backlog = None
        return []


class ConverterSubplugin:
    """API for custom media→tensor converters (NNStreamerExternalConverter
    analog, include/nnstreamer_plugin_api_converter.h:41)."""

    NAME = ""

    def negotiate(self, in_spec: MediaSpec) -> TensorsSpec:
        raise NotImplementedError

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        raise NotImplementedError


def register_converter(name: str):
    def deco(cls):
        cls.NAME = name
        registry.register(PluginKind.CONVERTER, name, cls)
        return cls
    return deco
