"""Sink elements.

Reference parity: gsttensor_sink.c (appsink-like `new-data`/`eos` signals
with signal-rate limiting :56-109,168-171), fakesink, and filesink (the
reference test pipelines' standard result capture — e.g.
tests/nnstreamer_filter_deepview_rt/runTest.sh writes the decoded label
with `filesink location=class.out.log`).
"""

from __future__ import annotations

import threading
import time
from typing import List

from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import PropDef, SinkElement, prop_bool
from nnstreamer_tpu.runtime.sync import device_sync
from nnstreamer_tpu.tensor.buffer import TensorBuffer


@register_element("tensor_sink")
class TensorSink(SinkElement):
    """Collects buffers and fires a `new_data` callback.

    signal-rate (signals/sec, 0 = every buffer) rate-limits the callback
    exactly like the reference's signal-rate property; collection into
    `.results` is always unthrottled (appsink pull analog).
    """

    WANTS_HOST = True
    ELEMENT_NAME = "tensor_sink"
    PROPS = {
        "new_data": PropDef(lambda s: s, None, "callback(buffer) (programmatic)"),
        "signal_rate": PropDef(int, 0, "max callbacks/sec, 0=all"),
        "collect": PropDef(prop_bool, True, "keep buffers in .results"),
        "max_results": PropDef(int, 0, "cap .results length, 0=unbounded"),
        "to_host": PropDef(prop_bool, True, "D2H-sync buffers at the sink"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.results: List[TensorBuffer] = []
        self._lock = threading.Lock()
        self._last_signal = 0.0
        self.eos = threading.Event()

    def render(self, buf: TensorBuffer) -> None:
        if self.props["to_host"]:
            buf = buf.to_host()  # the single D2H point of the pipeline
        with self._lock:
            if self.props["collect"]:
                self.results.append(buf)
                cap = self.props["max_results"]
                if cap and len(self.results) > cap:
                    del self.results[: len(self.results) - cap]
        cb = self.props["new_data"]
        if cb is not None:
            rate = self.props["signal_rate"]
            now = time.monotonic()
            if not rate or (now - self._last_signal) >= 1.0 / rate:
                self._last_signal = now
                cb(buf)

    def flush(self):
        self.eos.set()
        return []


@register_element("filesink")
class FileSink(SinkElement):
    """Writes each buffer's raw bytes to a file (gst filesink analog).

    Text streams (e.g. the image_labeling decoder's label output) land
    as readable text; tensor streams land as their raw little-endian
    bytes — the same thing gst filesink would write, so the reference's
    golden-file test recipes (`filesink location=class.out.log` →
    compare) port verbatim. `append=false` (default) truncates at
    pipeline start."""

    WANTS_HOST = True
    ELEMENT_NAME = "filesink"
    PROPS = {
        "location": PropDef(str, None, "output file path"),
        "append": PropDef(prop_bool, False, "append instead of truncate"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["location"]:
            from nnstreamer_tpu.core.errors import PipelineError

            raise PipelineError(
                f"filesink {self.name}: location= is required")
        self._fh = None
        self.count = 0

    def negotiate(self, in_specs):
        # open (and truncate) at pipeline start, like gst filesink at
        # state change — a run that produces zero buffers must not
        # leave a previous run's output behind a passing golden compare
        self._handle()
        return super().negotiate(in_specs)

    def _handle(self):
        if self._fh is None:
            mode = "ab" if self.props["append"] else "wb"
            self._fh = open(self.props["location"], mode)
        return self._fh

    def render(self, buf: TensorBuffer) -> None:
        import numpy as np

        fh = self._handle()
        for t in buf.to_host().tensors:
            fh.write(np.asarray(t).tobytes())
        fh.flush()
        self.count += 1

    def flush(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        return []


@register_element("fakesink")
class FakeSink(SinkElement):
    """Discards everything (terminates unused branches)."""

    ELEMENT_NAME = "fakesink"
    PROPS = {
        "sync_device": PropDef(prop_bool, False,
                               "block on device arrays (bench timing)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.count = 0

    def render(self, buf: TensorBuffer) -> None:
        if self.props["sync_device"]:
            # one whole-tuple sync per buffer (not a per-tensor loop):
            # a single runtime round-trip, counted by the tracer
            device_sync(buf.tensors, self._tracer, self.name)
        self.count += 1
