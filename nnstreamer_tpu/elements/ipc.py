"""ipc_sink / ipc_src — zero-copy local IPC over the native shm ring.

TPU-native addition beyond the reference: nnstreamer crossing process
boundaries falls back to TCP/MQTT serialization (SURVEY.md §5.8); these
elements move wire frames through /dev/shm (native/nt_shmring.cc) with
one memcpy per side and no socket stack — the right transport between a
camera/ingest process and a TPU inference process on the same host.

The payload is the standard wire frame (edge/wire.py), so caps travel
with every frame; ipc_src negotiates its spec from dims/types props or
from the first frame when `dims` is omitted (blocking briefly).
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.graph.pipeline import (
    PropDef, SinkElement, SourceElement, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorsSpec

log = get_logger("elements.ipc")


@register_element("ipc_sink")
class IpcSink(SinkElement):
    WANTS_HOST = True
    ELEMENT_NAME = "ipc_sink"
    PROPS = {
        "ring": PropDef(str, None, "shm ring name, e.g. /nns-cam0"),
        "capacity": PropDef(int, 1 << 22, "ring bytes (default 4 MiB)"),
        "timeout_ms": PropDef(int, 10_000, "blocking-write bound"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["ring"]:
            raise PipelineError(f"ipc_sink {self.name}: ring= is required")
        self._ring = None

    def start(self) -> None:
        from nnstreamer_tpu.native import ShmRing

        self._ring = ShmRing(self.props["ring"], create=True,
                             capacity=self.props["capacity"])

    def render(self, buf: TensorBuffer) -> None:
        self._ring.write(encode_buffer(buf), self.props["timeout_ms"])

    def flush(self):
        if self._ring is not None:
            self._ring.close_write()
        return []

    def stop(self) -> None:
        if self._ring is not None:
            self._ring.close_write()
            self._ring.close()
            self._ring = None


@register_element("ipc_src")
class IpcSrc(SourceElement):
    ELEMENT_NAME = "ipc_src"
    PROPS = {
        "ring": PropDef(str, None, "shm ring name to open"),
        "dims": PropDef(str, "", "expected dims (else sniffed from frame 1)"),
        "types": PropDef(str, "float32"),
        "sniff_timeout": PropDef(float, 10.0, "first-frame wait, s"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["ring"]:
            raise PipelineError(f"ipc_src {self.name}: ring= is required")
        self._ring = None
        self._stop = threading.Event()
        self._sniffed: Optional[TensorBuffer] = None

    def _open(self):
        from nnstreamer_tpu.native import ShmRing

        if self._ring is None:
            self._ring = ShmRing(self.props["ring"], create=False)
        return self._ring

    def output_spec(self) -> StreamSpec:
        if self.props["dims"]:
            return TensorsSpec.from_strings(self.props["dims"],
                                            self.props["types"])
        # sniff: block for the first frame, reuse it in generate()
        ring = self._open()
        deadline = self.props["sniff_timeout"]
        waited = 0.0
        while waited < deadline:
            try:
                frame = ring.read(timeout_ms=100)
            except EOFError:
                raise PipelineError(
                    f"ipc_src {self.name}: ring closed before the first "
                    f"frame; declare dims= to negotiate without sniffing"
                ) from None
            if frame is not None:
                self._sniffed, _ = decode_buffer(frame)
                return self._sniffed.spec()
            waited += 0.1
        raise PipelineError(
            f"ipc_src {self.name}: no frame arrived within {deadline}s to "
            f"sniff the stream type; declare dims=/types= instead")

    def interrupt(self) -> None:
        self._stop.set()

    def generate(self) -> Iterator[TensorBuffer]:
        ring = self._open()
        if self._sniffed is not None:
            yield self._sniffed
            self._sniffed = None
        while not self._stop.is_set():
            try:
                frame = ring.read(timeout_ms=100)
            except EOFError:
                return
            if frame is None:
                continue
            buf, _ = decode_buffer(frame)
            yield buf

    def stop(self) -> None:
        self._stop.set()
        if self._ring is not None:
            self._ring.close()
            self._ring = None
