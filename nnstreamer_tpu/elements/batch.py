"""Dynamic micro-batching: tensor_batch / tensor_unbatch.

The per-frame pipeline model (one buffer = one frame) leaves the
accelerator badly under-occupied for small models: each invoke launches
with batch 1 while the MXU could amortize weights over dozens of frames.
This pair makes batching a *stream property* negotiated like any other
cap, instead of something every filter reinvents:

- `tensor_batch` coalesces up to `max-batch` in-flight buffers along a
  new leading batch axis. A batch flushes when it is full OR when the
  oldest queued frame has waited `max-latency-ms`, whichever comes
  first — the deadline path rides the scheduler's timer wakeup
  (Element.next_deadline / on_timer, runtime/scheduler.py), so a
  half-full batch ships on time even if no further frame ever arrives.
- `tensor_unbatch` splits results back into per-frame buffers,
  restoring each frame's pts/duration/meta and arrival order. With N
  muxed input streams, frames carry (stream_id, seq) tags through the
  batch so they route back to the right output pad in order.

Negotiation keeps PER-FRAME shapes as the currency: the batched link's
`TensorsSpec.dyn_batch` marks "buffers on this wire carry up to K frames
stacked on axis 0", while `spec.tensors` still describe one frame.
Downstream elements that are not batch-aware refuse such links at
build time (Element.expect_tensors) with a message telling the user to
insert tensor_unbatch — occupancy varies buffer-to-buffer with load, so
it cannot be part of the static shape.

Wire format of a batched buffer (n = occupancy, n <= max_batch):
- per tensor j: frames whose per-frame leading dim is 1 are
  CONCATENATED along axis 0 (rank preserved — (1,H,W,C) frames become
  (n,H,W,C), what image models consume directly); all other frames are
  STACKED on a new axis 0 (rank + 1). tensor_unbatch distinguishes the
  two by comparing ranks against the negotiated per-frame spec.
- `buf.meta["dyn_batch"] = {"n", "reason", "frames": [{pts, duration,
  meta, stream_id, seq}, …]}` carries everything needed to reconstitute
  the originals.

There is no reference analog — NNStreamer has no cross-buffer batcher
(its tensor_aggregator concatenates *within* one stream's time window
and changes the negotiated shape). This is the paper's dynamic-batching
runtime (PAPER.md): server-style deadline batching as pipeline elements.
"""

from __future__ import annotations

import time
from dataclasses import replace
from typing import Dict, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import (
    DYNAMIC, Element, Emission, PropDef, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec

log = get_logger("elements.batch")


def _xp(arrays):
    """numpy or jax.numpy depending on where the arrays live."""
    if any(type(a).__module__.startswith("jax") for a in arrays):
        import jax.numpy as jnp

        return jnp
    return np


@register_element("tensor_batch")
class TensorBatch(Element):
    """Coalesce per-frame buffers into deadline-bounded micro-batches.

    Properties:
    - max-batch: flush as soon as this many frames are queued (the
      occupancy ceiling; also what dyn_batch advertises downstream).
    - max-latency-ms: per-frame latency budget. The deadline is armed
      when the FIRST frame of a batch arrives, so no frame ever waits
      longer than this (± one scheduler tick) for batch-mates.

    All sink pads must carry identical STATIC per-frame specs; frames
    from every pad share one batch (that is the point — cross-stream
    coalescing is where multi-tenant occupancy comes from).
    """

    ELEMENT_NAME = "tensor_batch"
    NUM_SINK_PADS = DYNAMIC
    NUM_SRC_PADS = 1
    # timer + fan-in: must run on its own worker, never in a fused chain
    CHAIN_FUSABLE = False
    PROPS = {
        "max_batch": PropDef(int, 8, "flush when this many frames queued"),
        "max_latency_ms": PropDef(
            float, 5.0, "max time the oldest frame waits for batch-mates"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        # all state below is touched only from this element's worker
        # thread (process/on_timer/next_deadline/flush contract), so no
        # locking is needed
        self._pending: List[dict] = []
        self._deadline: Optional[float] = None
        self._keepdims: List[bool] = []
        self._seq: Dict[object, int] = {}
        # counters surfaced through PipelineRunner.stats() (extra_stats)
        self.frames_in = 0
        self.batches_out = 0
        self.flush_full = 0
        self.flush_deadline = 0
        self.flush_eos = 0
        self.occupancy_hist: Dict[int, int] = {}

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        specs = [self.expect_tensors(s, i) for i, s in enumerate(in_specs)]
        first = specs[0]
        for i, s in enumerate(specs[1:], 1):
            if not first.is_compatible(s):
                self.fail_negotiation(
                    f"sink pad {i} spec {s} incompatible with pad 0 spec "
                    f"{first}; tensor_batch coalesces frames from every pad "
                    f"into one batch, so all input streams must share one "
                    f"per-frame type"
                )
        if first.format != TensorFormat.STATIC:
            self.fail_negotiation(
                f"input format is {first.format.name}; micro-batching needs "
                f"STATIC per-frame shapes (a batch axis over self-describing "
                f"flexible frames would be ragged)"
            )
        max_batch = int(self.props["max_batch"])
        if max_batch < 1:
            self.fail_negotiation(f"max-batch must be >= 1, got {max_batch}")
        if float(self.props["max_latency_ms"]) < 0:
            self.fail_negotiation(
                f"max-latency-ms must be >= 0, got "
                f"{self.props['max_latency_ms']}")
        # leading size-1 frame dims batch by concatenation (rank kept);
        # everything else stacks on a new axis — recorded per tensor so
        # process() doesn't re-derive it per frame
        self._keepdims = [
            len(t.shape) >= 1 and t.shape[0] == 1 for t in first.tensors
        ]
        return [replace(first, dyn_batch=max_batch)]

    # -- dataflow ----------------------------------------------------------
    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        stream_id = buf.meta.get("stream_id", pad)
        seq = self._seq.get(stream_id, 0)
        self._seq[stream_id] = seq + 1
        self.frames_in += 1
        if not self._pending:
            self._deadline = (time.perf_counter()
                             + float(self.props["max_latency_ms"]) * 1e-3)
        self._pending.append({
            "tensors": buf.tensors,
            "pts": buf.pts,
            "duration": buf.duration,
            "meta": buf.meta,
            "stream_id": stream_id,
            "seq": seq,
        })
        if len(self._pending) >= int(self.props["max_batch"]):
            return self._flush("full")
        return []

    def next_deadline(self) -> Optional[float]:
        return self._deadline if self._pending else None

    def on_timer(self) -> List[Emission]:
        if not self._pending:
            return []
        return self._flush("deadline")

    def flush(self) -> List[Emission]:
        if not self._pending:
            return []
        return self._flush("eos")

    def _flush(self, reason: str) -> List[Emission]:
        frames, self._pending = self._pending, []
        self._deadline = None
        n = len(frames)
        self.batches_out += 1
        self.occupancy_hist[n] = self.occupancy_hist.get(n, 0) + 1
        setattr(self, "flush_" + reason,
                getattr(self, "flush_" + reason) + 1)
        if self._tracer.active:
            # flush markers make batch assembly visible in the trace:
            # full vs deadline flushes with occupancy, per flush
            self._tracer.instant(self.name, "flush_" + reason, n=n)
        batched = []
        for j, keep in enumerate(self._keepdims):
            rows = [f["tensors"][j] for f in frames]
            xp = _xp(rows)
            batched.append(xp.concatenate(rows, axis=0) if keep
                           else xp.stack(rows, axis=0))
        out = TensorBuffer(
            tensors=tuple(batched),
            pts=frames[0]["pts"],
            duration=frames[0]["duration"],
            meta={"dyn_batch": {
                "n": n,
                "reason": reason,
                "frames": [{"pts": f["pts"], "duration": f["duration"],
                            "meta": f["meta"], "stream_id": f["stream_id"],
                            "seq": f["seq"]} for f in frames],
            }},
        )
        return [(0, out)]

    # -- stats -------------------------------------------------------------
    def extra_stats(self) -> dict:
        occ = self.occupancy_hist
        total = sum(n * c for n, c in occ.items())
        return {
            "frames_in": self.frames_in,
            "batches_out": self.batches_out,
            "flush_full": self.flush_full,
            "flush_deadline": self.flush_deadline,
            "flush_eos": self.flush_eos,
            "occupancy_hist": dict(sorted(occ.items())),
            "occupancy_avg": (total / self.batches_out
                              if self.batches_out else 0.0),
        }


@register_element("tensor_unbatch")
class TensorUnbatch(Element):
    """Split micro-batched buffers back into per-frame buffers.

    Restores each frame's pts/duration/meta from the batch's
    `dyn_batch` meta and emits in arrival order. With one src pad,
    every frame goes to pad 0; with several, each frame routes to the
    pad matching its stream_id tag (integer pad index), which undoes an
    N-stream fan-in through tensor_batch.
    """

    ELEMENT_NAME = "tensor_unbatch"
    NUM_SINK_PADS = 1
    NUM_SRC_PADS = DYNAMIC
    # 1→N emission: a chain expects one buffer out per buffer in
    CHAIN_FUSABLE = False
    ACCEPTS_DYN_BATCH = True
    PROPS = {}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ranks: List[int] = []

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if not spec.dyn_batch:
            self.fail_negotiation(
                f"input stream {spec} is not micro-batched; tensor_unbatch "
                f"only follows a tensor_batch (directly or across "
                f"batch-aware elements such as tensor_filter)"
            )
        n_out = len(self._pipeline.links_from(self)) if self._pipeline else 1
        self._ranks = [len(t.shape) for t in spec.tensors]
        return [replace(spec, dyn_batch=0)] * max(1, n_out)

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        db = buf.meta.get("dyn_batch")
        if db is None:
            # a well-formed upstream always tags batches; fail loud
            # rather than silently forwarding a mis-shaped buffer
            raise ValueError(
                f"{self.name}: buffer has no dyn_batch meta (upstream "
                f"element dropped it?)")
        n = db["n"]
        frames = db["frames"]
        n_pads = max(1, len(self.out_specs))
        out: List[Emission] = []
        for i in range(n):
            tensors = []
            for j, t in enumerate(buf.tensors):
                # rank == per-frame rank → frames were concatenated
                # (leading dim 1): slice keeps the frame's own rank;
                # rank + 1 → frames were stacked: index removes the axis
                tensors.append(t[i:i + 1] if t.ndim == self._ranks[j]
                               else t[i])
            fr = frames[i]
            meta = dict(fr["meta"])
            meta.setdefault("stream_id", fr["stream_id"])
            meta["batch_seq"] = fr["seq"]
            dst = fr["stream_id"] if n_pads > 1 else 0
            if not isinstance(dst, int) or not 0 <= dst < n_pads:
                raise ValueError(
                    f"{self.name}: frame stream_id {fr['stream_id']!r} does "
                    f"not name one of {n_pads} src pads; with multiple "
                    f"output pads stream ids must be integer pad indices")
            out.append((dst, TensorBuffer(
                tensors=tuple(tensors), pts=fr["pts"],
                duration=fr["duration"], meta=meta)))
        return out
