"""Wire-format converter/decoder subplugins.

Reference parity: the flatbuf/flexbuf/protobuf converter+decoder pairs
(ext/nnstreamer/tensor_converter/*, tensor_decoder/tensordec-{flatbuf,
flexbuf,protobuf}.cc) that serialize tensor streams for IPC. The three
schema formats collapse into the one self-describing wire codec
(edge/wire.py — schema-free like flexbuf, versioned magic like flatbuf):

- decoder mode ``wire``: tensors → one uint8 wire-frame tensor
  (application/octet-stream payload a transport ships as-is)
- converter ``mode=custom:wire``: wire bytes → the original tensors

Roundtrip: `... ! tensor_decoder mode=wire ! <any byte transport> !
tensor_converter mode=custom:wire ! ...`.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.edge.wire import decode_buffer, encode_buffer
from nnstreamer_tpu.elements.converter import ConverterSubplugin, register_converter
from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import MediaSpec, OctetSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec


@register_decoder("wire")
class WireEncode(DecoderSubplugin):
    """tensors → wire bytes (the flatbuf/protobuf decoder analog)."""

    def negotiate(self, in_spec: TensorsSpec) -> OctetSpec:
        return OctetSpec(rate=in_spec.rate)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        frame = encode_buffer(buf)
        return buf.with_tensors((np.frombuffer(frame, np.uint8).copy(),))


@register_converter("wire")
class WireDecode(ConverterSubplugin):
    """wire bytes → tensors. The stream is FLEXIBLE: every frame is
    self-describing, so shapes may vary per buffer (the property the
    reference gets from flexbuf)."""

    def negotiate(self, in_spec: MediaSpec) -> TensorsSpec:
        return TensorsSpec(tensors=(), format=TensorFormat.FLEXIBLE,
                           rate=in_spec.rate)

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        data = np.ascontiguousarray(np.asarray(buf.tensors[0])).tobytes()
        out, _ = decode_buffer(data)
        if buf.pts is not None and out.pts is None:
            out = out.with_tensors(out.tensors, pts=buf.pts)
        return out
