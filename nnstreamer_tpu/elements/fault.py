"""tensor_fault — deterministic fault injection for robustness testing.

No reference equivalent: the reference exercises error paths with
hand-built broken pipelines; here chaos is a first-class passthrough
element you splice anywhere (`bench.py --chaos` does it mechanically).
Faults are *seeded* — the same seed + stream yields the same fault
frames — so a failing chaos run is replayable.

Modes:
- ``raise``          raise FaultInjected (exercises error-policy paths)
- ``corrupt``        cast tensors to a wrong dtype (breaks downstream
                     dtype expectations)
- ``corrupt-shape``  flatten tensors to 1-D (breaks shape expectations)
- ``delay``          sleep ``delay-ms`` before passing through
                     (exercises the watchdog stall budget; stop-aware)
- ``drop``           swallow the buffer (emits nothing)

Fault frames are picked by ``period`` (every Nth buffer, deterministic)
or ``probability`` (seeded RNG), capped by ``max-faults``.
"""

from __future__ import annotations

import time
from typing import List, Sequence

import numpy as np

from nnstreamer_tpu.core.errors import FaultInjected, PipelineError
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import Element, Emission, PropDef, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer

_MODES = ("raise", "corrupt", "corrupt-shape", "delay", "drop")


@register_element("tensor_fault")
class TensorFault(Element):
    ELEMENT_NAME = "tensor_fault"
    # transparent passthrough: a micro-batched stream flows through
    # untouched (faults then apply per coalesced buffer, not per frame)
    ACCEPTS_DYN_BATCH = True
    PROPS = {
        "mode": PropDef(str, "raise", "|".join(_MODES)),
        "probability": PropDef(float, 0.0, "per-buffer fault probability"),
        "period": PropDef(int, 0, "fault every Nth buffer (0 = off)"),
        "seed": PropDef(int, 0, "RNG seed (probability mode)"),
        "delay_ms": PropDef(float, 100.0, "sleep for mode=delay"),
        "max_faults": PropDef(int, 0, "stop injecting after N (0 = no cap)"),
        "corrupt_dtype": PropDef(str, "uint8", "target dtype for mode=corrupt"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._rng = np.random.default_rng(self.props["seed"])
        self._frame = 0
        self.injected = 0
        self.passed = 0

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        mode = self.props["mode"]
        if mode not in _MODES:
            self.fail_negotiation(
                f"unknown mode {mode!r}; expected one of {'|'.join(_MODES)}")
        if self.props["probability"] < 0 or self.props["probability"] > 1:
            self.fail_negotiation(
                f"probability={self.props['probability']} outside [0, 1]")
        return [in_specs[0]]

    def _should_fault(self) -> bool:
        if self.props["max_faults"] and self.injected >= self.props["max_faults"]:
            return False
        period = self.props["period"]
        if period > 0:
            return self._frame % period == 0
        p = self.props["probability"]
        return p > 0 and self._rng.random() < p

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        self._frame += 1
        if not self._should_fault():
            self.passed += 1
            return [(pad, buf)]
        self.injected += 1
        mode = self.props["mode"]
        if mode == "raise":
            raise FaultInjected(
                f"tensor_fault {self.name}: injected failure on buffer "
                f"#{self._frame} (pts={buf.pts}, seed={self.props['seed']})")
        if mode == "drop":
            return []
        if mode == "delay":
            # stop-aware sleep: teardown mid-delay wakes immediately
            # (_stop_evt is installed by the runner; standalone use sleeps)
            delay = self.props["delay_ms"] / 1e3
            if self._stop_evt is not None:
                self._stop_evt.wait(delay)
            else:
                time.sleep(delay)
            self.passed += 1
            return [(pad, buf)]
        if mode == "corrupt":
            try:
                dt = np.dtype(self.props["corrupt_dtype"])
            except TypeError:
                raise PipelineError(
                    f"tensor_fault {self.name}: bad corrupt-dtype "
                    f"{self.props['corrupt_dtype']!r}") from None
            bad = tuple(np.asarray(t).astype(dt) for t in buf.tensors)
        else:  # corrupt-shape
            bad = tuple(np.asarray(t).reshape(-1) for t in buf.tensors)
        return [(pad, buf.with_tensors(bad))]

    def extra_stats(self) -> dict:
        return {"faults_injected": self.injected,
                "buffers_passed": self.passed}
