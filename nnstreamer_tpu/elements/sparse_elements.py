"""tensor_sparse_enc / tensor_sparse_dec — COO sparse codec elements.

Reference parity: gsttensor_sparseenc.c / gsttensor_sparsedec.c /
gsttensor_sparseutil.c. The wire codec itself lives in tensor/sparse.py
(values + flat uint32 indices after a self-describing MetaHeader); these
elements switch a stream between STATIC dense payloads and SPARSE
byte payloads (each tensor becomes a uint8 wire-frame array — the shape
a transport element ships as-is).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import Element, Emission, StreamSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec
from nnstreamer_tpu.tensor.sparse import sparse_decode, sparse_encode


@register_element("tensor_sparse_enc")
class TensorSparseEnc(Element):
    ELEMENT_NAME = "tensor_sparse_enc"
    PROPS = {}

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if spec.format != TensorFormat.STATIC:
            self.fail_negotiation(
                f"sparse encoder takes a STATIC dense stream, got "
                f"{spec.format.name}"
            )
        return [TensorsSpec(tensors=spec.tensors,
                            format=TensorFormat.SPARSE, rate=spec.rate)]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        frames = tuple(
            np.frombuffer(sparse_encode(np.asarray(t)), np.uint8)
            for t in buf.tensors
        )
        return [(0, buf.with_tensors(frames, format=TensorFormat.SPARSE))]


@register_element("tensor_sparse_dec")
class TensorSparseDec(Element):
    ELEMENT_NAME = "tensor_sparse_dec"
    PROPS = {}

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if spec.format != TensorFormat.SPARSE:
            self.fail_negotiation(
                f"sparse decoder takes a SPARSE stream (from "
                f"tensor_sparse_enc or a transport), got {spec.format.name}"
            )
        return [TensorsSpec(tensors=spec.tensors,
                            format=TensorFormat.STATIC, rate=spec.rate)]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        dense = tuple(sparse_decode(np.asarray(t).tobytes())
                      for t in buf.tensors)
        return [(0, buf.with_tensors(dense, format=TensorFormat.STATIC))]
