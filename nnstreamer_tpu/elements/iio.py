"""tensor_src_iio — Linux IIO sensor source.

Reference parity: gst/nnstreamer/elements/gsttensor_srciio.c (2604 LoC,
the largest single element in the reference). Reads an Industrial-I/O
device's scan_elements channel config from sysfs
(`/sys/bus/iio/devices/iio:deviceN/`), decodes the device's binary
sample stream, and emits per-sample (or merged) tensors.

sysfs layout consumed (same files the reference reads):
  <base>/iio:deviceN/name                      device name
  <base>/iio:deviceN/sampling_frequency        Hz (optional)
  <base>/iio:deviceN/scan_elements/in_X_en     1 if channel enabled
  <base>/iio:deviceN/scan_elements/in_X_index  position in the frame
  <base>/iio:deviceN/scan_elements/in_X_type   "le:s12/16>>4" layout
  <base>/iio:deviceN/in_X_scale / in_X_offset  optional float transforms

TPU-first redesign notes:
- configuration parsing is identical in spirit but ~10× smaller: numpy
  decodes whole sample blocks vectorized instead of per-sample bit
  fiddling; the (raw + offset) * scale transform happens on the full
  block at once.
- the data source is a file path (`data` property): `/dev/iio:deviceN`
  on a real system, a regular file in tests (the reference's own test
  uses a fake sysfs tree the same way, tests/nnstreamer_source_iio).
  A regular file is read once then EOS; a char device streams forever.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from fractions import Fraction
from typing import Iterator, List, Optional

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import PropDef, SourceElement, StreamSpec, prop_bool
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.dtypes import DType
from nnstreamer_tpu.tensor.info import TensorInfo, TensorsSpec

log = get_logger("elements.iio")

DEFAULT_BASE = "/sys/bus/iio/devices"
_TYPE_RE = re.compile(
    r"^(?P<endian>be|le):(?P<sign>[su])(?P<used>\d+)/(?P<storage>\d+)"
    r">>(?P<shift>\d+)\s*$")


@dataclass
class _Channel:
    name: str
    index: int
    used_bits: int
    storage_bits: int
    shift: int
    signed: bool
    big_endian: bool
    scale: float = 1.0
    offset: float = 0.0

    @property
    def np_dtype(self) -> np.dtype:
        size = self.storage_bits // 8
        if size not in (1, 2, 4, 8):
            raise PipelineError(
                f"iio channel {self.name}: storage {self.storage_bits} bits "
                f"is not byte-aligned")
        return np.dtype(f"{'>' if self.big_endian else '<'}u{size}")

    def decode(self, raw: np.ndarray) -> np.ndarray:
        """Vectorized sample decode: shift, mask to used bits, sign-extend,
        then (value + offset) * scale → float32 (IIO convention)."""
        v = raw.astype(np.uint64) >> np.uint64(self.shift)
        mask = np.uint64((1 << self.used_bits) - 1)
        v = v & mask
        if self.signed:
            # sign-extend any width (incl. 64-bit timestamps) without
            # overflow: left-align in the 64-bit word, arithmetic shift back
            sh = np.uint64(64 - self.used_bits)
            vi = (v << sh).view(np.int64) >> np.int64(sh)
            out = vi.astype(np.float32)
        else:
            out = v.astype(np.float32)
        return ((out + self.offset) * self.scale).astype(np.float32)


def _read_file(path: str) -> Optional[str]:
    try:
        with open(path, "r") as f:
            return f.read().strip()
    except OSError:
        return None


def parse_channel_type(name: str, text: str) -> dict:
    """Parse the scan_elements *_type format ("le:s12/16>>4",
    gsttensor_srciio.c:725-790)."""
    m = _TYPE_RE.match(text)
    if not m:
        raise PipelineError(
            f"iio channel {name}: bad _type contents {text!r}; expected "
            f"e.g. 'le:s12/16>>4'")
    used = int(m.group("used"))
    storage = int(m.group("storage"))
    if used == 0 or used > storage or storage > 64:
        raise PipelineError(
            f"iio channel {name}: invalid bits {used}/{storage}")
    return dict(used_bits=used, storage_bits=storage,
                shift=int(m.group("shift")),
                signed=m.group("sign") == "s",
                big_endian=m.group("endian") == "be")


@register_element("tensor_src_iio")
class TensorSrcIIO(SourceElement):
    """Emit IIO sensor samples as tensor frames.

    device: device name (matched against <base>/iio:deviceN/name) or
    "iio:deviceN" directly. frames_per_tensor: samples per emitted
    buffer. merge_channels: one (frames, channels) float32 tensor
    (default) vs one tensor per channel. data: sample stream path
    (defaults to /dev/<device>).
    """

    ELEMENT_NAME = "tensor_src_iio"
    PROPS = {
        "device": PropDef(str, None, "IIO device name or iio:deviceN"),
        "base_dir": PropDef(str, DEFAULT_BASE, "sysfs root (tests override)"),
        "data": PropDef(str, "", "sample stream path (default /dev/<dev>)"),
        "frames_per_tensor": PropDef(int, 1),
        "merge_channels": PropDef(prop_bool, True),
        "num_buffers": PropDef(int, 0, "0 = until EOF"),
        "frequency": PropDef(int, 0, "override sampling_frequency (Hz)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["device"]:
            raise PipelineError(
                f"tensor_src_iio ({self.name}) requires device=<name|"
                f"iio:deviceN>")
        self._channels: List[_Channel] = []
        self._dev_dir = ""
        self._rate = Fraction(0, 1)

    # -- sysfs scan (start-time config, gsttensor_srciio.c:1620-1700) ------
    def _find_device_dir(self) -> str:
        base = self.props["base_dir"]
        want = self.props["device"]
        if want.startswith("iio:device"):
            d = os.path.join(base, want)
            if not os.path.isdir(d):
                raise PipelineError(
                    f"tensor_src_iio {self.name}: no {d!r}")
            return d
        try:
            entries = sorted(os.listdir(base))
        except OSError as e:
            raise PipelineError(
                f"tensor_src_iio {self.name}: cannot scan {base!r}: {e}"
            ) from None
        for ent in entries:
            if not ent.startswith("iio:device"):
                continue
            nm = _read_file(os.path.join(base, ent, "name"))
            if nm == want:
                return os.path.join(base, ent)
        raise PipelineError(
            f"tensor_src_iio {self.name}: no IIO device named {want!r} "
            f"under {base!r} (found: "
            f"{[e for e in entries if e.startswith('iio:')]}))")

    def _scan_channels(self, dev_dir: str) -> List[_Channel]:
        scan = os.path.join(dev_dir, "scan_elements")
        if not os.path.isdir(scan):
            raise PipelineError(
                f"tensor_src_iio {self.name}: {scan!r} missing — device "
                f"has no buffered capture support")
        chans: List[_Channel] = []
        for fn in sorted(os.listdir(scan)):
            if not fn.endswith("_en"):
                continue
            chan_name = fn[:-3]
            if _read_file(os.path.join(scan, fn)) != "1":
                continue
            idx = _read_file(os.path.join(scan, f"{chan_name}_index"))
            typ = _read_file(os.path.join(scan, f"{chan_name}_type"))
            if idx is None or typ is None:
                raise PipelineError(
                    f"tensor_src_iio {self.name}: channel {chan_name} "
                    f"missing _index/_type in {scan!r}")
            spec = parse_channel_type(chan_name, typ)
            scale = _read_file(os.path.join(dev_dir, f"{chan_name}_scale"))
            offset = _read_file(os.path.join(dev_dir, f"{chan_name}_offset"))
            chans.append(_Channel(
                name=chan_name, index=int(idx),
                scale=float(scale) if scale else 1.0,
                offset=float(offset) if offset else 0.0, **spec))
        if not chans:
            raise PipelineError(
                f"tensor_src_iio {self.name}: no enabled channels in "
                f"{scan!r} (echo 1 > in_..._en)")
        chans.sort(key=lambda c: c.index)
        return chans

    def output_spec(self) -> StreamSpec:
        self._dev_dir = self._find_device_dir()
        self._channels = self._scan_channels(self._dev_dir)
        hz = self.props["frequency"] or int(
            float(_read_file(os.path.join(self._dev_dir,
                                          "sampling_frequency")) or 0))
        self._rate = Fraction(hz, max(1, self.props["frames_per_tensor"])) \
            if hz else Fraction(0, 1)
        n = self.props["frames_per_tensor"]
        if self.props["merge_channels"]:
            infos = (TensorInfo((n, len(self._channels)), DType.FLOAT32),)
        else:
            infos = tuple(TensorInfo((n, 1), DType.FLOAT32,
                                     name=c.name) for c in self._channels)
        return TensorsSpec(tensors=infos, rate=self._rate)

    # -- capture loop ------------------------------------------------------
    def _layout(self) -> List[int]:
        """Byte offset of each channel in a frame. The kernel aligns every
        scan element to its own storage size (gsttensor_srciio.c:1503-1522:
        location = align(running_size, storage_bytes)), so mixed-width
        channels (e.g. 3×s16 + u64 timestamp) have padding holes."""
        offs, size = [], 0
        for c in self._channels:
            sb = c.storage_bits // 8
            rem = size % sb
            loc = size if rem == 0 else size - rem + sb
            offs.append(loc)
            size = loc + sb
        return offs

    @property
    def _frame_bytes(self) -> int:
        offs = self._layout()
        last = self._channels[-1]
        return offs[-1] + last.storage_bits // 8

    def _data_path(self) -> str:
        if self.props["data"]:
            return self.props["data"]
        return os.path.join("/dev", os.path.basename(self._dev_dir))

    def generate(self) -> Iterator[TensorBuffer]:
        path = self._data_path()
        fpt = self.props["frames_per_tensor"]
        block = self._frame_bytes * fpt
        limit = self.props["num_buffers"]
        period_ns = int(1e9 / self._rate) if self._rate else 0
        emitted = 0
        try:
            f = open(path, "rb", buffering=0)
        except OSError as e:
            raise PipelineError(
                f"tensor_src_iio {self.name}: cannot open data stream "
                f"{path!r}: {e}") from None
        with f:
            while not limit or emitted < limit:
                # raw char devices legally return short reads when fewer
                # samples are buffered: accumulate until a full block or
                # true EOF (empty read)
                data = b""
                while len(data) < block:
                    chunk = f.read(block - len(data))
                    if not chunk:
                        break
                    data += chunk
                if len(data) < block:
                    if data:
                        log.warning(
                            "%s: discarding %d trailing bytes (< one "
                            "%d-byte block) at EOF", self.name, len(data),
                            block)
                    break   # EOF (regular file) or device stopped
                yield self._decode_block(data, fpt, emitted, period_ns)
                emitted += 1

    def _decode_block(self, data: bytes, fpt: int, seq: int,
                      period_ns: int) -> TensorBuffer:
        # split interleaved storage: channels sit at their aligned
        # locations within each frame (kernel scan-element layout)
        cols = []
        stride = self._frame_bytes
        offs = self._layout()
        raw = np.frombuffer(data, np.uint8).reshape(fpt, stride)
        for c, off in zip(self._channels, offs):
            size = c.storage_bits // 8
            col = raw[:, off:off + size].copy().view(c.np_dtype)[:, 0]
            cols.append(c.decode(col))
        pts = seq * period_ns if period_ns else seq
        if self.props["merge_channels"]:
            return TensorBuffer.of(np.stack(cols, axis=1), pts=pts)
        return TensorBuffer.of(*(col[:, None] for col in cols), pts=pts)
