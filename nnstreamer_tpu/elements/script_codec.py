"""Scripted converter/decoder subplugins — the reference's python3
custom-script contract.

Reference parity:
- `tensor_decoder mode=python3 option1=<script.py>` —
  `ext/nnstreamer/tensor_decoder/tensordec-python3.cc`: the script's
  ``CustomDecoder.decode(raw_data, in_info, rate_n, rate_d) -> bytes``
  serializes tensors to a media frame, caps from ``getOutCaps()``.
- `tensor_converter mode=custom-script:<script.py>` —
  `ext/nnstreamer/tensor_converter/tensor_converter_python3.cc`: the
  script's ``CustomConverter.convert([bytes array]) ->
  (tensors_info, raw_data, rate_n, rate_d)`` parses a media frame into
  tensors (a FLEXIBLE stream — shapes are per-frame).

Both run the reference's own checked-in scripts unmodified
(`tests/test_models/models/custom_decoder.py` / `custom_converter.py`,
flexbuffers wire) — goldens in tests/test_python3_filter.py include
cross-interop with this repo's native flexbuf codec.
"""

from __future__ import annotations

from fractions import Fraction
from typing import List, Optional

import numpy as np

from nnstreamer_tpu.backends.python3_script import (
    TensorShape, load_script_class)
from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.elements.converter import ConverterSubplugin
from nnstreamer_tpu.elements.decoder import DecoderSubplugin, register_decoder
from nnstreamer_tpu.graph.media import MediaSpec, OctetSpec
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec


def _rate_pair(rate: Optional[Fraction]):
    if not rate:
        return 0, 1
    return int(rate.numerator), int(rate.denominator)


@register_decoder("python3")
class Python3Decoder(DecoderSubplugin):
    """tensors → media bytes via a CustomDecoder script."""

    def init(self, props: dict) -> None:
        path = props.get("option1", "")
        if not path:
            raise PipelineError(
                "tensor_decoder mode=python3 requires option1=<script "
                "path> (reference tensordec-python3 contract)")
        cls = load_script_class(path, "CustomDecoder")
        try:
            self._decoder = cls()
        except Exception as e:
            raise PipelineError(
                f"python3 decoder script {path!r}: CustomDecoder() "
                f"raised {type(e).__name__}: {e}") from e
        self._path = path

    def negotiate(self, in_spec: TensorsSpec) -> MediaSpec:
        self._rate = in_spec.rate
        return OctetSpec(rate=in_spec.rate)

    def decode(self, buf: TensorBuffer) -> TensorBuffer:
        arrays = [np.asarray(t) for t in buf.tensors]
        raw = [np.ascontiguousarray(a).view(np.uint8).ravel()
               for a in arrays]
        # reference dims are innermost-first, always rank 4 (1-padded);
        # higher ranks cannot be represented on this wire — fail loud
        # rather than truncate
        for a in arrays:
            if a.ndim > 4:
                raise PipelineError(
                    f"python3 decoder {self._path!r}: rank-{a.ndim} "
                    f"tensor does not fit the reference's 4-dim wire")
        info = [TensorShape(
            (list(reversed(a.shape)) + [1, 1, 1, 1])[:4], a.dtype)
            for a in arrays]
        n, d = _rate_pair(getattr(self, "_rate", None))
        out = self._decoder.decode(raw, info, n, d)
        if not isinstance(out, (bytes, bytearray)):
            raise PipelineError(
                f"python3 decoder {self._path!r}: decode returned "
                f"{type(out).__name__}, expected bytes")
        return buf.with_tensors(
            (np.frombuffer(bytes(out), np.uint8).copy(),))


class Python3Converter(ConverterSubplugin):
    """media bytes → tensors via a CustomConverter script (FLEXIBLE
    stream: every frame is self-describing)."""

    def __init__(self, path: str):
        cls = load_script_class(path, "CustomConverter")
        try:
            self._converter = cls()
        except Exception as e:
            raise PipelineError(
                f"python3 converter script {path!r}: CustomConverter() "
                f"raised {type(e).__name__}: {e}") from e
        self._path = path

    def negotiate(self, in_spec: MediaSpec) -> TensorsSpec:
        return TensorsSpec(tensors=(), format=TensorFormat.FLEXIBLE,
                           rate=in_spec.rate)

    def convert(self, buf: TensorBuffer) -> TensorBuffer:
        flat = np.ascontiguousarray(
            np.asarray(buf.tensors[0])).view(np.uint8).ravel()
        out = self._converter.convert([flat])
        try:
            infos, raws, rate_n, rate_d = out
        except (TypeError, ValueError):
            raise PipelineError(
                f"python3 converter {self._path!r}: convert must "
                f"return (tensors_info, raw_data, rate_n, rate_d), "
                f"got {type(out).__name__}")
        if len(infos) != len(raws):
            raise PipelineError(
                f"python3 converter {self._path!r}: {len(infos)} "
                f"tensors_info entries but {len(raws)} raw_data blobs")
        tensors: List[np.ndarray] = []
        for ts, raw in zip(infos, raws):
            if not isinstance(ts, TensorShape):
                raise PipelineError(
                    f"python3 converter {self._path!r}: tensors_info "
                    f"entries must be nnstreamer_python.TensorShape")
            dt = np.dtype(ts.getType())
            # reference dims are innermost-first and zero/one-padded to
            # rank 4; trim trailing 0 axes beyond the data size
            dims = [int(x) for x in ts.getDims() if int(x) > 0]
            shape = tuple(reversed(dims))
            if not isinstance(raw, (bytes, bytearray)):
                raw = np.ascontiguousarray(np.asarray(raw))
            arr = np.frombuffer(raw, np.uint8).view(dt)
            if arr.size != int(np.prod(shape)):
                raise PipelineError(
                    f"python3 converter {self._path!r}: tensor of "
                    f"{arr.size} {dt} elements does not fit declared "
                    f"dims {dims}")
            tensors.append(arr.reshape(shape).copy())
        meta = {}
        if rate_n and rate_d:
            meta["rate"] = (int(rate_n), int(rate_d))
        return TensorBuffer(tensors=tuple(tensors),
                            format=TensorFormat.FLEXIBLE,
                            meta=meta, pts=buf.pts)


def make_script_converter(path: str) -> ConverterSubplugin:
    """Factory for `tensor_converter mode=custom-script:<path>`."""
    return Python3Converter(path)
