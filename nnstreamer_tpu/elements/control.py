"""Data-driven control-flow elements: tensor_if, tensor_rate, tensor_crop.

Reference parity (SURVEY.md §2.2):
- tensor_if  (gsttensor_if.c) — stream branching on tensor values:
  compared-value modes (gsttensor_if.h:42-55), 10 operators (:60-71),
  then/else actions (:79-91) incl. passthrough/skip/fill-zero/tensorpick,
  plus registered custom python predicates (TIFCV_CUSTOM analog).
- tensor_rate (gsttensor_rate.c) — framerate conform by drop/dup with
  `throttle` QoS.
- tensor_crop (gsttensor_crop.c) — data-driven crop: geometry arrives as
  a second stream; output is FLEXIBLE (per-buffer shapes).

TPU-first note (§7 hard part c): tensor_if's condition evaluates on tiny
scalars. When the compared tensors live on device, only the reduced
scalar comes back to host (one cheap D2H of 4 bytes), never the payload;
the payload arrays keep flowing by reference.
"""

from __future__ import annotations

import operator
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import (
    DYNAMIC, Element, Emission, PropDef, StreamSpec, prop_bool)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec

# -- tensor_if ---------------------------------------------------------------

#: registered custom predicates (include/tensor_if.h analog)
_custom_conds: Dict[str, Callable[[TensorBuffer], bool]] = {}


def register_if_condition(name: str, fn: Callable[[TensorBuffer], bool]) -> None:
    """Custom-condition registration (TIFCV_CUSTOM analog): fn(buffer)->bool."""
    _custom_conds[name] = fn


_OPS = {
    "eq": operator.eq, "ne": operator.ne,
    "gt": operator.gt, "ge": operator.ge,
    "lt": operator.lt, "le": operator.le,
}
#: two-operand range operators (gsttensor_if.h:67-70); supplied_value is
#: "lo:hi" for these
_RANGE_OPS = {
    "range_inclusive": lambda v, lo, hi: lo <= v <= hi,
    "range_exclusive": lambda v, lo, hi: lo < v < hi,
    "not_in_range_inclusive": lambda v, lo, hi: not (lo <= v <= hi),
    "not_in_range_exclusive": lambda v, lo, hi: not (lo < v < hi),
}

CV_MODES = ("a_value", "average", "custom")
ACTIONS = ("passthrough", "skip", "fill_zero", "fill_values",
           "fill_with_file", "repeat_previous", "tensorpick")


@register_element("tensor_if")
class TensorIf(Element):
    """2 src pads: 0 = then-branch, 1 = else-branch (optional).

    compared_value: a_value (option "<tensor>:<flat_index>"), average
    (option "<tensor>"), or custom (option = registered predicate name).
    operator: eq|ne|gt|ge|lt|le against supplied_value (single float), or
    range_inclusive|range_exclusive|not_in_range_inclusive|
    not_in_range_exclusive against supplied_value "lo:hi"
    (gsttensor_if.h:60-71, all 10 operators).
    then/else: passthrough | skip | fill_zero | fill_values (option =
    per-tensor values, comma-separated, single value broadcasts) |
    fill_with_file (option = raw payload file) | repeat_previous |
    tensorpick (option = comma indices). (gsttensor_if.h:79-91.)
    """

    ELEMENT_NAME = "tensor_if"
    NUM_SRC_PADS = DYNAMIC
    # branching fan-out: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {
        "compared_value": PropDef(str, "a_value", "|".join(CV_MODES)),
        "compared_value_option": PropDef(str, "0:0"),
        "operator": PropDef(str, "gt",
                            "|".join(list(_OPS) + list(_RANGE_OPS))),
        "supplied_value": PropDef(lambda s: s, 0.0,
                                  "float, or 'lo:hi' for range operators"),
        "then": PropDef(str, "passthrough", "|".join(ACTIONS)),
        "then_option": PropDef(str, ""),
        "else_": PropDef(str, "skip", "|".join(ACTIONS)),
        "else_option": PropDef(str, ""),
    }

    def __init__(self, name=None, **props):
        props = {("else_" if k in ("else", "else-") else k): v
                 for k, v in props.items()}
        super().__init__(name, **props)
        op = self.props["operator"]
        if op not in _OPS and op not in _RANGE_OPS:
            raise PipelineError(
                f"tensor_if {self.name}: unknown operator {op!r}; valid: "
                f"{sorted(_OPS) + sorted(_RANGE_OPS)}"
            )
        if self.props["compared_value"] not in CV_MODES:
            raise PipelineError(
                f"tensor_if {self.name}: unknown compared_value "
                f"{self.props['compared_value']!r}; valid: {CV_MODES}"
            )
        self._sv = self._parse_supplied(self.props["supplied_value"], op)
        for which in ("then", "else_"):
            if self.props[which] not in ACTIONS:
                raise PipelineError(
                    f"tensor_if {self.name}: unknown {which.rstrip('_')} "
                    f"action {self.props[which]!r}; valid: {ACTIONS}"
                )
        # per-branch action material, keyed by src pad (then=0, else=1):
        # both branches may use fill_with_file/fill_values with different
        # options, so nothing here may be shared state
        self._fill_bytes: Dict[int, bytes] = {}
        self._fill_vals: Dict[int, List[float]] = {}
        for pad_i, which in enumerate(("then", "else_")):
            option = self.props[f"{which.rstrip('_')}_option"]
            if self.props[which] == "fill_with_file":
                try:
                    with open(option, "rb") as f:
                        self._fill_bytes[pad_i] = f.read()
                except OSError as e:
                    raise PipelineError(
                        f"tensor_if {self.name}: fill_with_file cannot "
                        f"read {option!r}: {e}"
                    ) from None
            elif self.props[which] == "fill_values":
                try:
                    vals = [float(v) for v in str(option).split(",")
                            if v.strip()]
                except ValueError:
                    raise PipelineError(
                        f"tensor_if {self.name}: fill_values option "
                        f"{option!r} is not a comma-separated value list"
                    ) from None
                if not vals:
                    raise PipelineError(
                        f"tensor_if {self.name}: fill_values needs option="
                        f"<v>[,<v>…] (one per tensor, or one broadcast)")
                self._fill_vals[pad_i] = vals
        self._last_fwd: Optional[TensorBuffer] = None

    def _parse_supplied(self, sv, op: str):
        parts = str(sv).split(":")
        try:
            vals = tuple(float(p) for p in parts if p != "")
        except ValueError:
            raise PipelineError(
                f"tensor_if {self.name}: bad supplied_value {sv!r}"
            ) from None
        need = 2 if op in _RANGE_OPS else 1
        if len(vals) != need:
            raise PipelineError(
                f"tensor_if {self.name}: operator {op!r} needs "
                f"{need} supplied value(s), got {len(vals)} from {sv!r}"
                + (" (use supplied_value=lo:hi)" if need == 2 else "")
            )
        if need == 2 and vals[0] > vals[1]:
            raise PipelineError(
                f"tensor_if {self.name}: range lo {vals[0]} > hi {vals[1]}"
            )
        return vals

    def _out_spec_for(self, action: str, option: str,
                      spec: TensorsSpec) -> TensorsSpec:
        if action == "tensorpick":
            idxs = [int(x) for x in option.split(",") if x.strip()]
            for i in idxs:
                if i >= spec.num_tensors:
                    self.fail_negotiation(
                        f"tensorpick index {i} out of range "
                        f"({spec.num_tensors} tensors)"
                    )
            return TensorsSpec(
                tensors=tuple(spec.tensors[i] for i in idxs),
                rate=spec.rate)
        return spec

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        n_out = len(self._pipeline.links_from(self)) if self._pipeline else 1
        if n_out not in (1, 2):
            self.fail_negotiation(
                f"tensor_if has then/else src pads; {n_out} links found"
            )
        outs = [self._out_spec_for(self.props["then"],
                                   self.props["then_option"], spec)]
        if n_out == 2:
            outs.append(self._out_spec_for(self.props["else_"],
                                           self.props["else_option"], spec))
        # repeat_previous replays the last forwarded buffer, which may
        # come from the other branch: that is only spec-safe if the other
        # branch is shape-preserving (tensorpick would replay a subset
        # onto a pad negotiated for the full tensor set)
        acts = (self.props["then"], self.props["else_"])
        if "repeat_previous" in acts and "tensorpick" in acts:
            self.fail_negotiation(
                "repeat_previous cannot pair with tensorpick on the other "
                "branch: the repeated buffer would not match this pad's "
                "negotiated tensor set")
        return outs

    # -- condition evaluation (tensor_data.c scalar math analog) -----------
    def _decide(self, buf: TensorBuffer) -> bool:
        mode = self.props["compared_value"]
        opt = self.props["compared_value_option"]
        if mode == "custom":
            fn = _custom_conds.get(opt)
            if fn is None:
                raise PipelineError(
                    f"tensor_if {self.name}: no custom condition {opt!r} "
                    f"registered; call register_if_condition() first"
                )
            return bool(fn(buf))
        if mode == "a_value":
            ti, _, idx = opt.partition(":")
            t = buf.tensors[int(ti or 0)]
            flat_idx = int(idx or 0)
            # index on whatever device t lives — float() then moves only
            # the one scalar to host (the §7(c) no-stall property)
            val = float(t.reshape(-1)[flat_idx])
        else:  # average
            t = buf.tensors[int(opt or 0)]
            # device-side reduce → single scalar D2H
            val = float(np.asarray(t.mean() if hasattr(t, "mean")
                                   else np.mean(t)))
        op = self.props["operator"]
        if op in _RANGE_OPS:
            return _RANGE_OPS[op](val, self._sv[0], self._sv[1])
        return _OPS[op](val, self._sv[0])

    def _apply(self, action: str, option: str, pad: int,
               buf: TensorBuffer) -> List[Emission]:
        if action == "passthrough":
            return [(pad, buf)]
        if action == "skip":
            return []
        if action == "fill_zero":
            # build zeros from shape/dtype — never pull the payload to host
            zeros = tuple(np.zeros(t.shape, t.dtype) for t in buf.tensors)
            return [(pad, buf.with_tensors(zeros))]
        if action == "fill_values":
            vals = self._fill_vals[pad]   # parsed/validated in __init__
            if len(vals) == 1:
                vals = vals * buf.num_tensors
            if len(vals) != buf.num_tensors:
                raise PipelineError(
                    f"tensor_if {self.name}: fill_values got {len(vals)} "
                    f"values for {buf.num_tensors} tensors")
            filled = tuple(np.full(t.shape, v, t.dtype)
                           for t, v in zip(buf.tensors, vals))
            return [(pad, buf.with_tensors(filled))]
        if action == "fill_with_file":
            tensors = []
            off = 0
            data = self._fill_bytes.get(pad, b"")
            for i, t in enumerate(buf.tensors):
                dt = np.dtype(str(t.dtype)) if not isinstance(t, np.ndarray) \
                    else t.dtype
                n = int(np.prod(t.shape)) * dt.itemsize
                if off + n > len(data):
                    raise PipelineError(
                        f"tensor_if {self.name}: fill file has "
                        f"{len(data)} bytes but tensor {i} needs bytes "
                        f"[{off}, {off + n})")
                tensors.append(np.frombuffer(
                    data, dt, count=int(np.prod(t.shape)),
                    offset=off).reshape(t.shape))
                off += n
            return [(pad, buf.with_tensors(tuple(tensors)))]
        if action == "repeat_previous":
            # re-emit the element's last forwarded frame (either branch)
            # with the current frame's timestamp: then=passthrough /
            # else=repeat_previous gives downstream the last good frame
            # when the condition fails. Declared-but-unimplemented in the
            # reference chain (gsttensor_if.c:1171 default case), so the
            # useful semantics are defined here. Skip when no history.
            prev = self._last_fwd
            if prev is None:
                return []   # nothing to repeat yet
            return [(pad, prev.with_tensors(prev.tensors, pts=buf.pts))]
        if action == "tensorpick":
            idxs = [int(x) for x in option.split(",") if x.strip()]
            return [(pad, buf.subset(idxs))]
        raise PipelineError(
            f"tensor_if {self.name}: unknown action {action!r}; valid: "
            f"{ACTIONS}"
        )

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        cond = self._decide(buf)
        has_else = len(self.out_specs) == 2
        if cond:
            out = self._apply(self.props["then"],
                              self.props["then_option"], 0, buf)
        elif has_else:
            out = self._apply(self.props["else_"],
                              self.props["else_option"], 1, buf)
        else:
            out = []
        for _, b in out:
            self._last_fwd = b   # repeat_previous source material
        return out


# -- tensor_rate -------------------------------------------------------------

@register_element("tensor_rate")
class TensorRate(Element):
    """Conform stream to `framerate` by dropping/duplicating frames.

    PTS-based like the reference (gsttensor_rate.c): each output slot i
    has target time i/rate; incoming frames fill slots up to their PTS
    (dup when source is slower, drop when faster). `throttle=true` posts
    an upstream QoS event with the target inter-frame interval
    (gsttensor_rate.c:22-34) so sources can *skip generating* frames
    that would be dropped here (skip-before-compute); bounded queues
    still provide generic backpressure either way.
    """

    ELEMENT_NAME = "tensor_rate"
    PROPS = {
        "framerate": PropDef(str, None, "target rate 'N/D'"),
        "throttle": PropDef(prop_bool, True),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if not self.props["framerate"]:
            raise PipelineError(
                f"tensor_rate {self.name}: framerate=N/D is required"
            )
        self._rate = Fraction(self.props["framerate"].replace(":", "/"))
        if self._rate <= 0:
            raise PipelineError(
                f"tensor_rate {self.name}: framerate must be positive"
            )
        self._next_slot = 0
        self._prev: Optional[TensorBuffer] = None
        self.dropped = 0
        self.duplicated = 0
        self._qos_posted = False

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        return [spec.with_rate(self._rate)]

    def _slot_pts(self, slot: int) -> int:
        return int(slot * 1_000_000_000 / self._rate)

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        if buf.pts is None:
            return [(0, buf)]  # untimed stream: pass through
        out: List[Emission] = []
        # emit pending slots whose target time has passed, using the
        # previous frame (duplication when upstream is slow)
        while self._prev is not None and \
                self._slot_pts(self._next_slot) < buf.pts:
            out.append((0, self._prev.with_tensors(
                self._prev.tensors, pts=self._slot_pts(self._next_slot))))
            if len(out) > 1:
                self.duplicated += 1
            self._next_slot += 1
        if self._slot_pts(self._next_slot) >= buf.pts or self._prev is None:
            # frame lands in (or before) the next slot — it becomes the
            # candidate; a faster-than-rate source overwrites (drop)
            if self._prev is not None and buf.pts < self._slot_pts(self._next_slot):
                self.dropped += 1
                if self.props["throttle"] and not self._qos_posted:
                    # upstream QoS: ask sources to pace at the target rate
                    self._qos_posted = True
                    self.post_upstream_event({
                        "type": "qos",
                        "min_interval_ns": int(1_000_000_000 / self._rate),
                    })
            self._prev = buf
        return out

    def flush(self) -> List[Emission]:
        if self._prev is not None:
            return [(0, self._prev.with_tensors(
                self._prev.tensors, pts=self._slot_pts(self._next_slot)))]
        return []


# -- tensor_crop -------------------------------------------------------------

@register_element("tensor_crop")
class TensorCrop(Element):
    """Data-driven crop: sink 0 = raw tensors, sink 1 = crop info stream.

    Crop info per frame: tensor of shape (num_regions, 4) [x, y, w, h]
    (gsttensor_crop.c:18-33). Output is a FLEXIBLE stream — region sizes
    vary per frame. `lateness` (ns) bounds the PTS distance accepted
    between raw and info frames (:87).
    """

    ELEMENT_NAME = "tensor_crop"
    NUM_SINK_PADS = 2
    # two-pad fan-in: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {
        "lateness": PropDef(int, 33_000_000, "max |pts_raw - pts_info| ns"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._raw: List[TensorBuffer] = []
        self._info: List[TensorBuffer] = []

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        raw = self.expect_tensors(in_specs[0], 0)
        self.expect_tensors(in_specs[1], 1)
        if raw.num_tensors != 1:
            self.fail_negotiation(
                f"tensor_crop takes a single-tensor raw stream, got "
                f"{raw.num_tensors} tensors (demux first)"
            )
        t = raw.tensors[0]
        if len(t.shape) < 2:
            self.fail_negotiation(
                f"crop input must be at least rank-2 (spatial); got {t}"
            )
        return [TensorsSpec(tensors=raw.tensors, format=TensorFormat.FLEXIBLE,
                            rate=raw.rate)]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        (self._raw if pad == 0 else self._info).append(buf)
        out: List[Emission] = []
        lateness = self.props["lateness"]
        while self._raw and self._info:
            raw, info = self._raw[0], self._info[0]
            d = abs((raw.pts or 0) - (info.pts or 0))
            if d > lateness:
                # discard the older of the two and retry
                if (raw.pts or 0) < (info.pts or 0):
                    self._raw.pop(0)
                else:
                    self._info.pop(0)
                continue
            self._raw.pop(0)
            self._info.pop(0)
            out.append((0, self._crop(raw, info)))
        return out

    def _crop(self, raw: TensorBuffer, info: TensorBuffer) -> TensorBuffer:
        t = raw.tensors[0]
        regions = np.asarray(info.tensors[0]).reshape(-1, 4).astype(np.int64)
        crops = []
        # spatial dims: assume (..., H, W, C) if rank>=3 else (H, W)
        h_ax = t.ndim - 3 if t.ndim >= 3 else 0
        w_ax = h_ax + 1
        H, W = t.shape[h_ax], t.shape[w_ax]
        for x, y, w, h in regions:
            x0, y0 = max(0, int(x)), max(0, int(y))
            x1, y1 = min(W, x0 + int(w)), min(H, y0 + int(h))
            sl = [slice(None)] * t.ndim
            sl[h_ax] = slice(y0, y1)
            sl[w_ax] = slice(x0, x1)
            crops.append(t[tuple(sl)])
        return TensorBuffer(tensors=tuple(crops), pts=raw.pts,
                            format=TensorFormat.FLEXIBLE)
