"""Stream routing elements: mux, merge, demux, split, join, tee.

Reference parity (SURVEY.md §2.2, §3.5):
- tensor_mux   (gsttensor_mux.c)    — N tensor streams → 1 multi-tensor
- tensor_merge (gsttensor_merge.c)  — N single-tensor streams → 1 tensor,
  concatenated along a chosen dim
- tensor_demux (gsttensor_demux.c)  — 1 multi-tensor stream → N streams,
  with `tensorpick` subset/reorder
- tensor_split (gsttensor_split.c)  — 1 tensor → N along a dim (tensorseg)
- join         (gst/join/gstjoin.c) — N-to-1 active-pad pass-through
- tee          (GStreamer core)     — 1-to-N duplication (the reference
  leans on GStreamer's tee; our graph needs it as an element)

Multi-pad time synchronization implements the reference's four policies
(nnstreamer_plugin_api_impl.c:267 `gst_tensor_time_sync_buffer_from_
collectpad`, modes tensor_common.h:62-68, semantics documented in
Documentation/synchronization-policies-at-mux-merge.md):

- nosync  — FIFO pairing: emit whenever every pad has a queued buffer.
- slowest — base time = max of head PTS across pads; per pad, drop
  buffers older than base and take the nearest one.
- basepad — option `<pad>:<duration_ns>`: pad N's buffers set the base
  time; others contribute their newest buffer within the window.
- refresh — emit on every arrival, reusing the last-seen buffer of every
  other pad.

TPU-first notes: mux/merge do no copies on the host path — mux passes
array references; merge concatenation happens with jnp/np on whatever
device the arrays already live on.
"""

from __future__ import annotations

from collections import deque
from dataclasses import replace
from fractions import Fraction
from typing import Deque, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.core.errors import PipelineError
from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import (
    DYNAMIC, Element, Emission, PropDef, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import (
    MAX_TENSORS_PER_FRAME, TensorInfo, TensorsSpec)

log = get_logger("elements.routing")

SYNC_MODES = ("nosync", "slowest", "basepad", "refresh")


def _xp(arrays):
    """numpy or jax.numpy depending on where the arrays live."""
    if any(type(a).__module__.startswith("jax") for a in arrays):
        import jax.numpy as jnp

        return jnp
    return np


class _SyncCollect:
    """Shared multi-pad collect/synchronize machinery (GstCollectPads +
    time-sync helpers analog)."""

    def __init__(self, element: Element, n_pads: int, mode: str, option: str):
        if mode not in SYNC_MODES:
            raise PipelineError(
                f"{element.name}: unknown sync_mode {mode!r}; valid: "
                f"{', '.join(SYNC_MODES)}"
            )
        self.e = element
        self.n = n_pads
        self.mode = mode
        self.queues: List[Deque[TensorBuffer]] = [deque() for _ in range(n_pads)]
        self.last: List[Optional[TensorBuffer]] = [None] * n_pads
        self.base_pad = 0
        self.window_ns = 0
        if mode == "basepad":
            parts = (option or "0").split(":")
            self.base_pad = int(parts[0])
            self.window_ns = int(parts[1]) if len(parts) > 1 else 0
            if self.base_pad >= n_pads:
                raise PipelineError(
                    f"{element.name}: basepad {self.base_pad} out of range "
                    f"for {n_pads} sink pads"
                )

    def offer(self, pad: int, buf: TensorBuffer) -> List[List[TensorBuffer]]:
        """Queue one arrival; return the list of synchronized groups
        (one buffer per pad) ready to emit."""
        self.queues[pad].append(buf)
        self.last[pad] = buf
        out = []
        while True:
            group = self._try_collect(pad)
            if group is None:
                break
            out.append(group)
            if self.mode == "refresh":
                break  # refresh emits at most once per arrival
        return out

    def _try_collect(self, arrived_pad: int) -> Optional[List[TensorBuffer]]:
        if self.mode == "refresh":
            if any(l is None for l in self.last):
                return None
            group = [q.popleft() if q else self.last[i]
                     for i, q in enumerate(self.queues)]
            return group
        if any(not q for q in self.queues):
            return None
        if self.mode == "nosync":
            return [q.popleft() for q in self.queues]
        if self.mode == "slowest":
            base = max(q[0].pts or 0 for q in self.queues)
            group = []
            for q in self.queues:
                # drop frames strictly older than base when a newer one is
                # also queued (catch-up), then take the head
                while len(q) > 1 and (q[1].pts or 0) <= base:
                    q.popleft()
                group.append(q.popleft())
            return group
        # basepad
        bq = self.queues[self.base_pad]
        while bq:
            base = bq[0].pts or 0
            group: List[Optional[TensorBuffer]] = [None] * self.n
            # queues whose matched head should be consumed — deferred to
            # group success, so an aborted (waiting) group loses nothing
            pops: List[Deque[TensorBuffer]] = []
            expired = waiting = False
            for i, q in enumerate(self.queues):
                if i == self.base_pad:
                    continue
                while (len(q) > 1
                       and self._dist(q[1], base) <= self._dist(q[0], base)):
                    q.popleft()
                if self.window_ns and self._dist(q[0], base) > self.window_ns:
                    # q[0] is the best queued candidate (catch-up loop
                    # above). PTS is monotonic per pad, so once the NEWEST
                    # queued frame is past base+window no future frame can
                    # match either — expire the base head (drop + log)
                    # instead of stalling the group forever (ref drops on
                    # window miss, nnstreamer_plugin_api_impl.c:267)
                    if (q[-1].pts or 0) > base + self.window_ns:
                        log.warning(
                            "%s: basepad head pts=%s expired (pad %d has no "
                            "frame within ±%dns); dropping",
                            self.e.name, base, i, self.window_ns)
                        bq.popleft()
                        expired = True
                    else:
                        # partner lags behind: a closer frame may still come
                        waiting = True
                    break
                group[i] = q[0]
                if len(q) > 1:
                    pops.append(q)   # consume on success; reuse if last
            if waiting:
                return None
            if expired:
                continue   # retry with the next base head
            for q in pops:
                q.popleft()
            group[self.base_pad] = bq.popleft()
            return [g for g in group]  # type: ignore
        return None

    @staticmethod
    def _dist(buf: TensorBuffer, base: int) -> int:
        return abs((buf.pts or 0) - base)

    def drain(self) -> List[List[TensorBuffer]]:
        """At EOS: flush complete FIFO groups (nosync only; timed modes
        drop stragglers like the reference's EOS pad handling)."""
        out = []
        if self.mode == "nosync":
            while all(q for q in self.queues):
                out.append([q.popleft() for q in self.queues])
        return out


def _common_rate(specs: Sequence[TensorsSpec]) -> Fraction:
    rates = [s.rate for s in specs if s.rate]
    return max(rates) if rates else Fraction(0, 1)


@register_element("tensor_mux")
class TensorMux(Element):
    """N tensor streams → one multi-tensor stream (num_tensors = Σ)."""

    ELEMENT_NAME = "tensor_mux"
    NUM_SINK_PADS = DYNAMIC
    NUM_SRC_PADS = 1
    # dynamic fan-in: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {
        "sync_mode": PropDef(str, "slowest", "|".join(SYNC_MODES)),
        "sync_option": PropDef(str, "", "basepad option '<pad>:<window_ns>'"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._collect: Optional[_SyncCollect] = None

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        specs = [self.expect_tensors(s, i) for i, s in enumerate(in_specs)]
        total = sum(s.num_tensors for s in specs)
        if total > MAX_TENSORS_PER_FRAME:
            self.fail_negotiation(
                f"muxing {total} tensors exceeds the {MAX_TENSORS_PER_FRAME}"
                f"-tensor frame limit"
            )
        self._collect = _SyncCollect(self, len(specs),
                                     self.props["sync_mode"],
                                     self.props["sync_option"])
        infos = tuple(t for s in specs for t in s.tensors)
        return [TensorsSpec(tensors=infos, rate=_common_rate(specs))]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        out = []
        for group in self._collect.offer(pad, buf):
            tensors = tuple(t for b in group for t in b.tensors)
            pts = group[self._collect.base_pad].pts \
                if self._collect.mode == "basepad" \
                else max((b.pts or 0) for b in group)
            out.append((0, TensorBuffer(tensors=tensors, pts=pts)))
        return out

    def flush(self) -> List[Emission]:
        return [
            (0, TensorBuffer(
                tensors=tuple(t for b in g for t in b.tensors),
                pts=max((b.pts or 0) for b in g)))
            for g in self._collect.drain()
        ]


@register_element("tensor_merge")
class TensorMerge(Element):
    """N single-tensor streams → 1 tensor, concatenated along a dim.

    mode=linear option=<dim> — dim indexes the ROW-MAJOR shape. The
    reference's channel/width/height/batch keywords map onto row-major
    axes of NHWC at negotiation (gsttensor_merge.c linear modes).
    """

    ELEMENT_NAME = "tensor_merge"
    NUM_SINK_PADS = DYNAMIC
    NUM_SRC_PADS = 1
    # dynamic fan-in: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    _KEYWORDS = {"batch": 0, "height": 1, "width": 2, "channel": 3}
    PROPS = {
        "mode": PropDef(str, "linear"),
        "option": PropDef(str, "channel", "concat axis: int or NHWC keyword"),
        "sync_mode": PropDef(str, "slowest", "|".join(SYNC_MODES)),
        "sync_option": PropDef(str, ""),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._collect: Optional[_SyncCollect] = None
        self._axis = 0

    def _resolve_axis(self, ndim: int) -> int:
        opt = self.props["option"].strip()
        if opt in self._KEYWORDS:
            if ndim != 4:
                self.fail_negotiation(
                    f"axis keyword {opt!r} assumes NHWC rank-4 tensors but "
                    f"input rank is {ndim}; use a numeric axis"
                )
            return self._KEYWORDS[opt]
        try:
            ax = int(opt)
        except ValueError:
            self.fail_negotiation(
                f"bad merge option {opt!r}: expected an axis number or one "
                f"of {sorted(self._KEYWORDS)}"
            )
        return ax % ndim

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        specs = [self.expect_tensors(s, i) for i, s in enumerate(in_specs)]
        if self.props["mode"] != "linear":
            self.fail_negotiation(
                f"unsupported merge mode {self.props['mode']!r} (only "
                f"'linear' exists — the reference's other modes were never "
                f"implemented either, gsttensor_merge.c)"
            )
        for i, s in enumerate(specs):
            if s.num_tensors != 1:
                self.fail_negotiation(
                    f"sink pad {i} carries {s.num_tensors} tensors; "
                    f"tensor_merge needs single-tensor streams (use "
                    f"tensor_mux for multi-tensor framing)"
                )
        first = specs[0].tensors[0]
        ax = self._resolve_axis(len(first.shape))
        self._axis = ax
        out_dim = 0
        for i, s in enumerate(specs):
            t = s.tensors[0]
            if t.dtype != first.dtype:
                self.fail_negotiation(
                    f"dtype mismatch on pad {i}: {t.dtype.type_name} vs "
                    f"{first.dtype.type_name}"
                )
            if len(t.shape) != len(first.shape) or any(
                a != b for d, (a, b) in enumerate(zip(t.shape, first.shape))
                if d != ax
            ):
                self.fail_negotiation(
                    f"shape mismatch on pad {i}: {t.shape} vs {first.shape} "
                    f"(must agree on all axes except concat axis {ax})"
                )
            out_dim += t.shape[ax]
        shape = tuple(out_dim if d == ax else v
                      for d, v in enumerate(first.shape))
        self._collect = _SyncCollect(self, len(specs),
                                     self.props["sync_mode"],
                                     self.props["sync_option"])
        return [TensorsSpec.of(TensorInfo(shape, first.dtype),
                               rate=_common_rate(specs))]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        out = []
        for group in self._collect.offer(pad, buf):
            arrays = [b.tensors[0] for b in group]
            xp = _xp(arrays)
            merged = xp.concatenate(arrays, axis=self._axis)
            out.append((0, TensorBuffer(
                tensors=(merged,), pts=max((b.pts or 0) for b in group))))
        return out

    def flush(self) -> List[Emission]:
        out = []
        for group in self._collect.drain():
            arrays = [b.tensors[0] for b in group]
            xp = _xp(arrays)
            out.append((0, TensorBuffer(
                tensors=(xp.concatenate(arrays, axis=self._axis),),
                pts=max((b.pts or 0) for b in group))))
        return out


@register_element("tensor_demux")
class TensorDemux(Element):
    """1 multi-tensor stream → N streams. `tensorpick` picks/reorders;
    entries may group several tensors per pad with '+': "0,1+2"."""

    ELEMENT_NAME = "tensor_demux"
    NUM_SINK_PADS = 1
    NUM_SRC_PADS = DYNAMIC
    # dynamic fan-out: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {
        "tensorpick": PropDef(str, "", "e.g. '0,2' or '0,1+2'; empty = all"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._picks: List[List[int]] = []

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        n_out = len(self._pipeline.links_from(self)) if self._pipeline else 0
        pick = self.props["tensorpick"]
        if pick:
            self._picks = [[int(x) for x in part.split("+")]
                           for part in pick.split(",")]
        else:
            self._picks = [[i] for i in range(spec.num_tensors)]
        if n_out and len(self._picks) != n_out:
            self.fail_negotiation(
                f"{len(self._picks)} tensorpick group(s) but {n_out} src "
                f"pad(s) linked"
            )
        for grp in self._picks:
            for i in grp:
                if i >= spec.num_tensors:
                    self.fail_negotiation(
                        f"tensorpick index {i} out of range; input has "
                        f"{spec.num_tensors} tensors"
                    )
        return [
            replace(spec, tensors=tuple(spec.tensors[i] for i in grp))
            for grp in self._picks
        ]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        return [
            (p, buf.subset(grp)) for p, grp in enumerate(self._picks)
        ]


@register_element("tensor_split")
class TensorSplit(Element):
    """1 tensor → N tensors along an axis by `tensorseg` sizes.

    tensorseg="2:4:2" splits axis (default: last) into sizes 2,4,2 —
    the per-dimension unshard primitive (gsttensor_split.c).
    """

    ELEMENT_NAME = "tensor_split"
    NUM_SINK_PADS = 1
    NUM_SRC_PADS = DYNAMIC
    # dynamic fan-out: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {
        "tensorseg": PropDef(str, None, "colon-separated segment sizes"),
        "axis": PropDef(int, -1, "row-major split axis (default last)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sizes: List[int] = []
        self._axis = -1

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0])
        if spec.num_tensors != 1:
            self.fail_negotiation(
                f"tensor_split needs a single-tensor stream, got "
                f"{spec.num_tensors} tensors (tensor_demux separates them)"
            )
        if not self.props["tensorseg"]:
            self.fail_negotiation("tensorseg=<s1:s2:…> is required")
        t = spec.tensors[0]
        self._axis = self.props["axis"] % len(t.shape)
        try:
            self._sizes = [int(x) for x in self.props["tensorseg"].split(":")]
        except ValueError:
            self.fail_negotiation(
                f"bad tensorseg {self.props['tensorseg']!r}: expected "
                f"colon-separated ints"
            )
        if sum(self._sizes) != t.shape[self._axis]:
            self.fail_negotiation(
                f"tensorseg {self._sizes} sums to {sum(self._sizes)} but "
                f"axis {self._axis} has size {t.shape[self._axis]}"
            )
        outs = []
        for s in self._sizes:
            shape = tuple(s if d == self._axis else v
                          for d, v in enumerate(t.shape))
            outs.append(TensorsSpec.of(TensorInfo(shape, t.dtype),
                                       rate=spec.rate))
        n_out = len(self._pipeline.links_from(self)) if self._pipeline else 0
        if n_out and n_out != len(outs):
            self.fail_negotiation(
                f"{len(outs)} segments but {n_out} src pads linked"
            )
        return outs

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        t = buf.tensors[0]
        out = []
        off = 0
        for p, s in enumerate(self._sizes):
            sl = [slice(None)] * t.ndim
            sl[self._axis] = slice(off, off + s)
            out.append((p, buf.with_tensors((t[tuple(sl)],))))
            off += s
        return out


@register_element("join")
class Join(Element):
    """N-to-1 active-pad pass-through without synchronization — whatever
    arrives on any pad goes out (gst/join/gstjoin.c). Used to rejoin
    branches after demux/tensor_if routing."""

    ELEMENT_NAME = "join"
    NUM_SINK_PADS = DYNAMIC
    NUM_SRC_PADS = 1
    # dynamic fan-in: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {}

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        specs = [self.expect_tensors(s, i) for i, s in enumerate(in_specs)]
        first = specs[0]
        for i, s in enumerate(specs[1:], 1):
            if not first.is_compatible(s):
                self.fail_negotiation(
                    f"pad {i} spec {s} incompatible with pad 0 spec {first}; "
                    f"join requires identical stream types on every pad"
                )
        return [first]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        return [(0, buf)]


@register_element("tee")
class Tee(Element):
    """1-to-N duplication. Zero-copy: every branch receives the same
    array references (arrays are immutable in the jax world)."""

    ELEMENT_NAME = "tee"
    NUM_SINK_PADS = 1
    NUM_SRC_PADS = DYNAMIC
    # dynamic fan-out: chain fusion is single-in/single-out only
    CHAIN_FUSABLE = False
    PROPS = {}

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        n = len(self._pipeline.links_from(self)) if self._pipeline else 1
        return [in_specs[0]] * max(1, n)

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        n = len(self.out_specs)
        return [(p, buf) for p in range(n)]


@register_element("queue")
class Queue(Element):
    """DSL-parity no-op: every link in this runtime already is a bounded
    queue (runtime/scheduler.py), so `queue` just passes through."""

    ELEMENT_NAME = "queue"
    PROPS = {
        "max_size_buffers": PropDef(int, 0, "accepted, ignored"),
        "leaky": PropDef(str, "", "accepted, ignored"),
    }

    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        return [in_specs[0]]

    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        return [(0, buf)]
