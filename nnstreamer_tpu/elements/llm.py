"""tensor_llm: continuous-batching LLM generation as a pipeline element.

One buffer in = one generation request (a 1-D int32 prompt); buffers
out = incremental token chunks per request, so downstream sees tokens
as they are produced, not when the request finishes. The element wraps
`llm.engine.LLMEngine` and rides the scheduler's timer contract
(next_deadline/on_timer — the same machinery tensor_batch uses for its
deadline flush): process() only *queues* a request and arms a short
admission window; the engine steps inside on_timer(). That shape is
load-bearing: each timer fire runs exactly one serving quantum
(admit + prefill + one decode step for the whole in-flight batch) and
then yields the deadline back, so newly arriving prompts are read off
the input channel *between* decode steps and merge into the next one —
continuous batching, not run-to-completion.

Per-request knobs ride `buf.meta["llm"]` (request_id, max_new_tokens,
temperature, top_k, seed, eos_id), defaulting to element properties.
Output buffers carry `meta["llm"]` with the request id, done flag and,
on the final chunk, the request's latency summary; first-token and
inter-token latency are also recorded in the tracer per request.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from nnstreamer_tpu.core.log import get_logger
from nnstreamer_tpu.core.registry import register_element
from nnstreamer_tpu.graph.pipeline import (
    Element, Emission, PropDef, StreamSpec)
from nnstreamer_tpu.tensor.buffer import TensorBuffer
from nnstreamer_tpu.tensor.info import TensorFormat, TensorsSpec

log = get_logger("elements.llm")


@register_element("tensor_llm")
class TensorLLM(Element):
    """Continuous-batching generation over a paged KV cache.

    Properties:
    - model: ``store://name[@version]`` ref (hot-swappable via the model
      store unless pinned) or a zoo name; default store://transformer.
    - scheduling: "continuous" (default) or "static" — the A/B baseline
      where a batch admits only from empty and runs to completion.
    - block_size / num_blocks: paged KV pool geometry (block 0 is the
      padding scratch block; capacity = (num_blocks-1) * block_size
      token slots).
    - max_batch: decode-batch slot ceiling.
    - max_len: per-sequence ceiling (prompt + generated tokens).
    - admit_window_ms: how long a serving step waits for co-arriving
      prompts before the next decode step runs.
    - stream_chunk: emit every N tokens (1 = stream each token).
    - eos_id: stop token (-1 disables); max_new_tokens: token budget.
    - paged_kernel: "pallas" (paged flash attention, backends/
      pallas_paged.py) or "xla" (the bit-reference, llm/paged_model.py);
      "" defers to $NNS_PAGED_KERNEL then defaults to xla. An
      unavailable Pallas path serves on XLA and counts a
      kernel_fallback — never an error.
    - prefill_chunk: prompts longer than this prefill in N-token chunks
      interleaved with decode steps (0 = whole-prompt prefill), so a
      long prompt does not head-of-line block the batch's inter-token
      latency.
    - shards: tensor-parallel shard count (2/4/8) — the executor opens
      one mesh-sharded backend over N leased chips with head-sharded
      projections and KV pools (docs/sharded_serving.md); bit-identical
      to shards=1 by the canonical-blocking construction. Exclusive
      with prefill_chunk and pallas.
    - ring_prefill_min: with shards>0, prompts at least this long
      prefill through sequence-parallel ring attention over the same
      chips (allclose-, not bit-, equivalent; decode stays bit-exact).
    """

    ELEMENT_NAME = "tensor_llm"
    NUM_SINK_PADS = 1
    NUM_SRC_PADS = 1
    # timer element (decode-step wakeups): needs its own worker loop
    CHAIN_FUSABLE = False
    WANTS_HOST = True
    PROPS = {
        "model": PropDef(str, "store://transformer",
                         "store:// ref or zoo model name"),
        "n_heads": PropDef(int, 4, "attention heads (must match model)"),
        "dtype": PropDef(str, "float32", "activation dtype"),
        "block_size": PropDef(int, 16, "KV block size in token slots"),
        "num_blocks": PropDef(int, 64,
                              "KV pool size in blocks (incl. scratch)"),
        "max_batch": PropDef(int, 8, "decode-batch slot ceiling"),
        "max_len": PropDef(int, 128,
                           "per-sequence prompt+output ceiling"),
        "max_new_tokens": PropDef(
            int, 32, "default token budget per request"),
        "temperature": PropDef(
            float, 0.0, "default sampling temperature (0 = greedy)"),
        "eos_id": PropDef(int, -1, "default stop token (-1 = disabled)"),
        "scheduling": PropDef(
            str, "continuous", "continuous | static (A/B baseline)"),
        "admit_window_ms": PropDef(
            float, 0.5, "admission window between decode steps"),
        "stream_chunk": PropDef(
            int, 1, "tokens per output buffer (1 = per-token)"),
        "paged_kernel": PropDef(
            str, "", "attention kernel: pallas | xla | '' = "
                     "$NNS_PAGED_KERNEL or xla"),
        "prefill_chunk": PropDef(
            int, 0, "chunked-prefill chunk size in tokens "
                    "(0 = whole-prompt prefill)"),
        "shards": PropDef(
            int, 0, "tensor-parallel shard count (0 = single chip; "
                    "2/4/8 serve one mesh-sharded backend whose chips "
                    "are leased as one shard group)"),
        "ring_prefill_min": PropDef(
            int, 0, "with shards>0: prompts at least this long prefill "
                    "through sequence-parallel ring attention "
                    "(0 = always the blocked tensor-parallel path)"),
        "warm_start": PropDef(
            int, 1, "replay manifest prefill buckets at start()"),
        "prewarm": PropDef(
            int, 0, "eagerly compile all decode buckets and prefill "
                    "buckets up to this prompt length at start() "
                    "(0 = compile lazily on first use)"),
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.engine = None
        self._leases = None
        self._deadline: Optional[float] = None
        # per-request emission state, engine-thread only
        self._chunks: Dict[str, List[int]] = {}
        self._req_seq = 0
        self.requests_in = 0
        self.chunks_out = 0
        self.warm_compiles = 0

    # -- negotiation -------------------------------------------------------
    def negotiate(self, in_specs: Sequence[StreamSpec]) -> List[StreamSpec]:
        spec = self.expect_tensors(in_specs[0], 0)
        sched = self.props["scheduling"]
        if sched not in ("continuous", "static"):
            self.fail_negotiation(
                f"scheduling must be 'continuous' or 'static', "
                f"got {sched!r}")
        kern = self.props["paged_kernel"]
        if kern not in ("", "pallas", "xla"):
            self.fail_negotiation(
                f"paged_kernel must be 'pallas', 'xla' or '' "
                f"(env/default), got {kern!r}")
        if int(self.props["prefill_chunk"]) < 0:
            self.fail_negotiation(
                f"prefill_chunk must be >= 0, got "
                f"{self.props['prefill_chunk']}")
        shards = int(self.props["shards"])
        if shards > 0:
            from nnstreamer_tpu.serving.sharding import SUPPORTED_SHARDS

            if shards not in SUPPORTED_SHARDS:
                self.fail_negotiation(
                    f"shards must be one of {SUPPORTED_SHARDS} (canonical "
                    f"8-block serving layout), got {shards}")
            if int(self.props["prefill_chunk"]) > 0:
                self.fail_negotiation(
                    "prefill_chunk and shards are exclusive — sharded "
                    "long prompts use ring_prefill_min (sequence-"
                    "parallel ring prefill), not chunking")
        elif int(self.props["ring_prefill_min"]) > 0:
            self.fail_negotiation(
                "ring_prefill_min needs shards>0 (ring prefill runs "
                "over the shard group's chips)")
        if spec.format == TensorFormat.STATIC:
            for t in spec.tensors:
                if np.dtype(t.dtype) != np.int32:
                    self.fail_negotiation(
                        f"tensor_llm consumes int32 token-id prompts, "
                        f"got {t.dtype}")
        # prompts vary per request and chunks vary per step: both sides
        # of this element are inherently FLEXIBLE streams
        return [TensorsSpec(tensors=(), format=TensorFormat.FLEXIBLE,
                            rate=spec.rate)]

    def start(self) -> None:
        from nnstreamer_tpu.llm.engine import LLMEngine

        import jax.numpy as jnp

        model = self.props["model"]
        if isinstance(model, str) and "://" not in model:
            model = f"store://{model}"
        shards = int(self.props["shards"])
        chips = None
        if shards > 0:
            # lease the group's chips under ONE owner so a member-chip
            # fence is one ledger row flip for the whole group
            from nnstreamer_tpu.serving.placement import ChipLeaseTable
            from nnstreamer_tpu.serving.sharding import visible_devices

            self._leases = ChipLeaseTable(range(len(visible_devices())))
            chips = self._leases.lease(self.name, shards)
        self.engine = LLMEngine(
            model,
            n_heads=int(self.props["n_heads"]),
            dtype=jnp.dtype(self.props["dtype"]),
            block_size=int(self.props["block_size"]),
            num_blocks=int(self.props["num_blocks"]),
            max_batch=int(self.props["max_batch"]),
            max_len=int(self.props["max_len"]),
            static_batching=self.props["scheduling"] == "static",
            prefill_chunk=int(self.props["prefill_chunk"]),
            paged_kernel=str(self.props["paged_kernel"]) or None,
            shards=shards, shard_chips=chips,
            ring_prefill_min=int(self.props["ring_prefill_min"]),
            tracer=self._tracer,
            name=self.name)
        if int(self.props["warm_start"]):
            self.warm_compiles = self.engine.executor.warm_start()
        if int(self.props["prewarm"]) > 0:
            self.warm_compiles += self.engine.prewarm(
                int(self.props["prewarm"]))

    def stop(self) -> None:
        if self.engine is not None:
            self.engine.executor.close()
        if getattr(self, "_leases", None) is not None:
            self._leases.release(self.name)

    # -- dataflow ----------------------------------------------------------
    def process(self, pad: int, buf: TensorBuffer) -> List[Emission]:
        meta = buf.meta.get("llm") if isinstance(buf.meta, dict) else None
        meta = meta if isinstance(meta, dict) else {}
        prompt = np.asarray(buf.tensors[0]).reshape(-1)
        req_id = meta.get("request_id")
        if req_id is None:
            self._req_seq += 1
            req_id = f"{self.name}-{self._req_seq}"
        eos = meta.get("eos_id", int(self.props["eos_id"]))
        self.engine.submit(
            prompt,
            req_id=str(req_id),
            max_new_tokens=int(meta.get(
                "max_new_tokens", self.props["max_new_tokens"])),
            temperature=float(meta.get(
                "temperature", self.props["temperature"])),
            top_k=int(meta.get("top_k", 0)),
            seed=int(meta.get("seed", 0)),
            eos_id=None if eos is None or int(eos) < 0 else int(eos),
            pts=buf.pts)
        self.requests_in += 1
        if self._deadline is None:
            # arm the admission window; co-arriving prompts land in the
            # same first step (the scheduler reads the channel until the
            # deadline, then fires on_timer)
            self._deadline = time.perf_counter() + self._window_s()
        return []

    def _window_s(self) -> float:
        # a non-positive window would starve the input channel (an
        # always-past deadline makes the scheduler fire timers forever
        # without reading input) — clamp to one scheduler-visible tick
        return max(0.05, float(self.props["admit_window_ms"])) * 1e-3

    def next_deadline(self) -> Optional[float]:
        return self._deadline

    def on_timer(self) -> List[Emission]:
        if self.engine is None or not self.engine.has_work:
            self._deadline = None
            return []
        events = self.engine.step()
        self._deadline = (time.perf_counter() + self._window_s()
                          if self.engine.has_work else None)
        return self._emit(events)

    def flush(self) -> List[Emission]:
        """EOS: no more requests will arrive — run the engine dry."""
        self._deadline = None
        if self.engine is None or not self.engine.has_work:
            return []
        return self._emit(self.engine.drain())

    # -- emission ----------------------------------------------------------
    def _emit(self, events) -> List[Emission]:
        chunk = max(1, int(self.props["stream_chunk"]))
        out: List[Emission] = []
        for ev in events:
            req = ev.request
            pend = self._chunks.setdefault(req.req_id, [])
            pend.extend(ev.tokens)
            if len(pend) < chunk and not ev.done:
                continue
            del self._chunks[req.req_id]
            meta = {"llm": {
                "request_id": req.req_id,
                "done": ev.done,
                "n_tokens": len(req.tokens),
            }}
            if ev.done:
                meta["llm"].update(req.summary())
            out.append((0, TensorBuffer(
                tensors=(np.asarray(pend, np.int32),),
                pts=req.pts, meta=meta)))
            self.chunks_out += 1
        return out

    # -- stats -------------------------------------------------------------
    def extra_stats(self) -> dict:
        stats = {"requests_in": self.requests_in,
                 "chunks_out": self.chunks_out,
                 "warm_compiles": self.warm_compiles}
        if self.engine is not None:
            stats.update(self.engine.stats())
        if self._leases is not None:
            stats["leases"] = self._leases.snapshot()["counts"]
        return stats
